"""Multi-host mesh setup — the DCN tier of the communication backend.

The reference scales out with Spark executors + Aeron UDP between JVMs
(SURVEY.md §2c "Communication backend").  The TPU-native equivalent is
``jax.distributed``: one process per host, XLA runs collectives over ICI
within a slice and DCN across slices — no user-visible transport or
serialization layer.

On a single host (this environment, and any test rig) everything is a
no-op passthrough: the same mesh-building code serves 1 host or N.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from gan_deeplearning4j_tpu.parallel.mesh import make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job.  With no arguments, uses the standard env
    (JAX_COORDINATOR_ADDRESS etc.) and is a no-op on a single host."""
    if coordinator_address is None and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return  # single host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def aggregate_goodput(report: Dict[str, float]) -> Dict[str, float]:
    """Cross-host goodput aggregation: MEAN of every numeric phase over
    all processes (each host times its own training thread; the fleet
    breakdown is their average — a straggler shows up as everyone
    else's readback/other inflation, which is exactly the signal).

    Single process (this environment, and any test rig): passthrough,
    no device contact at all — the same no-op discipline as
    ``initialize``.  Multi-process: one ``process_allgather`` (the
    standard allreduce helper) carries the few floats over DCN."""
    if jax.process_count() == 1:
        return report
    from jax.experimental import multihost_utils

    from gan_deeplearning4j_tpu.telemetry import events

    keys = sorted(k for k, v in report.items()
                  if isinstance(v, (int, float)))
    vals = np.asarray([float(report[k]) for k in keys], np.float32)
    with events.span("collective.aggregate_goodput",
                     processes=jax.process_count()):
        gathered = multihost_utils.process_allgather(vals)  # [n_proc, len]
    mean = np.asarray(gathered).reshape(-1, len(keys)).mean(axis=0)
    out = dict(report)
    out.update({k: round(float(m), 6) for k, m in zip(keys, mean)})
    out["aggregated_processes"] = jax.process_count()
    return out


def agree_preemption(triggered: bool, step: int) -> tuple:
    """Fleet preemption consensus: allgather every host's (triggered,
    step) and return ``(any_triggered, min_step)``.

    This is a COLLECTIVE, so on a multi-process fleet it must be entered
    by EVERY host at the same boundary — the caller polls it
    unconditionally once preemption is armed, never only on the host
    that happened to receive the signal (a conditionally-entered
    collective deadlocks a partially-signaled fleet against the training
    step's own collectives).  ``any_triggered`` then preempts the WHOLE
    fleet together: one evicted host takes the others down cleanly, each
    with an emergency checkpoint at the agreed (min; equal under SPMD
    lockstep) step.  Single process: passthrough, no device contact —
    the same no-op discipline as ``initialize``/``aggregate_goodput``.
    Cost when it does gather: one small DCN allgather per boundary, paid
    only while a preemption guard is armed."""
    if jax.process_count() == 1:
        return bool(triggered), int(step)
    from jax.experimental import multihost_utils

    from gan_deeplearning4j_tpu.telemetry import events

    with events.span("collective.agree_preemption", step=int(step),
                     triggered=bool(triggered)):
        gathered = multihost_utils.process_allgather(
            np.asarray([int(bool(triggered)), int(step)], np.int64))
    arr = np.asarray(gathered).reshape(-1, 2)
    return bool(arr[:, 0].any()), int(arr[:, 1].min())


def agree_world() -> tuple:
    """Mesh-formation consensus (elastic recovery, parallel/elastic.py):
    every member of the CURRENT ``jax.distributed`` job reports
    ``(process_id, local_device_count)`` and gets back
    ``(process_count, total_devices)`` — the world the re-formed mesh
    must be built over.  The allgather IS the barrier: no host returns
    until every member has checked in, so the fleet re-forms one mesh
    instead of N partial ones.

    Scope, precisely: the barrier synchronizes the surviving
    INCARNATIONS of one job — hosts that crashed and restarted, hosts
    whose device count changed under them.  A host that is permanently
    GONE cannot be voted out from in here (``process_allgather`` is a
    collective over the job's fixed membership; a dead member means
    the scheduler must restart the job, at which point the NEW job's
    membership — and this barrier's result — is the smaller world).
    That re-exec path is exactly the ``XLA_FLAGS`` world-shrink the
    chaos harness models, and the checkpoint layer is what carries
    state across it (reshard-on-restore, checkpoint/checkpointer.py).

    Entered on RESTART paths only (``_maybe_resume``, inside a
    watchdog region) — never inside the training loop, so it costs one
    DCN allgather per incarnation, not per step.  Single process:
    passthrough, no device contact — the same no-op discipline as the
    other ``agree_*`` collectives above."""
    if jax.process_count() == 1:
        return 1, len(jax.devices())
    from jax.experimental import multihost_utils

    from gan_deeplearning4j_tpu.telemetry import events

    with events.span("collective.agree_world",
                     process=jax.process_index()):
        gathered = multihost_utils.process_allgather(
            np.asarray([jax.process_index(),
                        jax.local_device_count()], np.int64))
    arr = np.asarray(gathered).reshape(-1, 2)
    return int(arr.shape[0]), int(arr[:, 1].sum())


# agree_rollback sentinel for "this host has no local bad step": any
# real step is far below it, so the fleet min ignores non-alarmed hosts
_NO_BAD_STEP = 1 << 62


def agree_rollback(triggered: bool, step: int,
                   bad_step: Optional[int] = None) -> tuple:
    """Fleet rollback consensus — ``agree_preemption``'s mirror for the
    training-health layer (train/rollback.py): allgather every host's
    (triggered, boundary step, first-known-bad step) and return
    ``(any_triggered, min_step, min_bad_step-or-None)``.

    Same collective discipline: while a rollback manager is armed,
    EVERY host enters this at EVERY step/chunk boundary, never only the
    host whose alarm tripped (a conditionally-entered collective
    deadlocks the fleet against the training step's own collectives).
    ``any_triggered`` rolls the WHOLE fleet back together — a lone host
    restoring an old checkpoint while its peers train on would desync
    the SPMD state irrecoverably.  The BAD step must be agreed too:
    every host restores strictly before the fleet-MIN bad step (hosts
    whose own alarm never tripped contribute no bound) — hosts
    restoring to different points would desync the same way.
    ``min_step`` (equal under lockstep) is recorded so a straggler
    mismatch is observable.  Single process: passthrough, no device
    contact."""
    if jax.process_count() == 1:
        return bool(triggered), int(step), bad_step
    from jax.experimental import multihost_utils

    from gan_deeplearning4j_tpu.telemetry import events

    local_bad = _NO_BAD_STEP if bad_step is None else int(bad_step)
    with events.span("collective.agree_rollback", step=int(step),
                     triggered=bool(triggered)):
        gathered = multihost_utils.process_allgather(
            np.asarray([int(bool(triggered)), int(step), local_bad],
                       np.int64))
    arr = np.asarray(gathered).reshape(-1, 3)
    fleet_bad = int(arr[:, 2].min())
    return (bool(arr[:, 0].any()), int(arr[:, 1].min()),
            None if fleet_bad >= _NO_BAD_STEP else fleet_bad)


def hybrid_mesh(ici_shape: Dict[str, int], dcn_axis: str,
                num_slices: Optional[int] = None) -> Mesh:
    """Mesh for multi-slice TPU jobs: ``dcn_axis`` spans slices (hosts),
    every axis in ``ici_shape`` stays within a slice.  The standard
    layout rule — bandwidth-hungry collectives (TP/SP/grad-sync) ride
    ICI; only the outer axis's traffic crosses DCN.

    Uses the devices' slice topology when exposed (real multi-slice
    PJRT), else falls back to host-major order (virtual CPU meshes,
    single slice) — so one code path serves tests and production."""
    if dcn_axis in ici_shape:
        raise ValueError(
            f"dcn_axis {dcn_axis!r} collides with an ici_shape axis — "
            "the DCN tier must be its own axis")
    devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    real_topology = len(slice_ids) > 1
    if num_slices is None:
        if real_topology:
            num_slices = len(slice_ids)
        else:
            # no slice topology exposed (single slice / virtual mesh):
            # carve the DCN axis out of host-major order
            per = int(np.prod(list(ici_shape.values())))
            num_slices = len(devices) // per
    if num_slices < 1:
        raise ValueError("ici_shape larger than the device count")
    shape = {dcn_axis: num_slices}
    shape.update(ici_shape)
    if not real_topology:
        # virtual/CPU: no slice boundaries exist, host-major order IS the
        # topology — a create_hybrid_device_mesh failure here would only
        # be masked, never corrected, so don't attempt it
        return global_mesh(shape, dcn_axis=dcn_axis)
    from jax.experimental import mesh_utils

    # real multi-slice hardware: any error (shape not matching the
    # per-slice device count etc.) is a genuine topology error and MUST
    # propagate — a host-major fallback could silently lay the "ICI"
    # axis across DCN.  create_hybrid_device_mesh multiplies mesh_shape
    # and dcn_mesh_shape ELEMENTWISE (same length, same order), so the
    # DCN tier gets its own leading axis by padding both shapes:
    # (1, *ici) x (num_slices, 1, ...) -> (num_slices, *ici).
    arr = mesh_utils.create_hybrid_device_mesh(
        (1,) + tuple(ici_shape.values()),
        (num_slices,) + (1,) * len(ici_shape),
        devices=devices, process_is_granule=False)
    if arr.shape != tuple(shape.values()):  # contract check, not a cast
        raise ValueError(
            f"hybrid mesh came back {arr.shape}, wanted "
            f"{tuple(shape.values())}")
    return Mesh(arr, tuple(shape.keys()))


def global_mesh(shape: Dict[str, int],
                dcn_axis: Optional[str] = None) -> Mesh:
    """Mesh over ALL processes' devices.  If ``dcn_axis`` names an axis, it
    is laid out across hosts (slices) so only that axis's collectives ride
    DCN; every other axis stays within a slice on ICI — the layout rule
    that keeps the bandwidth-hungry collectives on the fast interconnect."""
    devices = jax.devices()  # all processes' devices, host-major order
    if dcn_axis is None:
        return make_mesh(shape, devices=devices)
    if dcn_axis not in shape:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in mesh shape {shape}")
    # host-major order: put the DCN axis outermost so host boundaries fall
    # on that axis's partitions
    ordered = {dcn_axis: shape[dcn_axis]}
    ordered.update({k: v for k, v in shape.items() if k != dcn_axis})
    mesh = make_mesh(ordered, devices=devices)
    # reorder axes back to caller's order
    names = tuple(shape.keys())
    arr = np.moveaxis(
        mesh.devices,
        [list(ordered).index(n) for n in names],
        range(len(names)),
    )
    return Mesh(arr, names)
