"""Multi-host mesh setup — the DCN tier of the communication backend.

The reference scales out with Spark executors + Aeron UDP between JVMs
(SURVEY.md §2c "Communication backend").  The TPU-native equivalent is
``jax.distributed``: one process per host, XLA runs collectives over ICI
within a slice and DCN across slices — no user-visible transport or
serialization layer.

On a single host (this environment, and any test rig) everything is a
no-op passthrough: the same mesh-building code serves 1 host or N.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from gan_deeplearning4j_tpu.parallel.mesh import make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job.  With no arguments, uses the standard env
    (JAX_COORDINATOR_ADDRESS etc.) and is a no-op on a single host."""
    if coordinator_address is None and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return  # single host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(shape: Dict[str, int],
                dcn_axis: Optional[str] = None) -> Mesh:
    """Mesh over ALL processes' devices.  If ``dcn_axis`` names an axis, it
    is laid out across hosts (slices) so only that axis's collectives ride
    DCN; every other axis stays within a slice on ICI — the layout rule
    that keeps the bandwidth-hungry collectives on the fast interconnect."""
    devices = jax.devices()  # all processes' devices, host-major order
    if dcn_axis is None:
        return make_mesh(shape, devices=devices)
    if dcn_axis not in shape:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in mesh shape {shape}")
    # host-major order: put the DCN axis outermost so host boundaries fall
    # on that axis's partitions
    ordered = {dcn_axis: shape[dcn_axis]}
    ordered.update({k: v for k, v in shape.items() if k != dcn_axis})
    mesh = make_mesh(ordered, devices=devices)
    # reorder axes back to caller's order
    names = tuple(shape.keys())
    arr = np.moveaxis(
        mesh.devices,
        [list(ordered).index(n) for n in names],
        range(len(names)),
    )
    return Mesh(arr, names)
