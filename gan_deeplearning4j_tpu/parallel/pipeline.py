"""Pipeline parallelism — GPipe-style microbatched stages over a mesh axis.

The reference has nothing layer-sharded (SURVEY.md §2c marks PP absent /
not required), but distributed coverage is first-class in this
framework's scope: when a model outgrows one chip's HBM the remaining
axis after data/tensor/sequence sharding is DEPTH.  This is the ICI
idiom for it, built from XLA collectives (no torch/NCCL translation):

  - stage ``s`` of ``S`` lives on device ``s`` of the ``pipe`` mesh axis
    (stage params are stacked on a leading axis and sharded over it)
  - the batch splits into ``M`` microbatches; at schedule tick ``t``
    (T = M + S - 1 ticks total) device ``s`` processes microbatch
    ``t - s`` when ``0 <= t - s < M`` — the classic GPipe staircase
  - activations flow stage-to-stage with ONE ``lax.ppermute`` hop per
    tick (neighbour traffic on the ICI torus); the last stage accumulates
    its outputs and a final ``psum`` broadcasts them
  - bubble fraction is (S-1)/T — amortized away by more microbatches

Stages must map activations of one fixed shape to the same shape (the
rotating buffer is shape-static under jit); heterogeneous-width models
pad to the pipeline width.  Forward-only here: it is the building block
the GANPair/fused engines would call per sub-network, and the exactness
contract (pipeline == sequential composition, tests) is the hard part.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipe_body(stage_params, micro, fn: Callable, axis_name: str,
               n_stages: int, n_micro: int):
    """shard_map body.  stage_params: this device's stage leaves (leading
    stage axis stripped by sharding).  micro: [M, B, F] microbatches
    (replicated).  Returns [M, B, F] outputs (replicated via psum)."""
    s = lax.axis_index(axis_name)
    # shard_map keeps the sharded stage axis as size 1 — strip it so the
    # body sees ONE stage's params
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    T = n_micro + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(t, carry):
        buf, outs = carry
        # what this device works on at tick t: microbatch m = t - s
        m = t - s
        feeding = jnp.logical_and(m >= 0, m < n_micro)
        # stage 0 reads the microbatch; later stages read the rotated buffer
        my_in = jnp.where(
            s == 0,
            lax.dynamic_index_in_dim(
                micro, jnp.clip(m, 0, n_micro - 1), keepdims=False),
            buf)
        out = fn(stage_params, my_in)
        out = jnp.where(feeding, out, jnp.zeros_like(out))
        # last stage: bank the finished microbatch
        is_last = s == n_stages - 1
        outs = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(jnp.logical_and(feeding, is_last), out,
                      lax.dynamic_index_in_dim(
                          outs, jnp.clip(m, 0, n_micro - 1),
                          keepdims=False)),
            jnp.clip(m, 0, n_micro - 1), axis=0)
        # rotate activations one hop down the pipe for the next tick
        buf = lax.ppermute(out, axis_name, perm)
        return buf, outs

    # one-microbatch activation buffer / banked outputs, any rank
    buf = jnp.zeros(micro.shape[1:], micro.dtype)
    outs = jnp.zeros(micro.shape, micro.dtype)
    _, outs = lax.fori_loop(0, T, tick, (buf, outs))
    # only the last stage holds real outputs; broadcast to every device
    return lax.psum(outs, axis_name)


def pipeline_apply(fn: Callable, stacked_params, x, mesh: Mesh,
                   axis: str = "pipe", n_micro: int = 4) -> jax.Array:
    """Run ``x`` through ``S`` pipelined stages.

    ``fn(stage_params, x) -> y`` applies ONE stage (same shape in/out).
    ``stacked_params``: pytree whose leaves have a leading stage axis of
    size S = mesh.shape[axis] (stage s's slice lives on pipe device s).
    ``x``: [N, F] with N divisible by ``n_micro``.
    Returns [N, F], equal to applying the S stages sequentially.
    """
    S = mesh.shape[axis]
    N = x.shape[0]
    if N % n_micro != 0:
        raise ValueError(f"batch {N} not divisible by n_micro {n_micro}")
    micro = x.reshape(n_micro, N // n_micro, *x.shape[1:])

    out = shard_map(
        partial(_pipe_body, fn=fn, axis_name=axis, n_stages=S,
                n_micro=n_micro),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, micro)
    return out.reshape(N, *x.shape[1:])
