"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has no attention or sequence axis at all (SURVEY.md §2c/§5:
conv/MLP GANs only), but long-context support is first-class in this
framework's scope: when a model family with attention lands (roadmap), it
must scale past single-chip HBM by sharding the SEQUENCE dimension.

Design (Ring Attention with online softmax, a la Liu et al. 2023, built
from XLA collectives — no torch/NCCL translation):

  - every device holds a sequence shard of Q, K, V: [B, H, T/R, D] under
    ``shard_map`` over the ``seq`` mesh axis (R = ring size)
  - R unrolled steps: compute the local Q-shard x current KV-block partial
    attention with a numerically-stable ONLINE softmax (running max m,
    denominator l, numerator o — flash-attention's streaming form, which
    is what makes block-wise accumulation exact, not approximate), then
    rotate the KV block one hop around the ring via ``lax.ppermute``
  - compute and ICI transfer overlap: XLA schedules the ppermute of the
    next block against the matmuls of the current one
  - causal masking uses global position offsets reconstructed from
    ``lax.axis_index`` and the (static) step number, so masks stay
    shard-local and the ring needs no extra communication

Peak memory per device is O(T/R * T/R) for one score block instead of
O(T^2) — sequence length scales linearly with ring size.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = False) -> jax.Array:
    """Vanilla scaled-dot-product attention, [B, H, T, D] — the single
    -device reference that ring_attention must match exactly."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _online_block(q, k, v, m, l, o, scale, mask):
    """One KV-block accumulation step of the streaming softmax."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp(-inf - -inf) guard: fully-masked rows keep m = -inf, p = 0
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - safe_m)
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention_sharded(q, k, v, axis_name: str, causal: bool = False,
                           ring_size: Optional[int] = None):
    """The shard-local body: call inside ``shard_map`` with q/k/v sequence
    -sharded over ``axis_name``.  Shapes [B, H, T_local, D]."""
    R = ring_size if ring_size is not None else lax.axis_size(axis_name)
    scale = 1.0 / np.sqrt(q.shape[-1])
    t_local = q.shape[2]
    my_idx = lax.axis_index(axis_name)

    m = jnp.full(q.shape[:-1], -jnp.inf, dtype=q.dtype)
    l = jnp.zeros(q.shape[:-1], dtype=q.dtype)
    o = jnp.zeros_like(q)

    perm = [(i, (i + 1) % R) for i in range(R)]
    q_pos = my_idx * t_local + jnp.arange(t_local)          # global Q positions

    for step in range(R):  # static unroll: masks differ per step
        kv_idx = (my_idx - step) % R                        # block's origin
        if causal:
            k_pos = kv_idx * t_local + jnp.arange(t_local)  # global K positions
            mask = q_pos[:, None] >= k_pos[None, :]         # [Tq, Tk]
            mask = mask[None, None]                         # broadcast B, H
        else:
            mask = None
        m, l, o = _online_block(q, k, v, m, l, o, scale, mask)
        if step + 1 < R:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    return o / jnp.where(l == 0.0, 1.0, l)[..., None]


def ring_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                   causal: bool = False) -> jax.Array:
    """Host-level entry: shards [B, H, T, D] over ``axis`` and runs the
    ring.  T must be divisible by the ring size."""
    R = mesh.shape[axis]
    if q.shape[2] % R != 0:
        raise ValueError(f"sequence length {q.shape[2]} not divisible by ring {R}")
    spec = P(None, None, axis, None)
    f = shard_map(
        partial(ring_attention_sharded, axis_name=axis, causal=causal,
                ring_size=R),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return f(q, k, v)
