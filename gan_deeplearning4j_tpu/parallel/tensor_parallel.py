"""Tensor parallelism — layer-sharding helpers over a ``model`` mesh axis.

Absent from the reference (SURVEY.md §2c: "TP: ABSENT — not required for
parity; pjit sharding makes it nearly free if added").  Provided as
first-class framework capability: the canonical Megatron-style pattern
with XLA collectives, for model families whose dense layers outgrow one
chip's HBM.

  - column-parallel: W split on the OUTPUT dim; each device computes a
    slice of the activations (no communication; activations stay sharded)
  - row-parallel: W split on the INPUT dim over already-sharded
    activations; one ``psum`` over ICI completes the contraction
  - the pair (column -> nonlinearity -> row) costs ONE all-reduce per MLP
    block — the scaling-book recipe

These are shard-local bodies for ``shard_map``; ``tp_dense_pair`` is the
host-level convenience wrapper.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def column_parallel_dense(x, w_shard, b_shard):
    """x: [B, F] replicated; w_shard: [F, H/R]; -> [B, H/R] sharded out."""
    return x @ w_shard + b_shard


def row_parallel_dense(x_shard, w_shard, b, axis_name: str):
    """x_shard: [B, H/R]; w_shard: [H/R, F]; psum completes the matmul.
    Bias is added AFTER the reduce (it is replicated, not sharded)."""
    partial_out = x_shard @ w_shard
    return lax.psum(partial_out, axis_name) + b


def tp_dense_pair(
    x: jax.Array,
    w1: jax.Array, b1: jax.Array,
    w2: jax.Array, b2: jax.Array,
    mesh: Mesh,
    axis: str = "model",
    activation: Optional[Callable] = jnp.tanh,
) -> jax.Array:
    """Megatron MLP block: [B,F] -> column-parallel [B,H/R] -> activation
    -> row-parallel + psum -> [B,F].  One ICI all-reduce total."""
    if w1.shape[1] % mesh.shape[axis] != 0:
        raise ValueError(
            f"hidden dim {w1.shape[1]} not divisible by TP degree {mesh.shape[axis]}"
        )

    def body(x, w1, b1, w2, b2):
        h = column_parallel_dense(x, w1, b1)
        if activation is not None:
            h = activation(h)
        return row_parallel_dense(h, w2, b2, axis)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, axis), P(axis), P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )(x, w1, b1, w2, b2)
