"""Ulysses-style all-to-all sequence parallelism — the second SP idiom.

Complements ring attention (ring_attention.py): where the ring keeps the
sequence sharded and rotates KV blocks R hops around the ICI torus,
Ulysses (DeepSpeed-Ulysses, Jacobs et al. 2023) re-shards ONCE — an
``all_to_all`` swaps the sharded axis from sequence to heads, every
device computes FULL-sequence attention for its H/R head group, and a
second ``all_to_all`` swaps back:

  [B, H, T/R, D]  --a2a(head<-seq)-->  [B, H/R, T, D]
      full-sequence attention per local head group (any kernel)
  [B, H/R, T, D]  --a2a(seq<-head)-->  [B, H, T/R, D]

Trade-offs vs the ring (why the framework carries both):
  - Ulysses: 2 collectives total, attention itself is a stock local op
    (composes with any attention kernel, flash or vanilla); but the head
    count must be divisible by the SP degree, capping scale at H.
  - Ring: scales to any degree that divides T and never materializes the
    full sequence per device — O(T/R * T/R) score blocks; but the
    attention inner loop itself must be ring-aware.

Causal masking needs no position bookkeeping here: each device sees the
full sequence for its heads.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from gan_deeplearning4j_tpu.parallel.ring_attention import attention


def ulysses_attention_sharded(q, k, v, axis_name: str,
                              causal: bool = False) -> jax.Array:
    """shard_map body: q/k/v are local sequence shards [B, H, T/R, D];
    returns the local output shard [B, H, T/R, D]."""
    # seq-sharded -> head-sharded: split heads (axis 1) across the mesh
    # axis, gather the sequence (axis 2)
    qh, kh, vh = (
        lax.all_to_all(a, axis_name, split_axis=1, concat_axis=2, tiled=True)
        for a in (q, k, v))
    o = attention(qh, kh, vh, causal=causal)   # [B, H/R, T, D], full seq
    # head-sharded -> seq-sharded
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                      causal: bool = False) -> jax.Array:
    """Host-level entry: shards [B, H, T, D] over ``axis`` (sequence) and
    runs all-to-all SP.  H and T must both be divisible by the SP degree
    (H for the head swap, T for the input sharding)."""
    R = mesh.shape[axis]
    if q.shape[1] % R != 0:
        raise ValueError(f"head count {q.shape[1]} not divisible by SP {R}")
    if q.shape[2] % R != 0:
        raise ValueError(f"sequence length {q.shape[2]} not divisible by SP {R}")
    spec = P(None, None, axis, None)
    f = shard_map(
        partial(ulysses_attention_sharded, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return f(q, k, v)
