from gan_deeplearning4j_tpu.runtime import backend, prng  # noqa: F401
