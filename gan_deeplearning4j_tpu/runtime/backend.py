"""Device/backend runtime configuration.

TPU-native replacement for the reference's backend plumbing: the Maven
``nd4j.backend`` build property and the runtime CUDA context setup
(``CudaEnvironment.getInstance().getConfiguration().allowMultiGPU(true)...``,
reference ``Java/src/main/java/org/deeplearning4j/dl4jGANComputerVision.java:96-105``)
become a runtime flag choosing a JAX platform plus a ``jax.sharding.Mesh``
over however many chips are attached.  There is no device cache to size and no
P2P toggle: HBM allocation and ICI routing are owned by XLA/PJRT.

Dtype policy mirrors ``Nd4j.setDataType(DataBuffer.Type.FLOAT)``
(dl4jGANComputerVision.java:98): default compute dtype float32, with an
optional bfloat16 matmul policy for the MXU fast path.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np

# NOTE on platform selection: the axon TPU image force-sets jax_platforms
# via sitecustomize AND exports JAX_PLATFORMS=axon ambiently, so a
# module-level "re-apply the env var" here is NOT safe — it would clobber
# an explicit in-process override (e.g. tests/conftest.py forcing cpu)
# with the ambient value.  Platform forcing therefore stays the caller's
# job: ``jax.config.update("jax_platforms", ...)`` before the first op
# (conftest.py and the dryrun re-exec both do this).  PROCESS ENTRY POINTS
# (a ``__main__``/fresh subprocess, where no in-process override can exist
# yet) may honor an explicit env request via ``apply_env_platform()``.


def apply_env_platform() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` env request at a process entry
    point.  The sitecustomize clobber (NOTE above) means the env var alone
    does nothing; call this from ``__main__``-style entries ONLY — never
    at library import time (it would override conftest-style in-process
    forcing with the ambient value).

    The host CPU backend is always kept on the list (the image exports the
    bare ``JAX_PLATFORMS=axon``, but bench's baseline and several fallbacks
    need ``jax.devices("cpu")`` — sitecustomize itself forces "axon,cpu").
    Appending cpu does NOT mask a dead accelerator: with an explicit
    platform list, JAX raises if any NAMED backend fails to initialize
    (verified against a bogus libtpu)."""
    value = os.environ.get("JAX_PLATFORMS")
    if value is None:
        return
    platforms = [p.strip() for p in value.split(",") if p.strip()]
    if platforms and "cpu" not in platforms:
        platforms.append("cpu")
    jax.config.update("jax_platforms", ",".join(platforms))


def backend_initialized() -> bool:
    """Whether any XLA backend has already been created in this process
    (after which XLA_FLAGS edits are silently ignored)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        # private-API probe; an unknown jax layout just means "assume
        # initialized" (the conservative answer)
        return True


def apply_xla_flags(flags: str, strict: bool = False) -> bool:
    """Append scheduling/overlap flags (XLA_FLAGS syntax, space-separated
    — e.g. ``--xla_tpu_enable_latency_hiding_scheduler=true``) to the
    process environment, BEFORE the jax backend initializes.

    XLA parses the env var exactly once, at backend creation: flags
    applied later are silently ignored, which is how a scheduling A/B
    silently measures two identical programs.  Returns True when the
    flags can still take effect; on an already-initialized backend it
    warns and returns False (raises under ``strict``) so callers that
    need a guarantee — bench's per-flag lanes — re-exec a fresh process
    instead (benchmarks/overlap_ab.py)."""
    if not flags:
        return True
    if backend_initialized():
        msg = ("XLA backend already initialized; XLA_FLAGS %r would be "
               "silently ignored — set them before the first jax "
               "device/compile call (bench's flag lanes re-exec for this)"
               % flags)
        if strict:
            raise RuntimeError(msg)
        import logging

        logging.getLogger(__name__).warning(msg)
        return False
    prev = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (prev + " " + flags).strip()
    return True


@dataclasses.dataclass
class RuntimeConfig:
    """Runtime equivalent of the reference's hardcoded backend constants."""

    # `useGpu` (dl4jGANComputerVision.java:85) -> platform selection; None = auto.
    platform: Optional[str] = None
    # Nd4j.setDataType(FLOAT) (dl4jGANComputerVision.java:98).
    dtype: np.dtype = np.float32
    # bfloat16 matmuls on the MXU; params/activations stay float32.
    matmul_bf16: bool = False
    # space-to-depth rewrite of C_in=1 stride-2 convs (ops/conv.py): an
    # exact reindexing that densifies the MXU contraction of the first
    # conv (the profiled 1/8-utilized contraction, RESULTS r2 §4).
    # None = auto: ON where there is an MXU (TPU — measured +5% multistep
    # throughput, RESULTS r3), OFF on CPU so reference-numerics tests see
    # the reference summation order.  True/False force it either way; only
    # float summation order changes in any case.
    conv_s2d: Optional[bool] = None
    # Full mixed precision (the documented TPU fast mode): forward/backward
    # run with bfloat16 params and activations while the MASTER params,
    # optimizer state, batch-norm computation/statistics and the loss stay
    # float32 (the standard mixed-precision recipe).  Halves the HBM
    # traffic of every elementwise/normalization segment — the fused
    # step's non-MXU time — on top of matmul_bf16's contraction speedup.
    # Off by default: deviates further from the reference's fixed f32
    # numerics than matmul_bf16 (quality spot-check in RESULTS.md).
    compute_bf16: bool = False
    # seed 666 everywhere ("numberOfTheBeast", dl4jGANComputerVision.java:68).
    seed: int = 666


_config = RuntimeConfig()

BF16_HELP = (
    "bfloat16 operands into every MXU contraction (conv, transposed conv, "
    "dense); params/activations stay float32, each op's result is rounded "
    "through bf16 once (the MXU accumulates partial products in f32 "
    "internally). Faster; deviates from the reference's fixed float32 "
    "numerics — see RESULTS.md for the measured speed/quality trade."
)


def add_bf16_flag(parser) -> None:
    """Register the shared --bf16 CLI flag (one help text, no drift)."""
    parser.add_argument("--bf16", action="store_true", help=BF16_HELP)


MP_HELP = (
    "full mixed precision (the TPU fast mode): forward/backward in "
    "bfloat16 params/activations with float32 master params, optimizer "
    "state, batch-norm statistics and loss.  Implies nothing about "
    "--bf16 (combine them for the fastest path).  Deviates further from "
    "the reference's fixed float32 numerics — quality spot-check in "
    "RESULTS.md."
)


def add_mp_flag(parser) -> None:
    """Register the shared --mp (compute_bf16) CLI flag."""
    parser.add_argument("--mp", action="store_true", help=MP_HELP)


def configure(**kwargs) -> RuntimeConfig:
    """Set global runtime options (platform, dtype, seed)."""
    global _config
    _config = dataclasses.replace(_config, **kwargs)
    if _config.platform is not None:
        jax.config.update("jax_platforms", _config.platform)
    return _config


def config() -> RuntimeConfig:
    return _config


def conv_s2d_enabled() -> bool:
    """Resolve the tri-state ``conv_s2d`` flag (see RuntimeConfig): an
    explicit setting wins; auto (None) enables the rewrite exactly where
    the MXU makes it pay — i.e. not on the CPU backend.

    Auto keys on the device the op will actually run on BY DEFAULT, not
    just the process-wide backend: a ``with jax.default_device(cpu)``
    scope on a TPU host (bench.py's CPU-baseline measurement) must see
    the reference summation order, so an active default_device wins over
    ``jax.default_backend()``."""
    if _config.conv_s2d is not None:
        return _config.conv_s2d
    dev = getattr(jax.config, "jax_default_device", None)
    if dev is not None:
        platform = dev if isinstance(dev, str) else getattr(dev, "platform", None)
        if platform:
            return platform != "cpu"
    return jax.default_backend() != "cpu"


def default_dtype() -> np.dtype:
    return _config.dtype


def devices() -> list:
    return jax.devices()


def device_count() -> int:
    return len(jax.devices())


def make_mesh(
    axis_sizes: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("data",),
    devices: Optional[Sequence] = None,
) -> jax.sharding.Mesh:
    """Build a device mesh.

    The reference's only parallel axis is data (4 Spark workers under
    ``local[4]``, dl4jGANComputerVision.java:305); the general form here also
    carries a 'model' axis for tensor parallelism, which DL4J cannot express.
    """
    devs = list(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = [len(devs)] + [1] * (len(axis_names) - 1)
    n = int(np.prod(axis_sizes))
    if n > len(devs):
        raise ValueError(f"mesh wants {n} devices, only {len(devs)} available")
    grid = np.array(devs[:n]).reshape(axis_sizes)
    return jax.sharding.Mesh(grid, axis_names)


def host_device_count_for_testing(n: int = 8) -> None:
    """The reference tests its distributed path with Spark ``local[4]`` on one
    machine (SURVEY.md §4.4).  The TPU-native equivalent: N virtual CPU
    devices, so the full pjit/shard_map collective path runs clusterless.

    Must be called before the JAX backend initializes.
    """
    os.environ.setdefault(
        "XLA_FLAGS",
        f"--xla_force_host_platform_device_count={n}",
    )
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:  # gan4j-lint: disable=swallowed-exception — older jax lacks jax_num_cpu_devices; the XLA_FLAGS fallback above covers it
        pass
