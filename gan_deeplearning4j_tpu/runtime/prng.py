"""PRNG seeding discipline.

The reference seeds everything with 666 (``numberOfTheBeast``,
dl4jGANComputerVision.java:68) and relies on ND4J's global stateful RNG.  JAX
PRNG is functional — this module provides a small named-stream splitter so
trainers get reproducible, independent streams (init / noise / dropout / data)
from one root seed without global mutable state.
"""

from __future__ import annotations

import hashlib

import jax

NUMBER_OF_THE_BEAST = 666


def root_key(seed: int = NUMBER_OF_THE_BEAST) -> jax.Array:
    return jax.random.key(seed)


def stream(key: jax.Array, name: str) -> jax.Array:
    """Derive a named independent stream from a key (stable across runs)."""
    h = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")
    return jax.random.fold_in(key, h)


def fold_in_index(key: jax.Array, index) -> jax.Array:
    """Per-replica/per-step stream from a traced integer (e.g.
    ``lax.axis_index`` inside ``shard_map``)."""
    return jax.random.fold_in(key, index)


class KeySequence:
    """Stateful convenience wrapper: `next(seq)` yields fresh subkeys.

    Host-side only (do not use inside jit); inside jitted steps thread keys
    explicitly.
    """

    def __init__(self, key: jax.Array):
        self._key = key

    def __next__(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def take(self, n: int):
        keys = jax.random.split(self._key, n + 1)
        self._key = keys[0]
        return list(keys[1:])
