"""PRNG seeding discipline.

The reference seeds everything with 666 (``numberOfTheBeast``,
dl4jGANComputerVision.java:68) and relies on ND4J's global stateful RNG.  JAX
PRNG is functional — this module provides a small named-stream splitter so
trainers get reproducible, independent streams (init / noise / dropout / data)
from one root seed without global mutable state.
"""

from __future__ import annotations

import hashlib

import jax

NUMBER_OF_THE_BEAST = 666


def root_key(seed: int = NUMBER_OF_THE_BEAST) -> jax.Array:
    return jax.random.key(seed)


def stream(key: jax.Array, name: str) -> jax.Array:
    """Derive a named independent stream from a key (stable across runs)."""
    h = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")
    return jax.random.fold_in(key, h)


def fold_in_index(key: jax.Array, index) -> jax.Array:
    """Per-replica/per-step stream from a traced integer (e.g.
    ``lax.axis_index`` inside ``shard_map``)."""
    return jax.random.fold_in(key, index)
