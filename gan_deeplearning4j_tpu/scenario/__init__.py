"""Combined-chaos train→serve scenario: the production organism.

One process tree, two planes, every robustness subsystem engaged at
once: a fleet trainer (train/fleet.py) checkpoints while a serving
mesh (serve/controlplane.py) answers traffic; the checkpoint
publisher (serve/publisher.py) carries every verified checkpoint
across the gap via canary deployment; a seeded chaos schedule
(testing/chaos.py) injects preemption, device loss, replica kills,
slow-loris sockets, and corrupt tenant rows into both planes at
once.  The runner (scenario/runner.py) orchestrates the whole thing
and emits a typed verdict; the trainer child
(scenario/trainer_child.py) is the preemptible unit the runner
respawns.

Entry point: ``bench --scenario`` (``--soak`` rides the leak gate),
or ``run_scenario`` directly.  docs/SCENARIO.md is the operator's
guide.
"""

from gan_deeplearning4j_tpu.scenario.runner import run_scenario

__all__ = ["run_scenario"]
