"""Combined-chaos train→serve scenario runner.

One invocation stands up the WHOLE production organism and breaks it
on a seeded schedule, across both planes at once:

* a fleet trainer (scenario/trainer_child.py, a real subprocess)
  checkpoints while it trains; the runner respawns it through the
  preemption (exit 75) and device-lost (exit 82, shrunk world)
  protocols;
* a serving mesh (serve/controlplane.py, fleet replicas as real
  subprocesses) answers plain + per-tenant traffic throughout;
* the checkpoint publisher (serve/publisher.py) carries every
  verified checkpoint across the train→serve gap via canary
  deployment — and rejects the poisoned one;
* a seeded :class:`~gan_deeplearning4j_tpu.testing.chaos.ChaosSchedule`
  coordinates the injections (trainer SIGTERM, corrupt tenant rows,
  replica SIGKILL, slow-loris, device-lost + world shrink) and writes
  its deterministic timeline into the events stream.

The verdict is TYPED: zero non-typed serving failures, every verified
checkpoint promoted and the poisoned one rejected
(``gan4j_publish_rejected_total >= 1``), a direct poisoned deploy
rolled back by the canary, serving stale-but-answering after the
trainer stops, the chaos trajectory banded ≤``band`` (default 5%)
against an undisturbed control run at identical step count, and ONE
merged cross-process timeline (telemetry/tracing.merge_trace_files)
spanning every trainer incarnation and every replica.  ``soak=True``
additionally samples resources for the leak gate
(bench_gate.check_soak) — the scenario as a soak payload.

Entry: ``bench --scenario [--soak]``; docs/SCENARIO.md is the
operator's guide.
"""

import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TRAINER_MODULE = "gan_deeplearning4j_tpu.scenario.trainer_child"

# the merged-timeline ingestion set: every plane's instant events
TRACE_EVENT_PREFIXES = (
    "fleet.", "preempt.", "chaos.", "publish.", "serve.", "replica.",
    "controlplane.", "scenario.", "router.", "mesh.",
)


def _child_env(world: Optional[int]) -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # subprocesses must resolve the package the same way this process
    # did (the repo is run in-tree, not installed)
    env["PYTHONPATH"] = _PKG_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if world and world > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={world} "
            + env.get("XLA_FLAGS", "")).strip()
    return env


def _write_insurance_csv(path: str, rows: int, width: int,
                         seed: int) -> None:
    rng = np.random.default_rng(seed)
    feats = rng.uniform(0.0, 1.0, size=(rows, width - 1))
    labels = (rng.random(rows) < 0.5).astype(np.float64)
    data = np.concatenate([feats, labels[:, None]], axis=1)
    with open(path, "w") as f:
        for r in data:
            f.write(",".join(f"{v:.6f}" for v in r) + "\n")


class _LoadLoop:
    """Continuous plain + per-tenant traffic against whatever replicas
    the control plane currently reports — the SLO witness.  Every
    failure is CLASSIFIED: wire/HTTP/route errors during chaos are
    typed (expected, counted); anything else is a non-typed failure
    that fails the verdict."""

    def __init__(self, cp, tenants: int, seed: int):
        self.cp = cp
        self.tenants = int(tenants)
        self.rng = np.random.default_rng(seed)
        self.requests = 0
        self.ok = 0
        self.typed_errors = 0
        self.non_typed: List[str] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gan4j-scenario-load")

    def start(self):
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30.0)

    def probe_once(self) -> bool:
        """One synchronous plain request against the first live
        replica (the degradation witness: stale weights must still
        answer)."""
        return self._one_request(tenant=None)

    def _one_request(self, tenant: Optional[str]) -> bool:
        from gan_deeplearning4j_tpu.serve.client import (
            GatewayClient,
            GatewayHTTPError,
        )

        names = self.cp.replica_names()
        with self._lock:
            self.requests += 1
        if not names:
            with self._lock:
                self.typed_errors += 1  # mid-heal: typed, not silent
            return False
        name = names[self.requests % len(names)]
        host, port = name.rsplit(":", 1)
        xs = [self.rng.normal(size=(2, 2)).astype(np.float32)]
        client = GatewayClient(host, int(port), retries=0,
                               timeout_s=15.0)
        try:
            out = client.generate(xs, tenant=tenant, encoding="npy")
            finite = all(np.isfinite(o).all() for o in out)
            with self._lock:
                if finite:
                    self.ok += 1
                else:
                    self.non_typed.append(
                        f"non-finite output from {name}")
            return finite
        except (GatewayHTTPError, OSError):
            # replicas being killed / hotswapped / slow-lorised answer
            # with typed wire or HTTP errors — the contract under test
            with self._lock:
                self.typed_errors += 1
            return False
        except Exception as e:
            with self._lock:
                self.non_typed.append(f"{type(e).__name__}: {e}")
            return False
        finally:
            client.close()

    def _run(self) -> None:
        i = 0
        while not self._stop.is_set():
            tenant = (None if i % 2 == 0
                      else str(i % self.tenants))
            self._one_request(tenant)
            i += 1
            self._stop.wait(0.1)

    def report(self) -> Dict:
        with self._lock:
            return {"requests": self.requests, "ok": self.ok,
                    "typed_errors": self.typed_errors,
                    "non_typed": list(self.non_typed)}


class _TrainerSupervisor:
    """Spawn/respawn scenario trainer children and expose the current
    process to the chaos schedule (which signals it by pid)."""

    def __init__(self, res_path: str, data_csv: str, *, tenants: int,
                 batch_size: int, seed: int, checkpoint_every: int,
                 step_delay_s: float, log_dir: str):
        self.res_path = res_path
        self.data_csv = data_csv
        self.tenants = tenants
        self.batch_size = batch_size
        self.seed = seed
        self.checkpoint_every = checkpoint_every
        self.step_delay_s = step_delay_s
        self.log_dir = log_dir
        self.proc: Optional[subprocess.Popen] = None
        self.incarnation = 0
        self.exits: List[int] = []
        self._lock = threading.Lock()

    def spawn(self, *, iterations: int, world: Optional[int],
              resume: bool, step_delay_s: Optional[float] = None
              ) -> subprocess.Popen:
        with self._lock:
            self.incarnation += 1
        delay = (self.step_delay_s if step_delay_s is None
                 else step_delay_s)
        cmd = [sys.executable, "-m", TRAINER_MODULE,
               "--res-path", self.res_path,
               "--data", self.data_csv,
               "--tenants", str(self.tenants),
               "--iterations", str(iterations),
               "--batch-size", str(self.batch_size),
               "--seed", str(self.seed),
               "--checkpoint-every", str(self.checkpoint_every),
               "--step-delay-s", str(delay)]
        if world is not None:
            cmd += ["--n-devices", str(world)]
        if resume:
            cmd += ["--resume"]
        log_path = os.path.join(
            self.log_dir, f"trainer_{self.incarnation}.log")
        log = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                    env=_child_env(world))
        finally:
            log.close()
        with self._lock:
            self.proc = proc
        return proc

    def current(self) -> Optional[subprocess.Popen]:
        with self._lock:
            return self.proc

    def signal_current(self, signum: int) -> bool:
        proc = self.current()
        if proc is None or proc.poll() is not None:
            return False
        proc.send_signal(signum)
        return True

    def wait(self, timeout_s: float) -> int:
        """Bounded wait; a child that outlives the bound is killed and
        reported as exit -1 (a typed verdict failure, not a hang)."""
        proc = self.current()
        assert proc is not None
        try:
            code = int(proc.wait(timeout=timeout_s))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10.0)
            code = -1
        self.exits.append(code)
        return code

    def kill_current(self) -> None:
        proc = self.current()
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)


def _wait_for(pred, timeout_s: float, what: str,
              poll_s: float = 0.2) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll_s)
    return pred()


def run_scenario(out_dir: str, *, seed: int = 23, soak: bool = False,
                 budget_s: float = 180.0, tenants: int = 4,
                 rows_per_tenant: int = 16, batch_size: int = 4,
                 checkpoint_every: int = 8, step_delay_s: float = 0.15,
                 final_extra_steps: int = 16, band: float = 0.05,
                 stale_after_s: float = 6.0,
                 log=print) -> Dict:
    """Run the combined-chaos scenario; returns the typed verdict
    dict (``ok`` plus per-plane evidence), writing ``scenario.json``,
    ``merged_trace.json`` and all child logs/events under
    ``out_dir``."""
    from gan_deeplearning4j_tpu.models import mlpgan_insurance as M
    from gan_deeplearning4j_tpu.serve import (
        Autoscaler,
        CheckpointPublisher,
        ControlPlane,
        ReplicaLauncher,
    )
    from gan_deeplearning4j_tpu.telemetry import (
        MetricsRegistry,
        events as events_mod,
        serve_exporter,
        tracing as tracing_mod,
    )
    from gan_deeplearning4j_tpu.testing import chaos

    t_start = time.monotonic()
    os.makedirs(out_dir, exist_ok=True)
    trainer_dir = os.path.join(out_dir, "trainer")
    control_dir = os.path.join(out_dir, "control")
    serving_dir = os.path.join(out_dir, "serving")
    data_dir = os.path.join(out_dir, "data")
    for d in (trainer_dir, control_dir, serving_dir, data_dir):
        os.makedirs(d, exist_ok=True)
    ckpt_dir = os.path.join(trainer_dir, "checkpoints")

    width = M.InsuranceConfig().num_features + 1
    chaos_csv = os.path.join(data_dir, "chaos.csv")
    control_csv = os.path.join(data_dir, "control.csv")
    _write_insurance_csv(chaos_csv, tenants * rows_per_tenant, width,
                         seed)
    shutil.copyfile(chaos_csv, control_csv)

    events_path = os.path.join(out_dir, "scenario.events.jsonl")
    recorder = events_mod.EventRecorder(path=events_path)
    prev_rec = events_mod.install(recorder)
    registry = MetricsRegistry()
    rmon = None
    if soak:
        from gan_deeplearning4j_tpu.telemetry.resources import (
            ResourceMonitor,
        )

        rmon = ResourceMonitor(interval_s=0.25)
        rmon.start()
        registry.observe_resources(rmon.report)
    stop_exporter = serve_exporter(registry, 0)

    failures: List[str] = []

    def check(ok: bool, name: str, detail: str = "") -> bool:
        if not ok:
            failures.append(f"{name}: {detail}" if detail else name)
        return ok

    sup = _TrainerSupervisor(
        trainer_dir, chaos_csv, tenants=tenants,
        batch_size=batch_size, seed=seed,
        checkpoint_every=checkpoint_every,
        step_delay_s=step_delay_s, log_dir=out_dir)
    launcher = ReplicaLauncher(
        buckets=(4, 16), log_dir=serving_dir,
        env={"JAX_PLATFORMS": "cpu",
             "PYTHONPATH": _child_env(None)["PYTHONPATH"]},
        events_dir=serving_dir,
        args=("--fleet", "--fleet-tenants", str(tenants)))
    scaler = Autoscaler(min_replicas=2, max_replicas=2,
                        up_after=10 ** 6, down_after=10 ** 6,
                        cooldown_ticks=4)
    cp = ControlPlane(launcher, autoscaler=scaler, tick_s=0.25,
                      hold_ticks=2, max_rollbacks=2,
                      probe_timeout_s=60.0, p99_floor_ms=10_000.0)
    pub = None
    load = None
    schedule = None
    trainer_final: Optional[Dict] = None
    control_final: Optional[Dict] = None
    band_rec: Dict = {}
    merged_stats: Dict = {}
    trace_rec: Dict = {}
    try:
        events_mod.instant("scenario.start", seed=seed, soak=soak,
                           tenants=tenants)
        log(f"[scenario] seed {seed}: starting mesh (2 fleet replicas)")
        cp.start()
        registry.observe_controlplane(cp.report)
        registry.observe_serving_mesh(cp.mesh.report)
        pub = CheckpointPublisher(ckpt_dir, controlplane=cp,
                                  poll_s=0.3,
                                  stale_after_s=stale_after_s,
                                  deploy_timeout_s=60.0)
        registry.observe_publication(pub.report)
        pub.start()
        load = _LoadLoop(cp, tenants, seed + 1).start()

        # -- incarnation 1: train until the schedule preempts it ------
        log("[scenario] incarnation 1 (world=2): training under chaos")
        sup.spawn(iterations=10 ** 6, world=2, resume=False)
        # something to resume from + something for the publisher to
        # carry BEFORE chaos starts tearing things down
        check(_wait_for(lambda: pub.report()["last_step"] > 0, 120.0,
                        "first publication"),
              "first_publication",
              "no checkpoint published within 120s")

        victim = cp.replica_names()[0]
        vhost, vport = victim.rsplit(":", 1)
        schedule = chaos.ChaosSchedule(seed)
        schedule.add(0.2, "preempt_trainer", lambda: sup.signal_current(
            signal.SIGTERM), plane="train", signal="SIGTERM")
        schedule.add(0.5, "corrupt_tenant_rows",
                     lambda: chaos.ChaosInjector(seed).corrupt_csv_rows(
                         chaos_csv, n_rows=2),
                     plane="train", rows=2)
        schedule.add(1.0, "kill_replica",
                     lambda: chaos.kill_replica_process(
                         cp.process(victim)),
                     plane="serve", replica=victim)
        schedule.add(2.0, "slow_loris",
                     lambda: chaos.SlowLorisClient(
                         vhost, int(vport)).run(max_s=2.0),
                     plane="serve", target=victim)

        def _ready_pid() -> int:
            try:
                with open(os.path.join(trainer_dir,
                                       "READY.json")) as f:
                    return int(json.load(f).get("pid", -1))
            except (OSError, ValueError):
                return -1

        def _signal_ready_child(signum: int) -> bool:
            # only signal a child whose handler is ARMED (READY.json
            # names the pid): SIGUSR1 during interpreter startup would
            # kill the process instead of injecting the fault
            proc = sup.current()
            if (sup.incarnation < 2 or proc is None
                    or proc.poll() is not None
                    or _ready_pid() != proc.pid):
                return False
            return sup.signal_current(signum)

        def _device_lost():
            # fires once incarnation 2 is up and armed (bounded wait;
            # the schedule thread owns the delay, not the runner)
            _wait_for(lambda: _signal_ready_child(signal.SIGUSR1),
                      120.0, "device-lost signal delivery", poll_s=0.5)

        schedule.add(4.0, "device_lost_shrink_world", _device_lost,
                     plane="train", signal="SIGUSR1", world="2->1")
        schedule.start()

        code = sup.wait(timeout_s=120.0)
        check(code == 75, "preempt_exit_code",
              f"incarnation 1 exited {code}, wanted 75")
        check(os.path.exists(os.path.join(trainer_dir,
                                          "PREEMPTED.json")),
              "preempted_marker", "PREEMPTED.json missing")

        # -- incarnation 2: resume; the schedule's device-lost lands --
        log("[scenario] incarnation 2 (world=2): resume after preempt")
        sup.spawn(iterations=10 ** 6, world=2, resume=True)
        code = sup.wait(timeout_s=180.0)
        check(code == 82, "device_lost_exit_code",
              f"incarnation 2 exited {code}, wanted 82")

        # -- incarnation 3: shrunk world, runs to completion ----------
        from gan_deeplearning4j_tpu.train.fleet import FleetCheckpointer

        resume_step = (FleetCheckpointer(
            ckpt_dir, sweep_debris=False)._inner.latest_step() or 0)
        final_target = int(resume_step) + int(final_extra_steps)
        log(f"[scenario] incarnation 3 (world=1): resume at "
            f"{resume_step}, run to {final_target}")
        sup.spawn(iterations=final_target, world=1, resume=True)
        code = sup.wait(timeout_s=180.0)
        check(code == 0, "final_exit_code",
              f"incarnation 3 exited {code}, wanted 0")
        final_path = os.path.join(trainer_dir, "final.json")
        if os.path.exists(final_path):
            with open(final_path) as f:
                trainer_final = json.load(f)
        check(trainer_final is not None, "trainer_final",
              "final.json missing")
        schedule.stop()

        # -- publication catches up to the final checkpoint -----------
        ck = FleetCheckpointer(ckpt_dir, sweep_debris=False)
        final_step = int(ck._inner.latest_verified_step() or 0)
        check(_wait_for(
            lambda: pub.report()["last_step"] >= final_step, 90.0,
            "final promotion"),
            "final_promotion",
            f"publisher at {pub.report()['last_step']}, "
            f"final checkpoint {final_step}")
        verified = [s for s in ck.steps() if ck.verify(s)]
        promoted = set(pub.report()["promoted_steps"])
        check(set(verified) <= promoted, "every_verified_published",
              f"verified {verified} vs promoted {sorted(promoted)}")

        # -- poison: publisher rejects; direct deploy canary-rolls-back
        # (tenant 0 poisoned so the canary's plain probe — tenant 0's
        # engine — sees the NaN weights too)
        bad_step = chaos.poison_fleet_checkpoint_dir(ckpt_dir, tenant=0)
        events_mod.instant("chaos.poison_checkpoint", step=bad_step,
                           tenant=0)
        check(_wait_for(
            lambda: pub.report()["rejected_total"] >= 1, 30.0,
            "publisher rejection"),
            "publisher_rejects_poison",
            f"rejected_total={pub.report()['rejected_total']}")
        check(pub.report()["last_step"] == final_step,
              "poison_never_promoted",
              f"last_step moved to {pub.report()['last_step']}")

        deployed = False
        for _ in range(40):
            try:
                cp.deploy(ckpt_dir, step=bad_step)
                deployed = True
                break
            except RuntimeError:
                time.sleep(0.25)  # publisher deploy still in flight
        check(deployed, "direct_poison_deploy", "deploy stayed busy")
        if deployed:
            _wait_for(lambda: cp.deployment_status()["state"]
                      not in ("pending", "canary"), 90.0,
                      "poisoned canary resolution")
            status = cp.deployment_status()
            check(status["state"] == "rolled_back",
                  "canary_rollback",
                  f"deployment ended {status['state']}")
        check(cp.report()["rollbacks_total"] >= 1, "rollback_counted",
              str(cp.report()["rollbacks_total"]))
        check(cp.report()["replaced_total"] >= 1, "replica_healed",
              "killed replica was never replaced")

        # -- graceful degradation: stale but still answering ----------
        _wait_for(lambda: pub.report()["stale"], stale_after_s + 10.0,
                  "staleness flag")
        check(pub.report()["stale"], "serving_stale_flag",
              "publication never went stale after trainer stopped")
        check(registry.health().get("serving_stale") is True,
              "healthz_serving_stale", "healthz flag not raised")
        check(load.probe_once(), "stale_probe",
              "replica did not answer on stale weights")
    finally:
        if schedule is not None:
            schedule.stop()
        if load is not None:
            load.stop()
        if pub is not None:
            pub.stop()
        sup.kill_current()
        try:
            cp.stop()
        except Exception as e:  # gan4j-lint: disable=swallowed-exception — teardown must reach the recorder/exporter below; a stop error is recorded in the verdict via failures
            failures.append(f"controlplane_stop: {e!r}")
        if rmon is not None:
            rmon.stop()
        events_mod.instant("scenario.done",
                           wall_s=round(time.monotonic() - t_start, 3))
        recorder.flush()
        events_mod.install(prev_rec)
        recorder.close()
        stop_exporter()

    # -- serving SLO ---------------------------------------------------
    serving = load.report() if load is not None else {}
    check(not serving.get("non_typed"), "zero_non_typed",
          "; ".join(serving.get("non_typed", [])[:3]))
    check(serving.get("ok", 0) >= 5, "serving_throughput",
          f"only {serving.get('ok', 0)} successful requests")

    # -- one merged cross-process timeline -----------------------------
    trainer_events = os.path.join(trainer_dir, "events.jsonl")
    replica_events = sorted(glob.glob(
        os.path.join(serving_dir, "replica_*.events.jsonl")))
    trace_paths = [p for p in
                   [events_path, trainer_events] + replica_events
                   if os.path.exists(p)]
    merged = tracing_mod.merge_trace_files(
        trace_paths, include_events=TRACE_EVENT_PREFIXES)
    merged_stats = merged["stats"]
    with open(os.path.join(out_dir, "merged_trace.json"), "w") as f:
        json.dump(merged, f)
    timeline = merged["timeline"]
    trainer_hosts = {e["host"] for e in timeline
                     if e["name"].startswith(("fleet.", "preempt."))}
    replica_hosts = {e["host"] for e in timeline
                     if e["name"].startswith(("serve.", "replica."))}
    chaos_marks = [e for e in timeline
                   if e["name"].startswith("chaos.")]
    trace_rec = {"stats": merged_stats,
                 "trainer_incarnations": len(trainer_hosts),
                 "replica_processes": len(replica_hosts),
                 "chaos_events": len(chaos_marks)}
    check(len(trainer_hosts) >= 2, "trace_trainer_incarnations",
          f"{len(trainer_hosts)} trainer hosts in merged timeline")
    check(len(replica_hosts) >= 2, "trace_replica_hosts",
          f"{len(replica_hosts)} replica hosts in merged timeline")
    check(len(chaos_marks) >= 4, "trace_chaos_timeline",
          f"{len(chaos_marks)} chaos events in merged timeline")
    check(merged_stats.get("segments", 0) >= 3, "trace_segments",
          f"{merged_stats.get('segments')} recorder segments")

    # -- undisturbed control run at identical step count ----------------
    if trainer_final is not None:
        log(f"[scenario] control run: {trainer_final['step']} clean "
            "steps (no delay, no chaos)")
        ctl = _TrainerSupervisor(
            control_dir, control_csv, tenants=tenants,
            batch_size=batch_size, seed=seed,
            checkpoint_every=0, step_delay_s=0.0, log_dir=out_dir)
        ctl.spawn(iterations=int(trainer_final["step"]), world=2,
                  resume=False)
        code = ctl.wait(timeout_s=180.0)
        check(code == 0, "control_exit_code", f"exited {code}")
        ctl_path = os.path.join(control_dir, "final.json")
        if os.path.exists(ctl_path):
            with open(ctl_path) as f:
                control_final = json.load(f)
        if control_final is not None:
            for key in ("d_loss", "g_loss"):
                a = float(trainer_final[key])
                b = float(control_final[key])
                rel = abs(a - b) / max(abs(b), 1e-6)
                band_rec[key] = {"chaos": a, "control": b,
                                 "rel": round(rel, 4)}
                check(rel <= band, f"band_{key}",
                      f"|{a:.4f}-{b:.4f}|/{abs(b):.4f}="
                      f"{rel:.3f} > {band}")
            check(control_final["step"] == trainer_final["step"],
                  "band_same_steps", "step counts differ")
        else:
            check(False, "control_final", "control final.json missing")

    verdict: Dict = {
        "type": "scenario", "scenario": "combined_chaos",
        "seed": int(seed), "soak": bool(soak),
        "failures": failures, "ok": not failures,
        "trainer": {"exits": sup.exits,
                    "incarnations": sup.incarnation,
                    "final": trainer_final},
        "control": control_final,
        "band": band_rec,
        "publish": pub.report() if pub is not None else {},
        "controlplane": cp.report(),
        "serving": serving,
        "chaos": schedule.report() if schedule is not None else {},
        "trace": trace_rec,
        "wall_s": round(time.monotonic() - t_start, 3),
        "budget_s": float(budget_s),
        "artifacts_dir": out_dir,
    }
    if soak and rmon is not None:
        from gan_deeplearning4j_tpu.telemetry.resources import (
            leak_verdict,
        )

        samples = rmon.samples()
        verdict["leak"] = leak_verdict(samples)
        with open(os.path.join(out_dir, "soak_samples.json"),
                  "w") as f:
            json.dump(samples, f)
        verdict["ok"] = bool(verdict["ok"]
                             and verdict["leak"].get("ok"))
        if not verdict["leak"].get("ok"):
            verdict["failures"].append(
                f"leak_gate: {verdict['leak'].get('leaking')}")
    with open(os.path.join(out_dir, "scenario.json"), "w") as f:
        json.dump(verdict, f, indent=1, default=float)
    return verdict
