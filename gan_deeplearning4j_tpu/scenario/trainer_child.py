"""The scenario's preemptible trainer unit: one fleet incarnation.

``python -m gan_deeplearning4j_tpu.scenario.trainer_child`` runs ONE
incarnation of the fleet trainer (train/fleet.py) the way a cluster
scheduler would see it — a process that either finishes, is preempted,
or loses hardware — and maps each outcome to the exit-code protocol
the runner (scenario/runner.py) supervises:

* **0** — ran to ``--iterations``; ``final.json`` in ``--res-path``
  carries the terminal trajectory (step, mean d/g loss, quarantined
  rows) for the ≤5%-band comparison against the undisturbed control.
* **75** (``EXIT_PREEMPTED``) — the default SIGTERM guard
  (train/preemption.py) latched and the loop drained: emergency fleet
  checkpoint, ``PREEMPTED.json`` marker, clean exit.  The orchestrator
  respawns with ``--resume``; 75 is "requeue me", not a crash.
* **82** (``EXIT_DEVICE_LOST``) — the ``--device-lost-signal``
  (default SIGUSR1) handler raised
  :class:`~gan_deeplearning4j_tpu.testing.chaos.DeviceLostError` at
  the next step boundary, deliberately WITHOUT an emergency save:
  lost hardware does not get to flush, so the respawn exercises the
  restore-from-older-cadence-checkpoint path (and, when the runner
  shrinks ``--n-devices``, the elastic reshard-on-restore path).

Data is read tolerantly: rows the chaos injector rewrote as
``#CORRUPT#,...`` (testing/chaos.corrupt_csv_rows) parse to NaN rows
of the right width and flow into the TenantRouter, whose per-tenant
quarantine is exactly the subsystem under test — a corrupt feed must
cost rows, not the run.

``--step-delay-s`` paces the loop (the insurance MLPs step far faster
than any real fleet would) so checkpoint cadence, publisher
throughput, and chaos timing interact on CI the way they would at
production step times.
"""

import argparse
import json
import os
import signal
import sys
import time
from typing import List, Optional

import numpy as np

# deliberately NOT train/preemption's 75: a distinct code so the
# orchestrator can tell "requeue me" (preempted, emergency checkpoint
# on disk) from "hardware gone" (resume from an older cadence save)
EXIT_DEVICE_LOST = 82

FINAL_NAME = "final.json"


def read_csv_tolerant(path: str, width: int) -> np.ndarray:
    """Parse ``path`` into ``(rows, width)`` float32, mapping every
    unparsable or wrong-width line (e.g. the chaos injector's
    ``#CORRUPT#`` rewrites) to a NaN row instead of failing the load —
    deciding what a bad row COSTS is the TenantRouter quarantine's
    job, not the parser's."""
    rows: List[List[float]] = []
    nan_row = [float("nan")] * width
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                vals = [float(p) for p in line.split(",")]
            except ValueError:
                vals = nan_row
            rows.append(vals if len(vals) == width else nan_row)
    if not rows:
        raise ValueError(f"{path}: no data rows")
    return np.asarray(rows, np.float32)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--res-path", required=True)
    p.add_argument("--data", required=True,
                   help="CSV of num_features feature columns + 1 label")
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--iterations", type=int, required=True)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--seed", type=int, default=23)
    p.add_argument("--checkpoint-every", type=int, default=8)
    p.add_argument("--keep-checkpoints", type=int, default=64)
    p.add_argument("--n-devices", type=int, default=None,
                   help="tenant-mesh size (shrinks across respawns)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--print-every", type=int, default=0)
    p.add_argument("--step-delay-s", type=float, default=0.0)
    p.add_argument("--preempt-signals", default="SIGTERM",
                   help='guard signals ("" disables; exit 75 protocol)')
    p.add_argument("--device-lost-signal", default="SIGUSR1",
                   help='simulated hardware loss ("" disables; exit '
                        f"{EXIT_DEVICE_LOST})")
    args = p.parse_args(argv)

    from gan_deeplearning4j_tpu.models import mlpgan_insurance as M
    from gan_deeplearning4j_tpu.testing.chaos import DeviceLostError
    from gan_deeplearning4j_tpu.train import fleet as fleet_lib
    from gan_deeplearning4j_tpu.train.preemption import (
        EXIT_PREEMPTED,
        PreemptionError,
    )

    width = M.InsuranceConfig().num_features + 1
    data = read_csv_tolerant(args.data, width)
    feats, labels = data[:, :-1], data[:, -1]

    cfg = fleet_lib.FleetConfig(
        num_tenants=args.tenants,
        num_iterations=args.iterations,
        batch_size=args.batch_size,
        seed=args.seed,
        res_path=args.res_path,
        per_tenant_data=True,
        print_every=args.print_every,
        checkpoint_every=args.checkpoint_every,
        keep_checkpoints=args.keep_checkpoints,
        n_devices=args.n_devices,
        events=True,
        resume=args.resume,
        preempt_signals=args.preempt_signals or None,
    )
    trainer = fleet_lib.FleetTrainer(cfg)

    # device-lost seam: the signal only LATCHES here; the raise happens
    # at the next step boundary through fleet._chaos_step_hook, so the
    # "hardware loss" lands where a real one would be observed — at a
    # dispatch edge, not mid-handler
    lost = {"armed": False}
    if args.device_lost_signal:
        signum = getattr(signal, args.device_lost_signal)
        signal.signal(signum, lambda s, f: lost.update(armed=True))
    delay = max(0.0, float(args.step_delay_s))

    # readiness marker: the orchestrator must not fire the device-lost
    # signal while this process is still importing (the default SIGUSR1
    # action would kill it outright) — READY.json names the pid whose
    # handler is armed, and the runner gates the injection on it
    ready_tmp = os.path.join(args.res_path, "READY.json.tmp")
    with open(ready_tmp, "w") as f:
        json.dump({"pid": os.getpid()}, f)
    os.replace(ready_tmp, os.path.join(args.res_path, "READY.json"))

    def _hook(step: int) -> None:
        if lost["armed"]:
            raise DeviceLostError(
                f"injected device loss at step {step} "
                f"({args.device_lost_signal})")
        if delay:
            time.sleep(delay)

    fleet_lib._chaos_step_hook = _hook
    try:
        out = trainer.train(feats, labels)
    except PreemptionError as e:
        # PREEMPTED.json + the emergency checkpoint are already on
        # disk (train/fleet._preempt_drain); report, exit 75
        print(json.dumps({"preempted": True, "step": e.step,
                          "checkpoint": e.checkpoint}))
        return EXIT_PREEMPTED
    except DeviceLostError as e:
        # no emergency save, on purpose: lost hardware does not flush
        print(json.dumps({"device_lost": True,
                          "step": trainer.batch_counter,
                          "reason": str(e)}))
        return EXIT_DEVICE_LOST
    finally:
        fleet_lib._chaos_step_hook = None

    losses = trainer.last_losses
    final = {
        "step": int(out["steps"]),
        "tenants": int(out["tenants"]),
        "quarantined": int(out["quarantined"]),
        "d_loss": (None if losses is None
                   else float(np.mean(np.asarray(losses[0])))),
        "g_loss": (None if losses is None
                   else float(np.mean(np.asarray(losses[1])))),
    }
    tmp = os.path.join(args.res_path, FINAL_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(final, f)
    os.replace(tmp, os.path.join(args.res_path, FINAL_NAME))
    print(json.dumps(final))
    return 0


def cli() -> None:
    from gan_deeplearning4j_tpu.runtime import backend as _backend

    _backend.apply_env_platform()
    sys.exit(main())


if __name__ == "__main__":
    cli()
