"""Serving plane — the continuous-batching generation service.

``parallel/inference.py`` proves ONE bucketed sharded dispatch; this
package turns it into a service: an admission-controlled request queue
(``admission.py``) drained by a dedicated dispatch thread
(``engine.py``) that coalesces concurrent requests into the next
bucketed dispatch, an open-loop Poisson load harness (``loadgen.py``)
that measures p50/p95/p99 and saturation throughput (``bench
--serve``, docs/SERVING.md), and the network front door: an HTTP
gateway (``gateway.py``) over a health-aware replica/tenant router
(``router.py``) with a retrying reference client (``client.py``) —
``bench --serve --gateway``, docs/SERVING.md "Network front door".

Above the single process sits the mesh tier: replicas as standalone
PROCESSES (``replica.py``), a router over their HTTP surfaces with
typed ejection and bounded re-probe (``mesh.py``), and the
self-healing control plane (``controlplane.py``) — autoscaling with
hysteresis, dead-replica replacement, and budgeted canary/promote/
rollback weight deployments — docs/SERVING.md "Mesh and control
plane".
"""

from gan_deeplearning4j_tpu.serve.admission import (
    AdmissionQueue,
    Request,
    ShedError,
)
from gan_deeplearning4j_tpu.serve.client import (
    GatewayClient,
    GatewayHTTPError,
)
from gan_deeplearning4j_tpu.serve.controlplane import (
    Autoscaler,
    CanaryDeployment,
    ControlPlane,
    DeploymentRollbackError,
    ReplicaLauncher,
    ReplicaProcess,
    ReplicaSpawnError,
)
from gan_deeplearning4j_tpu.serve.engine import DispatchError, ServeEngine
from gan_deeplearning4j_tpu.serve.gateway import Gateway, TokenBucket
from gan_deeplearning4j_tpu.serve.loadgen import (
    measure_saturation,
    percentiles,
    run_load,
    run_socket_load,
    z_inputs,
)
from gan_deeplearning4j_tpu.serve.mesh import (
    MeshRouter,
    RemoteReplica,
    ReplicaProbeError,
)
from gan_deeplearning4j_tpu.serve.publisher import (
    CheckpointPublisher,
    finite_params_probe,
)
from gan_deeplearning4j_tpu.serve.router import (
    FleetTenantBank,
    NoHealthyReplicaError,
    Router,
    TenantThrottledError,
)

__all__ = [
    "AdmissionQueue",
    "Autoscaler",
    "CanaryDeployment",
    "CheckpointPublisher",
    "ControlPlane",
    "DeploymentRollbackError",
    "DispatchError",
    "FleetTenantBank",
    "Gateway",
    "GatewayClient",
    "GatewayHTTPError",
    "MeshRouter",
    "NoHealthyReplicaError",
    "RemoteReplica",
    "ReplicaLauncher",
    "ReplicaProbeError",
    "ReplicaProcess",
    "ReplicaSpawnError",
    "Request",
    "Router",
    "ServeEngine",
    "ShedError",
    "TenantThrottledError",
    "TokenBucket",
    "finite_params_probe",
    "measure_saturation",
    "percentiles",
    "run_load",
    "run_socket_load",
    "z_inputs",
]
