"""Serving plane — the continuous-batching generation service.

``parallel/inference.py`` proves ONE bucketed sharded dispatch; this
package turns it into a service: an admission-controlled request queue
(``admission.py``) drained by a dedicated dispatch thread
(``engine.py``) that coalesces concurrent requests into the next
bucketed dispatch, and an open-loop Poisson load harness
(``loadgen.py``) that measures p50/p95/p99 and saturation throughput
(``bench --serve``, docs/SERVING.md).
"""

from gan_deeplearning4j_tpu.serve.admission import (
    AdmissionQueue,
    Request,
    ShedError,
)
from gan_deeplearning4j_tpu.serve.engine import DispatchError, ServeEngine
from gan_deeplearning4j_tpu.serve.loadgen import (
    measure_saturation,
    percentiles,
    run_load,
    z_inputs,
)

__all__ = [
    "AdmissionQueue",
    "DispatchError",
    "Request",
    "ServeEngine",
    "ShedError",
    "measure_saturation",
    "percentiles",
    "run_load",
    "z_inputs",
]
