"""Admission control — the bounded front door of the serving plane.

An unbounded request queue converts overload into unbounded latency
(every admitted request waits behind everything before it) and
eventually into an OOM; the production behavior is to REJECT work the
service provably cannot finish inside its latency budget, loudly and
immediately, so the caller can retry elsewhere.  ``AdmissionQueue``
implements exactly that:

* **bounded depth** — more than ``max_depth`` queued requests is a shed
  regardless of rate (the backstop when no service rate is measured
  yet);
* **deadline budget** — once the dispatch loop has measured its service
  rate (a rows/sec EWMA fed by ``note_dispatch``), a request whose
  estimated queue wait ``(queued_rows + rows) / rate`` exceeds
  ``deadline_ms`` is shed on arrival: admitting it would only convert
  one fast failure into one guaranteed SLO miss.

A shed raises ``ShedError`` — a TYPED rejection carrying the depth,
the wait estimate, and the budget — and emits a ``serve.shed`` instant;
it never blocks.  The queue itself never blocks either: ``submit`` and
``drain`` are lock-and-go, and the engine's idle wait parks on the
``wake`` event OUTSIDE any lock (docs/STATIC_ANALYSIS.md, rule
lock-held-blocking-call).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.telemetry import events

# weight of the newest rows/sec sample in the service-rate EWMA: high
# enough to track a hot-swap or bucket-mix change within a few
# batches, low enough that one slow (compile-paying) dispatch does not
# flip the admission verdict
_RATE_ALPHA = 0.2


class ShedError(RuntimeError):
    """Typed load-shed rejection: the queue is past its depth bound or
    the estimated wait exceeds the deadline budget.  Carries the
    numbers so callers (and tests) can tell WHICH bound tripped."""

    def __init__(self, message: str, *, depth: int,
                 est_wait_ms: Optional[float], budget_ms: float):
        super().__init__(message)
        self.depth = depth
        self.est_wait_ms = est_wait_ms
        self.budget_ms = budget_ms


class Request:
    """One generation request: host-side inputs in, a ``done`` event
    and either ``outputs`` or a typed ``error`` out.  No lock — the
    dispatch thread owns every mutable field until ``done.set()``, the
    submitter only reads after ``done`` (the event IS the barrier)."""

    __slots__ = ("xs", "rows", "done", "outputs", "error",
                 "t_submit", "t_done", "trace")

    def __init__(self, xs: Tuple, trace=None):
        self.xs = tuple(np.asarray(x) for x in xs)
        if not self.xs:
            raise ValueError("a request needs at least one input array")
        self.rows = int(self.xs[0].shape[0])
        if self.rows <= 0:
            raise ValueError("a request needs at least one row")
        self.done = threading.Event()
        self.outputs = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_done: Optional[float] = None
        # opaque TraceContext (telemetry/tracing.py) or None; when set,
        # the engine decomposes this request into trace.* stage spans
        self.trace = trace

    def result(self, timeout: Optional[float] = None) -> List:
        """Block (bounded) for completion; return the output arrays or
        raise the typed error the engine attached."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"request ({self.rows} rows) not served within "
                f"{timeout}s — see /healthz and gan4j_serve_* for why")
        if self.error is not None:
            raise self.error
        return self.outputs

    @property
    def latency_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1000.0


class AdmissionQueue:
    """Bounded FIFO with deadline-budget load shedding.

    ``max_depth``: hard cap on queued requests.  ``deadline_ms``: the
    latency budget — arrivals whose estimated queue wait exceeds it are
    shed once a service rate is measured.  ``wake`` is the engine's
    parking event: set on every admit, cleared when a drain empties the
    queue (the engine waits on it OUTSIDE any lock)."""

    def __init__(self, max_depth: int = 256,
                 deadline_ms: float = 1000.0):
        if max_depth <= 0:
            raise ValueError("max_depth must be > 0")
        if deadline_ms <= 0:
            raise ValueError("deadline_ms must be > 0")
        self.max_depth = int(max_depth)
        self.deadline_ms = float(deadline_ms)
        self.wake = threading.Event()
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._queued_rows = 0
        self._closed = False
        self._admitted_total = 0
        self._shed_total = 0
        self._rate_rows_per_s: Optional[float] = None

    # -- producer side (any thread) -------------------------------------------

    def submit(self, request: Request) -> Request:
        """Admit ``request`` or raise ``ShedError``.  Never blocks.
        Raises ``RuntimeError`` once the queue is closed — the check
        happens under the SAME lock as the enqueue, so a submit racing
        ``close()`` either lands before the shutdown sweep (and is
        failed by it) or raises; it can never slip in after the sweep
        and strand until the caller's ``result()`` timeout."""
        with self._lock:
            closed = self._closed
            depth = len(self._queue)
            rate = self._rate_rows_per_s
            est_wait_ms = None
            if rate is not None and rate > 0:
                est_wait_ms = ((self._queued_rows + request.rows)
                               / rate * 1000.0)
            if closed:
                reason = None
            elif depth >= self.max_depth:
                reason = (f"queue depth {depth} at the max_depth "
                          f"{self.max_depth} bound")
            elif est_wait_ms is not None \
                    and est_wait_ms > self.deadline_ms:
                reason = (f"estimated wait {est_wait_ms:.0f}ms exceeds "
                          f"the {self.deadline_ms:.0f}ms deadline "
                          f"budget at depth {depth}")
            else:
                self._queue.append(request)
                self._queued_rows += request.rows
                self._admitted_total += 1
                reason = None
            if reason is not None:
                self._shed_total += 1
                shed_total = self._shed_total
        if closed:
            # a closed queue is a STOPPED service, not load shedding:
            # raise the engine's "not running" error, don't count a shed
            raise RuntimeError(
                "admission queue is closed (serve engine stopped)")
        if reason is not None:
            # event + raise OUTSIDE the lock: the recorder may write
            events.instant("serve.shed", depth=depth, rows=request.rows,
                           est_wait_ms=est_wait_ms,
                           budget_ms=self.deadline_ms,
                           shed_total=shed_total)
            raise ShedError(f"request shed: {reason}", depth=depth,
                            est_wait_ms=est_wait_ms,
                            budget_ms=self.deadline_ms)
        self.wake.set()
        return request

    def close(self) -> None:
        """Refuse all further admits: post-close ``submit`` raises
        ``RuntimeError`` under the queue lock instead of enqueueing.
        ``ServeEngine.stop`` closes the queue BEFORE its ``fail_all``
        sweep so nothing can be admitted after the sweep and strand."""
        with self._lock:
            self._closed = True

    def reopen(self) -> None:
        """Accept admits again (an engine restart after ``stop``)."""
        with self._lock:
            self._closed = False

    # -- consumer side (the dispatch thread) -----------------------------------

    def drain(self, max_rows: int) -> List[Request]:
        """Pop queued requests FIFO up to ``max_rows`` total rows —
        requests are never split, and the FIRST one is always taken
        even when larger than ``max_rows`` (the oversized path chunks
        downstream in ``ParallelInference.output``).  Never blocks."""
        with self._lock:
            out: List[Request] = []
            rows = 0
            while self._queue and (
                    not out or rows + self._queue[0].rows <= max_rows):
                r = self._queue.popleft()
                out.append(r)
                rows += r.rows
            self._queued_rows -= rows
            if not self._queue:
                self.wake.clear()
        return out

    def note_dispatch(self, rows: int, seconds: float) -> None:
        """Feed one completed dispatch into the service-rate EWMA —
        the number the deadline-budget shed is computed from."""
        if seconds <= 0 or rows <= 0:
            return
        inst = rows / seconds
        with self._lock:
            prev = self._rate_rows_per_s
            self._rate_rows_per_s = (
                inst if prev is None
                else _RATE_ALPHA * inst + (1.0 - _RATE_ALPHA) * prev)

    def fail_all(self, error: BaseException) -> List[Request]:
        """Pop EVERY queued request and complete it with ``error`` —
        the shutdown / watchdog-timeout path ("never hang": a queued
        request always gets an answer).  Returns the failed requests."""
        with self._lock:
            taken = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            self.wake.clear()
        for r in taken:
            r.error = error
            r.done.set()
        return taken

    # -- introspection ---------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def report(self) -> Dict:
        with self._lock:
            return {"depth": len(self._queue),
                    "queued_rows": self._queued_rows,
                    "admitted_total": self._admitted_total,
                    "shed_total": self._shed_total,
                    "rate_rows_per_s": self._rate_rows_per_s,
                    "deadline_ms": self.deadline_ms,
                    "max_depth": self.max_depth}
