"""Retrying HTTP client for the serving gateway.

The gateway's error contract is TYPED at the wire level (429 shed with
``Retry-After``, 503 unavailable, 400 validation — serve/gateway.py),
and this client is the reference consumer of that contract: bounded
retries with exponential backoff-and-jitter on the RETRYABLE statuses
(429/503 — the two that mean "the service is alive but can't take this
request right now"), honoring the server's ``Retry-After`` hint when it
is larger than the computed backoff.  Everything else (400, 404, 413…)
is a caller bug or a routing miss and fails fast on the first answer.

Stdlib-only (``http.client``) with a BOUNDED keep-alive pool: the
gateway speaks HTTP/1.1 with a Content-Length on every reply, so a
connection survives across requests and the ~80ms+ per-request
connect cost (BENCH_GATEWAY_r09) is paid once, not per call.  A
pooled socket can be stale (server restarted, idle timeout) — the
first transport error on a REUSED connection gets exactly one typed
reconnect on a fresh socket (counted in ``reconnects_total``) before
the retry policy sees anything; fresh-socket failures propagate
immediately, so retry storms no longer amplify connection churn.
Jitter comes from a seeded ``random.Random`` so tests and the bench
are reproducible.

Wire formats (mirrors serve/gateway.py):

* JSON — request ``{"inputs": [[...], ...]}`` (one nested list per
  graph input), response ``{"outputs": [[...], ...]}``.  Values are
  float32; float32 -> JSON -> float32 is exact (every float32 is
  representable as a float64, and JSON round-trips float64 shortest
  repr), so JSON responses are BIT-EQUAL to the engine's outputs.
* npy — request body is ``np.save`` bytes of the single input array
  (``application/x-npy``), response is ``np.savez`` bytes
  (``application/x-npz``, keys ``out0..outN``) — bit-exact by
  construction.
"""

from __future__ import annotations

import io
import json
import random
import threading
import time
from collections import deque
from http.client import HTTPConnection, HTTPException
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gan_deeplearning4j_tpu.telemetry import events, tracing

# statuses worth retrying: the service is up but cannot take THIS
# request right now (shed / no healthy replica / engine restarting)
RETRYABLE_STATUSES = (429, 503)


class GatewayHTTPError(RuntimeError):
    """A non-200 gateway answer (after retries, for retryable
    statuses).  Carries the status code, the server's error payload,
    and the ``Retry-After`` hint so callers can classify without
    string-matching."""

    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None,
                 error_type: Optional[str] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = int(status)
        self.retry_after = retry_after
        self.error_type = error_type


def _encode_json(xs: Sequence[np.ndarray]) -> bytes:
    return json.dumps(
        {"inputs": [np.asarray(x).tolist() for x in xs]}
    ).encode("utf-8")


def _encode_npy(xs: Sequence[np.ndarray]) -> bytes:
    if len(xs) != 1:
        raise ValueError(
            "the npy wire format carries exactly one input array; "
            "multi-input graphs must use the JSON format")
    buf = io.BytesIO()
    np.save(buf, np.asarray(xs[0]), allow_pickle=False)
    return buf.getvalue()


def _decode_outputs(body: bytes, content_type: str) -> List[np.ndarray]:
    if content_type.startswith("application/x-npz"):
        with np.load(io.BytesIO(body), allow_pickle=False) as f:
            return [np.asarray(f[f"out{i}"]) for i in range(len(f.files))]
    payload = json.loads(body.decode("utf-8"))
    return [np.asarray(o, dtype=np.float32) for o in payload["outputs"]]


class GatewayClient:
    """Bounded-retry client over one gateway base address.

    ``retries``: extra attempts AFTER the first (0 = fail fast — the
    loadgen's shed-counting mode).  ``backoff_s`` doubles per attempt
    (times ``backoff_mult``) with multiplicative jitter in
    ``[1, 1+jitter]``; a server ``Retry-After`` overrides the computed
    backoff when larger.  ``seed`` makes the jitter reproducible.
    ``pool_size`` bounds the idle keep-alive pool (0 disables reuse);
    ``reused_total`` / ``reconnects_total`` count pool hits and typed
    stale-socket reconnects.  ``close()`` drains the pool."""

    def __init__(self, host: str, port: int, *,
                 retries: int = 3, backoff_s: float = 0.05,
                 backoff_mult: float = 2.0, jitter: float = 0.5,
                 timeout_s: float = 60.0, seed: int = 0,
                 pool_size: int = 4):
        if pool_size < 0:
            raise ValueError("pool_size must be >= 0")
        self.host = host
        self.port = int(port)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_mult = float(backoff_mult)
        self.jitter = float(jitter)
        self.timeout_s = float(timeout_s)
        self.pool_size = int(pool_size)
        self._rng = random.Random(seed)
        self._pool_lock = threading.Lock()
        self._idle: deque = deque()
        self._pool_closed = False
        self.retried_total = 0
        self.reused_total = 0
        self.reconnects_total = 0

    # -- connection pool -------------------------------------------------------

    def _checkout(self) -> Tuple[HTTPConnection, bool]:
        """Pop an idle keep-alive connection, else make a fresh one
        (``HTTPConnection`` connects lazily — no socket I/O here)."""
        with self._pool_lock:
            if self._idle:
                self.reused_total += 1
                return self._idle.popleft(), True
        return (HTTPConnection(self.host, self.port,
                               timeout=self.timeout_s), False)

    def _checkin(self, conn: HTTPConnection) -> None:
        """Return a healthy connection to the pool, or close it when
        the pool is full/closed (the close happens OUTSIDE the lock)."""
        surplus = None
        with self._pool_lock:
            if self._pool_closed or len(self._idle) >= self.pool_size:
                surplus = conn
            else:
                self._idle.append(conn)
        if surplus is not None:
            surplus.close()

    def close(self) -> None:
        """Close every pooled connection and refuse further pooling
        (requests still work — they just run connection-per-call)."""
        with self._pool_lock:
            self._pool_closed = True
            taken = list(self._idle)
            self._idle.clear()
        for conn in taken:
            conn.close()

    def __enter__(self) -> "GatewayClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- low-level -------------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[bytes],
                 content_type: Optional[str], trace=None,
                 attempt: int = 0):
        headers = {}
        if content_type is not None:
            headers["Content-Type"] = content_type
        if trace is not None:
            headers[tracing.TRACE_HEADER] = tracing.to_header(trace)
        conn, reused = self._checkout()
        t_send = t_recv = None
        try:
            try:
                t_send = time.perf_counter()
                conn.request(method, path, body=body, headers=headers)
                t_recv = time.perf_counter()
                resp = conn.getresponse()
                data = resp.read()
            except (ConnectionError, HTTPException, OSError):
                conn.close()
                if not reused:
                    raise
                # a pooled socket can be stale (server restarted, idle
                # timeout): exactly ONE typed reconnect on a fresh
                # socket; a second failure is a real transport error
                # and propagates to the retry policy
                with self._pool_lock:
                    self.reconnects_total += 1
                conn = HTTPConnection(self.host, self.port,
                                      timeout=self.timeout_s)
                reused = False
                t_send = time.perf_counter()
                conn.request(method, path, body=body, headers=headers)
                t_recv = time.perf_counter()
                resp = conn.getresponse()
                data = resp.read()
        except BaseException:
            conn.close()
            raise
        t_done = time.perf_counter()
        if resp.will_close:
            conn.close()
        else:
            self._checkin(conn)
        if trace is not None:
            # one send + one recv span per wire attempt, children of
            # the caller's span — the client side of the wire gap
            events.complete("trace.wire_send", dur=t_recv - t_send,
                            t_start=t_send, trace=trace.trace,
                            span=tracing.new_span_id(),
                            parent=trace.span, attempt=attempt)
            events.complete("trace.wire_recv", dur=t_done - t_recv,
                            t_start=t_recv, trace=trace.trace,
                            span=tracing.new_span_id(),
                            parent=trace.span, attempt=attempt,
                            status=resp.status)
        return (resp.status, dict(resp.getheaders()), data)

    def _raise(self, status: int, headers: Dict, data: bytes) -> None:
        retry_after = None
        ra = headers.get("Retry-After")
        if ra is not None:
            try:
                retry_after = float(ra)
            except ValueError:
                retry_after = None
        message, error_type = data.decode("utf-8", "replace"), None
        try:
            payload = json.loads(message)
            message = payload.get("error", message)
            error_type = payload.get("type")
        except ValueError:  # gan4j-lint: disable=swallowed-exception — a non-JSON error body is still an error body: the raw text goes into the raised GatewayHTTPError below
            pass
        raise GatewayHTTPError(status, message, retry_after=retry_after,
                               error_type=error_type)

    def _with_retries(self, method: str, path: str,
                      body: Optional[bytes],
                      content_type: Optional[str], trace=None):
        backoff = self.backoff_s
        attempt = 0
        while True:
            try:
                status, headers, data = self._request(
                    method, path, body, content_type,
                    trace=trace, attempt=attempt)
            except (ConnectionError, HTTPException, OSError):
                # transport-level failure (reset, refused mid-restart):
                # retry on the same schedule as a 503
                if attempt >= self.retries:
                    raise
                status, headers, data = None, {}, b""
            if status is not None:
                if status == 200:
                    return headers, data
                if (status not in RETRYABLE_STATUSES
                        or attempt >= self.retries):
                    self._raise(status, headers, data)
            wait = backoff * (1.0 + self.jitter * self._rng.random())
            ra = headers.get("Retry-After")
            if ra is not None:
                try:
                    # the server's hint is authoritative when LARGER:
                    # retrying earlier than it asks just buys a 429
                    wait = max(wait, float(ra))
                except ValueError:  # gan4j-lint: disable=swallowed-exception — a malformed Retry-After is the server's bug, not a reason to stop retrying: the computed backoff stands
                    pass
            time.sleep(wait)
            backoff *= self.backoff_mult
            attempt += 1
            with self._pool_lock:
                self.retried_total += 1

    # -- API -------------------------------------------------------------------

    def generate(self, xs: Sequence[np.ndarray], *,
                 tenant: Optional[str] = None,
                 encoding: str = "json",
                 trace=None) -> List[np.ndarray]:
        """POST one generation request; returns the output arrays.
        ``tenant`` targets ``/v1/tenants/{tenant}/generate`` (the
        fleet-sliced model); without it the request load-balances
        across the replica set.  Raises ``GatewayHTTPError`` on a
        non-200 answer after retries.

        Tracing: with ``trace=None`` the client is the FIRST hop and
        mints a root ``trace.client`` span (the whole call, retries
        included); a caller-supplied ``tracing.TraceContext`` (the
        mesh's per-hop context) is propagated instead, without a new
        root.  Either way the context rides the ``X-Gan4j-Trace``
        header and each wire attempt records send/recv spans."""
        if encoding == "json":
            body, ctype = _encode_json(xs), "application/json"
        elif encoding == "npy":
            body, ctype = _encode_npy(xs), "application/x-npy"
        else:
            raise ValueError(f"unknown encoding {encoding!r} "
                             "(expected 'json' or 'npy')")
        path = ("/v1/generate" if tenant is None
                else f"/v1/tenants/{tenant}/generate")
        if trace is not None:
            headers, data = self._with_retries("POST", path, body,
                                               ctype, trace=trace)
            return _decode_outputs(data,
                                   headers.get("Content-Type", ""))
        ctx = tracing.mint()
        with events.span("trace.client", trace=ctx.trace,
                         span=ctx.span, path=path):
            headers, data = self._with_retries("POST", path, body,
                                               ctype, trace=ctx)
            return _decode_outputs(data,
                                   headers.get("Content-Type", ""))

    def healthz(self) -> Dict:
        """GET the gateway's own /healthz block (any status — health is
        a read, not a retryable mutation)."""
        status, _, data = self._request("GET", "/healthz", None, None)
        payload = json.loads(data.decode("utf-8"))
        payload["_status"] = status
        return payload

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_client`` (the
        ``gan4j_client_*`` series): the keep-alive pool's counters,
        read under the pool lock."""
        with self._pool_lock:
            return {"reused_total": self.reused_total,
                    "reconnects_total": self.reconnects_total,
                    "retried_total": self.retried_total,
                    "pool_idle": len(self._idle)}
