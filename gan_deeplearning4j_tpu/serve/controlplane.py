"""The self-healing control plane over the serving mesh.

serve/replica.py gives us replicas as PROCESSES and serve/mesh.py
routes over them; this module closes the loop so the fleet manages
itself.  One named control thread (``gan4j-controlplane``) ticks
three concerns:

1. **self-heal** — a replica process that died (SIGKILL, OOM, crash)
   is removed from the mesh and a replacement is spawned; the mesh's
   ejection already drained its traffic to the survivors.
2. **autoscale** — ``Autoscaler`` turns the mesh's probe aggregate
   (queue-depth sum, p99 max, shed trend) into +1/-1/0 decisions with
   hysteresis: ``up_after`` consecutive hot ticks before growing,
   ``down_after`` idle ticks before shrinking, a cooldown after every
   action, hard ``min/max`` bounds — a noisy metric trace must NOT
   flap the fleet.
3. **deploy** — ``deploy(directory)`` runs the rolling weight
   rollout: hotswap ONE canary replica, hold it under live traffic
   for ``hold_ticks`` SLO-clean probes (finite outputs, no error
   growth, probe latency within ``p99_factor`` of the pre-swap
   baseline), then promote fleet-wide — or auto-rollback the canary
   to the pre-deploy step on any regression.  Every rollback charges
   a ``RollbackManager`` budget keyed to PROMOTED progress, so a
   persistently poisoned checkpoint exhausts the budget and becomes a
   typed fatal (``DeploymentRollbackError``) instead of an infinite
   canary/rollback flap.

Lock discipline (rule lock-held-blocking-call): the control-plane
lock guards counters and the deployment record ONLY — every spawn,
SIGTERM/SIGKILL, probe, and admin call runs outside it.  A tick that
throws is counted and recorded, never silently lost, and never kills
the loop.
"""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gan_deeplearning4j_tpu.serve.client import GatewayHTTPError
from gan_deeplearning4j_tpu.serve.mesh import (
    MeshRouter,
    RemoteReplica,
    ReplicaProbeError,
)
from gan_deeplearning4j_tpu.telemetry import events
from gan_deeplearning4j_tpu.train.rollback import (
    RollbackError,
    RollbackManager,
)


class ReplicaSpawnError(RuntimeError):
    """The replica subprocess did not produce its ready line — it
    exited, closed stdout, or ran past the ready deadline.  Carries
    the log path so the post-mortem is one ``cat`` away."""

    def __init__(self, message: str, *, log_path: Optional[str] = None):
        super().__init__(message)
        self.log_path = log_path


class DeploymentRollbackError(RollbackError):
    """The deployment budget is exhausted: every canary of this
    checkpoint rolled back and no promote advanced the fleet — the
    checkpoint is POISONED and a human must look.  Typed fatal: the
    control plane refuses further deploys until the budget owner
    decides."""


class ReplicaProcess:
    """One spawned replica subprocess: the Popen handle, the
    host/port its ready line declared, and its log path.  No lock —
    the control thread owns it; ``alive()`` is a poll."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int,
                 log_path: str):
        self.proc = proc
        self.host = host
        self.port = int(port)
        self.log_path = log_path

    @property
    def name(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL + bounded reap (a zombie holds the pid table)."""
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:  # gan4j-lint: disable=swallowed-exception — a SIGKILLed child that cannot be reaped within 10s is the kernel's problem, not a hang we can fix by waiting longer; the poll()-based alive() keeps reporting it
            pass

    def stop(self, timeout_s: float = 10.0) -> None:
        """SIGTERM (the replica's drain path), bounded wait, then
        SIGKILL — retirement must terminate either way."""
        if self.proc.poll() is None:
            self.proc.terminate()
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.kill()


class ReplicaLauncher:
    """Factory for replica subprocesses.

    ``spawn()`` runs ``python -m gan_deeplearning4j_tpu.serve.replica
    --port 0 ...``, waits (bounded) for the ready line on the child's
    stdout to learn the REAL port, then hands the remaining stdout to
    a named daemon pump thread appending into the per-replica log
    (stderr writes there directly).  ``checkpoint`` is the directory
    new replicas boot from (the stable weights — NOT a canary
    directory); ``env`` overrides land on top of the parent's."""

    def __init__(self, *, checkpoint: Optional[str] = None,
                 buckets: Sequence[int] = (8, 32, 64),
                 log_dir: str = ".", host: str = "127.0.0.1",
                 ready_timeout_s: float = 120.0,
                 env: Optional[Dict[str, str]] = None,
                 events_dir: Optional[str] = None,
                 args: Sequence[str] = ()):
        self.checkpoint = checkpoint
        self.buckets = tuple(int(b) for b in buckets)
        self.log_dir = log_dir
        self.host = host
        self.ready_timeout_s = float(ready_timeout_s)
        self.env = dict(env or {})
        # extra CLI args EVERY spawn gets (e.g. ``--fleet``/
        # ``--fleet-tenants N``) — unlike per-spawn ``extra_args``,
        # these survive the control plane's heal/scale respawns
        self.args = tuple(str(a) for a in args)
        # when set, each replica writes its own events timeline there
        # (``replica_{seq}.events.jsonl``) — the per-process files
        # telemetry.tracing.merge_trace_files joins into one
        # cross-process trace view
        self.events_dir = events_dir
        self._seq = 0

    def _read_ready_line(self, proc: subprocess.Popen,
                         log_path: str) -> Dict:
        """Bounded read of the first stdout line (select-polled so a
        wedged child cannot hang the spawner)."""
        deadline = time.monotonic() + self.ready_timeout_s
        buf = b""
        while b"\n" not in buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._reap(proc)
                raise ReplicaSpawnError(
                    f"replica pid {proc.pid} produced no ready line "
                    f"within {self.ready_timeout_s:.0f}s",
                    log_path=log_path)
            ready, _, _ = select.select([proc.stdout], [], [],
                                        min(remaining, 0.25))
            if not ready:
                if proc.poll() is not None:
                    raise ReplicaSpawnError(
                        f"replica pid {proc.pid} exited rc="
                        f"{proc.returncode} before its ready line",
                        log_path=log_path)
                continue
            chunk = proc.stdout.read1(4096)
            if not chunk:
                self._reap(proc)
                raise ReplicaSpawnError(
                    f"replica pid {proc.pid} closed stdout before "
                    f"its ready line (rc={proc.poll()})",
                    log_path=log_path)
            buf += chunk
        line = buf.split(b"\n", 1)[0]
        try:
            info = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            self._reap(proc)
            raise ReplicaSpawnError(
                f"replica pid {proc.pid} ready line is not JSON "
                f"({e}): {line[:200]!r}", log_path=log_path) from None
        if info.get("event") != "replica_ready" or "port" not in info:
            self._reap(proc)
            raise ReplicaSpawnError(
                f"replica pid {proc.pid} ready line malformed: "
                f"{info!r}", log_path=log_path)
        return info

    @staticmethod
    def _reap(proc: subprocess.Popen) -> None:
        if proc.poll() is None:
            proc.kill()
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:  # gan4j-lint: disable=swallowed-exception — a SIGKILLed child that cannot be reaped in 10s is beyond a spawner's power; the raised ReplicaSpawnError already carries the diagnosis
            pass

    def spawn(self, *, checkpoint: Optional[str] = None,
              extra_args: Sequence[str] = ()) -> ReplicaProcess:
        """Spawn one replica; returns once it is serving.  Raises
        ``ReplicaSpawnError`` (typed, log path attached) otherwise."""
        self._seq += 1
        seq = self._seq
        log_path = os.path.join(self.log_dir, f"replica_{seq}.log")
        ckpt = checkpoint if checkpoint is not None else self.checkpoint
        cmd = [sys.executable, "-m",
               "gan_deeplearning4j_tpu.serve.replica",
               "--port", "0", "--host", self.host,
               "--buckets", ",".join(str(b) for b in self.buckets)]
        if ckpt:
            cmd += ["--checkpoint", str(ckpt)]
        if self.events_dir:
            cmd += ["--events", os.path.join(
                self.events_dir, f"replica_{seq}.events.jsonl")]
        cmd += list(self.args) + list(extra_args)
        env = dict(os.environ)
        env.update(self.env)
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                    stderr=log_f, env=env)
        info = self._read_ready_line(proc, log_path)
        pump = threading.Thread(
            target=self._pump_stdout, args=(proc, log_path),
            name=f"gan4j-replica-pump-{seq}", daemon=True)
        pump.start()
        events.instant("controlplane.replica_spawned",
                       pid=proc.pid, port=int(info["port"]),
                       log=log_path)
        return ReplicaProcess(proc, self.host, int(info["port"]),
                              log_path)

    @staticmethod
    def _pump_stdout(proc: subprocess.Popen, log_path: str) -> None:
        with open(log_path, "ab") as f:
            for chunk in iter(lambda: proc.stdout.read1(4096), b""):
                f.write(chunk)
                f.flush()


class Autoscaler:
    """Pure hysteresis: metrics aggregate in, +1/-1/0 out.  No locks,
    no I/O — the control thread is its only caller, and the unit
    tests drive it with synthetic traces.

    Hot = queue depth, p99, OR the shed delta since the last tick at
    or past its ``up_*`` threshold; ``up_after`` consecutive hot
    ticks grow the fleet.  Idle = depth 0, no sheds, p99 under
    ``down_p99_ms``; ``down_after`` consecutive idle ticks shrink it.
    Any action arms ``cooldown_ticks`` of forced no-ops and resets
    both streaks; bounds always win."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 up_queue_depth: float = 4.0, up_p99_ms: float = 500.0,
                 up_shed_delta: int = 1, up_after: int = 2,
                 down_p99_ms: Optional[float] = None,
                 down_after: int = 10, cooldown_ticks: int = 4):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_depth = float(up_queue_depth)
        self.up_p99_ms = float(up_p99_ms)
        self.up_shed_delta = int(up_shed_delta)
        self.up_after = int(up_after)
        self.down_p99_ms = (float(up_p99_ms) / 4.0
                            if down_p99_ms is None
                            else float(down_p99_ms))
        self.down_after = int(down_after)
        self.cooldown_ticks = int(cooldown_ticks)
        self._hot_streak = 0
        self._idle_streak = 0
        self._cooldown = 0
        self._last_shed: Optional[int] = None

    def tick(self, metrics: Dict, n_replicas: int) -> int:
        """Feed one aggregate (keys ``queue_depth``/``p99_ms``/
        ``shed_total``); returns the scale decision."""
        depth = float(metrics.get("queue_depth") or 0)
        p99 = float(metrics.get("p99_ms") or 0.0)
        shed = int(metrics.get("shed_total") or 0)
        shed_delta = (0 if self._last_shed is None
                      else max(0, shed - self._last_shed))
        self._last_shed = shed
        hot = (depth >= self.up_queue_depth
               or p99 >= self.up_p99_ms
               or shed_delta >= self.up_shed_delta)
        idle = (depth <= 0 and shed_delta == 0
                and p99 <= self.down_p99_ms)
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._idle_streak = self._idle_streak + 1 if idle else 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return 0
        if (self._hot_streak >= self.up_after
                and n_replicas < self.max_replicas):
            self._hot_streak = 0
            self._idle_streak = 0
            self._cooldown = self.cooldown_ticks
            return 1
        if (self._idle_streak >= self.down_after
                and n_replicas > self.min_replicas):
            self._hot_streak = 0
            self._idle_streak = 0
            self._cooldown = self.cooldown_ticks
            return -1
        return 0


class CanaryDeployment:
    """Pure per-deploy state machine: one SLO probe observation in,
    ``hold`` / ``promote`` / ``rollback`` out.

    Clean = finite outputs, no typed-error growth, probe latency
    within ``max(p99_floor_ms, baseline * p99_factor)``.
    ``hold_ticks`` consecutive clean observations promote; ONE dirty
    observation rolls back (a canary exists to be paranoid — the
    budget, not the window, is what bounds flapping)."""

    def __init__(self, directory: str, step: int, *,
                 baseline_ms: Optional[float],
                 hold_ticks: int = 3, p99_factor: float = 3.0,
                 p99_floor_ms: float = 250.0):
        self.directory = directory
        self.step = int(step)
        self.baseline_ms = baseline_ms
        self.hold_ticks = int(hold_ticks)
        self.p99_factor = float(p99_factor)
        self.p99_floor_ms = float(p99_floor_ms)
        self.clean = 0
        self.state = "canary"
        self.reason: Optional[str] = None

    def _bound_ms(self) -> Optional[float]:
        if self.baseline_ms is None:
            return None
        return max(self.p99_floor_ms,
                   self.baseline_ms * self.p99_factor)

    def observe(self, *, probe_ms: Optional[float], finite: bool,
                errors_delta: int = 0,
                failure: Optional[str] = None) -> str:
        """One observation of the canary under live traffic."""
        if self.state != "canary":
            return self.state
        dirty = failure
        if dirty is None and not finite:
            dirty = "non-finite outputs from the canary weights"
        if dirty is None and errors_delta > 0:
            dirty = (f"typed error count grew by {errors_delta} "
                     f"under the canary")
        bound = self._bound_ms()
        if dirty is None and probe_ms is not None \
                and bound is not None and probe_ms > bound:
            dirty = (f"probe latency {probe_ms:.0f}ms exceeds the "
                     f"{bound:.0f}ms SLO bound "
                     f"(baseline {self.baseline_ms:.0f}ms x "
                     f"{self.p99_factor:g})")
        if dirty is not None:
            self.state = "rolled_back"
            self.reason = dirty
            return "rollback"
        self.clean += 1
        if self.clean >= self.hold_ticks:
            self.state = "promoted"
            return "promote"
        return "hold"


class ControlPlane:
    """Owns the launcher, the mesh, the autoscaler, and the deploy
    budget; runs the tick loop on its named thread.  ``start()``
    spawns up to ``min_replicas`` before returning, so a started
    control plane is a SERVING control plane."""

    def __init__(self, launcher: ReplicaLauncher, *,
                 mesh: Optional[MeshRouter] = None,
                 autoscaler: Optional[Autoscaler] = None,
                 tick_s: float = 0.5,
                 hold_ticks: int = 3, p99_factor: float = 3.0,
                 p99_floor_ms: float = 250.0,
                 max_rollbacks: int = 2,
                 probe_rows: int = 4, probe_timeout_s: float = 30.0):
        self.launcher = launcher
        self.mesh = mesh if mesh is not None else MeshRouter()
        self.autoscaler = autoscaler if autoscaler is not None \
            else Autoscaler()
        self.tick_s = float(tick_s)
        self.hold_ticks = int(hold_ticks)
        self.p99_factor = float(p99_factor)
        self.p99_floor_ms = float(p99_floor_ms)
        self.probe_rows = int(probe_rows)
        self.probe_timeout_s = float(probe_timeout_s)
        self._budget = RollbackManager(max_rollbacks=max_rollbacks)
        self._lock = threading.Lock()
        self._procs: Dict[str, ReplicaProcess] = {}
        self._canary_name: Optional[str] = None
        self._canary: Optional[CanaryDeployment] = None
        self._pending_deploy: Optional[Tuple[str, Optional[int]]] = None
        self._deploy_state: Dict = {"state": "idle"}
        self._fatal: Optional[str] = None
        self._scale_up_total = 0
        self._scale_down_total = 0
        self._replaced_total = 0
        self._rollbacks_total = 0
        self._promoted_total = 0
        self._deploy_failed_total = 0
        self._tick_errors_total = 0
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ControlPlane":
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("control plane already started")
        # reach min_replicas BEFORE the loop starts: a started
        # control plane is a serving one.  Bounded attempts — a
        # persistently failing spawn is a typed error, not a hang.
        attempts = 0
        while len(self.mesh.names()) < self.autoscaler.min_replicas:
            if attempts >= 2 * self.autoscaler.min_replicas:
                raise ReplicaSpawnError(
                    f"could not reach min_replicas="
                    f"{self.autoscaler.min_replicas} after "
                    f"{attempts} spawn attempts (see replica logs "
                    f"in {self.launcher.log_dir})")
            attempts += 1
            self._spawn_one()
        thread = threading.Thread(
            target=self._run, name="gan4j-controlplane", daemon=True)
        with self._lock:
            self._thread = thread
        thread.start()
        events.instant("controlplane.start",
                       replicas=len(self.mesh.names()))
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            thread, self._thread = self._thread, None
            procs = list(self._procs.values())
            self._procs = {}
        if thread is not None:
            thread.join(timeout=30.0)
        for p in procs:
            self.mesh.remove(p.name)
            p.stop()
        events.instant("controlplane.stop")

    def __enter__(self) -> "ControlPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- public API ------------------------------------------------------------

    def deploy(self, directory: str,
               step: Optional[int] = None) -> None:
        """Queue a rolling deployment of ``directory`` (picked up on
        the next tick).  ``step`` pins the EXACT checkpoint to canary
        (the publisher's contract: the step it verified is the step
        that deploys — "newest verified" could silently pick up a
        younger save it never probed).  Raises
        ``DeploymentRollbackError`` once the budget is exhausted,
        ``RuntimeError`` while another deploy is still in flight."""
        with self._lock:
            if self._fatal is not None:
                raise DeploymentRollbackError(self._fatal)
            busy = (self._pending_deploy is not None
                    or self._canary is not None)
            if busy:
                raise RuntimeError(
                    "a deployment is already in flight; wait for "
                    "deployment_status() to settle")
            self._pending_deploy = (
                str(directory), None if step is None else int(step))
            self._deploy_state = {"state": "pending",
                                  "directory": str(directory)}
            if step is not None:
                self._deploy_state["step"] = int(step)

    def deployment_status(self) -> Dict:
        with self._lock:
            return dict(self._deploy_state)

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_controlplane``
        (the ``gan4j_controlplane_*`` series and the ``/healthz``
        controlplane block)."""
        with self._lock:
            out = {
                "replicas": len(self._procs),
                "scale_up_total": self._scale_up_total,
                "scale_down_total": self._scale_down_total,
                "replaced_total": self._replaced_total,
                "rollbacks_total": self._rollbacks_total,
                "promoted_total": self._promoted_total,
                "deploy_failed_total": self._deploy_failed_total,
                "tick_errors_total": self._tick_errors_total,
                "deploy_state": self._deploy_state.get("state"),
                "fatal": self._fatal,
                "ok": self._fatal is None,
            }
        return out

    def replica_names(self) -> List[str]:
        with self._lock:
            return list(self._procs)

    def process(self, name: str) -> Optional[ReplicaProcess]:
        """The live ``ReplicaProcess`` behind ``name`` (the chaos
        harness surface — ``kill_replica_process`` takes this)."""
        with self._lock:
            return self._procs.get(name)

    # -- the tick loop ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop_evt.wait(self.tick_s):
            try:
                self._tick()
            except Exception as e:
                # a broken tick is COUNTED and recorded, never lost,
                # and never kills the loop — the next tick retries
                with self._lock:
                    self._tick_errors_total += 1
                events.instant("controlplane.tick_error",
                               error=repr(e))

    def _tick(self) -> None:
        self._heal()
        if self._stop_evt.is_set():
            return
        agg = self.mesh.poll()
        self._autoscale(agg)
        if self._stop_evt.is_set():
            return
        self._advance_deploy()

    # -- self-heal -------------------------------------------------------------

    def _heal(self) -> None:
        with self._lock:
            dead = [(name, p) for name, p in self._procs.items()
                    if not p.alive()]
        for name, proc in dead:
            with self._lock:
                self._procs.pop(name, None)
                self._replaced_total += 1
                canary_died = (self._canary_name == name
                               and self._canary is not None)
            self.mesh.remove(name)
            events.instant("controlplane.replica_replaced",
                           replica=name, pid=proc.pid,
                           rc=proc.proc.returncode)
            if canary_died:
                self._finish_rollback(
                    "canary replica process died mid-hold",
                    environmental=True)
            self._spawn_one()

    def _spawn_one(self) -> Optional[ReplicaProcess]:
        try:
            proc = self.launcher.spawn()
        except ReplicaSpawnError as e:
            events.instant("controlplane.spawn_failed", error=str(e),
                           log=e.log_path)
            return None
        self.mesh.add(RemoteReplica(proc.host, proc.port))
        with self._lock:
            self._procs[proc.name] = proc
        return proc

    # -- autoscale -------------------------------------------------------------

    def _autoscale(self, agg: Dict) -> None:
        with self._lock:
            n = len(self._procs)
        delta = self.autoscaler.tick(agg, n)
        if delta > 0:
            proc = self._spawn_one()
            if proc is not None:
                with self._lock:
                    self._scale_up_total += 1
                events.instant("controlplane.scale_up",
                               replica=proc.name,
                               queue_depth=agg.get("queue_depth"),
                               p99_ms=agg.get("p99_ms"))
        elif delta < 0:
            victim = self._pick_retire_victim()
            if victim is None:
                return
            with self._lock:
                proc = self._procs.pop(victim, None)
                self._scale_down_total += 1
            self.mesh.remove(victim)
            if proc is not None:
                proc.stop()
            events.instant("controlplane.scale_down", replica=victim)

    def _pick_retire_victim(self) -> Optional[str]:
        """Newest non-canary replica (dict order = spawn order)."""
        with self._lock:
            names = [n for n in self._procs
                     if n != self._canary_name]
        return names[-1] if names else None

    # -- deployment ------------------------------------------------------------

    def _probe_canary(self, replica: RemoteReplica
                      ) -> Tuple[Optional[float], bool,
                                 Optional[str]]:
        """One live-traffic SLO probe: a small real generate.
        Returns ``(latency_ms, finite, typed_failure)``."""
        xs = [np.zeros((self.probe_rows, 2), np.float32)]
        t0 = time.perf_counter()
        try:
            outs = replica.generate(xs)
        except (GatewayHTTPError, ReplicaProbeError, OSError) as e:
            return None, True, f"canary probe failed: {e}"
        ms = (time.perf_counter() - t0) * 1000.0
        finite = all(bool(np.isfinite(np.asarray(o)).all())
                     for o in outs)
        return ms, finite, None

    def _advance_deploy(self) -> None:
        with self._lock:
            pending = self._pending_deploy
            self._pending_deploy = None
            canary = self._canary
            canary_name = self._canary_name
        if pending is not None and canary is None:
            self._start_canary(*pending)
            return
        if canary is None:
            return
        replica = self.mesh.get(canary_name) \
            if canary_name is not None else None
        if replica is None:
            self._finish_rollback("canary replica left the mesh",
                                  environmental=True)
            return
        probe_ms, finite, failure = self._probe_canary(replica)
        errors_delta = 0
        verdict = canary.observe(probe_ms=probe_ms, finite=finite,
                                 errors_delta=errors_delta,
                                 failure=failure)
        events.instant("controlplane.canary_observe",
                       verdict=verdict, probe_ms=probe_ms,
                       finite=finite, failure=failure)
        if verdict == "promote":
            self._finish_promote(canary)
        elif verdict == "rollback":
            environmental = False
            if failure is not None:
                # the probe never got an answer out of the canary
                # (connection reset, refused, timeout).  That refutes
                # the WEIGHTS only if the process behind it is still
                # standing; if it died under us (chaos, preemption)
                # the wire error is just the death seen from the
                # client side — same environmental verdict as the
                # canary-died scan path
                proc = self.process(canary_name) \
                    if canary_name is not None else None
                environmental = proc is None or not proc.alive()
            self._finish_rollback(canary.reason or "slo regression",
                                  environmental=environmental)

    def _start_canary(self, directory: str,
                      step: Optional[int] = None) -> None:
        names = self.mesh.names()
        replica = None
        for name in names:
            replica = self.mesh.get(name)
            if replica is not None:
                break
        if replica is None:
            with self._lock:
                self._deploy_failed_total += 1
                self._deploy_state = {
                    "state": "failed", "directory": directory,
                    "reason": "no replica available to canary"}
            return
        baseline_ms, _, fail = self._probe_canary(replica)
        if fail is not None:
            baseline_ms = None
        body = {"directory": directory}
        if step is not None:
            body["step"] = int(step)
        try:
            result = replica.admin("hotswap", body)
        except (GatewayHTTPError, ReplicaProbeError, OSError) as e:
            with self._lock:
                self._deploy_failed_total += 1
                self._deploy_state = {
                    "state": "failed", "directory": directory,
                    "reason": f"canary hotswap failed: {e}"}
            events.instant("controlplane.deploy_failed",
                           directory=directory, reason=str(e))
            return
        step = int(result["step"])
        canary = CanaryDeployment(
            directory, step, baseline_ms=baseline_ms,
            hold_ticks=self.hold_ticks, p99_factor=self.p99_factor,
            p99_floor_ms=self.p99_floor_ms)
        with self._lock:
            self._canary = canary
            self._canary_name = replica.name
            self._deploy_state = {"state": "canary",
                                  "directory": directory,
                                  "step": step,
                                  "replica": replica.name}
        events.instant("controlplane.canary_start",
                       directory=directory, step=step,
                       replica=replica.name,
                       baseline_ms=baseline_ms)

    def _finish_promote(self, canary: CanaryDeployment) -> None:
        with self._lock:
            canary_name = self._canary_name
        failures = []
        for name in self.mesh.names():
            if name == canary_name:
                continue
            replica = self.mesh.get(name)
            if replica is None:
                continue
            try:
                replica.admin("hotswap",
                              {"directory": canary.directory,
                               "max_step": canary.step})
            except (GatewayHTTPError, ReplicaProbeError,
                    OSError) as e:
                failures.append(f"{name}: {e}")
        with self._lock:
            self._canary = None
            self._canary_name = None
            self._promoted_total += 1
            self._deploy_state = {
                "state": "promoted", "directory": canary.directory,
                "step": canary.step,
                "fleet_failures": list(failures)}
        events.instant("controlplane.promoted",
                       directory=canary.directory, step=canary.step,
                       fleet_failures=len(failures))

    def _finish_rollback(self, reason: str, *,
                         environmental: bool = False) -> None:
        """``environmental=True`` marks a rollback that says nothing
        about the ARTIFACT — the canary process died or left the mesh
        mid-hold (chaos, preemption, OOM) before the SLO probes could
        refute the weights.  The flag rides the deployment status so
        the publisher retries the step once the mesh heals instead of
        stickying weights that were never proven bad."""
        with self._lock:
            canary = self._canary
            canary_name = self._canary_name
            self._canary = None
            self._canary_name = None
            if canary is None:
                return
            self._rollbacks_total += 1
            # budget keyed to PROMOTED progress: repeated rollbacks
            # with no promote in between accumulate and exhaust; a
            # promote resets the window (the fleet is getting
            # somewhere, each incident taxes it once)
            progress = self._promoted_total
        ok = self._budget.request(progress, reason,
                                  bad_step=canary.step)
        replica = self.mesh.get(canary_name) \
            if canary_name is not None else None
        restored: Optional[int] = None
        if replica is not None:
            try:
                result = replica.admin(
                    "hotswap", {"directory": canary.directory,
                                "max_step": canary.step - 1})
                restored = int(result["step"])
            except (GatewayHTTPError, ReplicaProbeError,
                    OSError) as e:
                events.instant("controlplane.rollback_restore_failed",
                               replica=canary_name, error=str(e))
        events.instant("controlplane.rollback",
                       directory=canary.directory, step=canary.step,
                       restored_step=restored, reason=reason,
                       environmental=environmental, budget_ok=ok,
                       budget_attempts=self._budget.attempts)
        if ok:
            with self._lock:
                self._deploy_failed_total += 1
                self._deploy_state = {
                    "state": "rolled_back",
                    "directory": canary.directory,
                    "step": canary.step, "restored_step": restored,
                    "reason": reason,
                    "environmental": bool(environmental)}
            return
        fatal = (f"deployment rollback budget exhausted "
                 f"({self._budget.attempts} rollbacks, max "
                 f"{self._budget.max_rollbacks}) — {canary.directory}"
                 f" is persistently failing its canary: {reason}")
        with self._lock:
            self._deploy_failed_total += 1
            self._fatal = fatal
            self._deploy_state = {
                "state": "failed_fatal",
                "directory": canary.directory,
                "step": canary.step, "restored_step": restored,
                "reason": reason}
        events.instant("controlplane.deploy_fatal",
                       directory=canary.directory, reason=reason)
