"""The continuous-batching dispatch engine.

One dedicated thread (``gan4j-serve-dispatch``) drains the admission
queue each cycle, coalesces whatever arrived into ONE batch, pads it to
the smallest covering serving bucket (``parallel/inference.py`` —
the engine never invents a dispatch shape, so steady-state serving is
recompile-free by construction), and overlaps host work with device
work at pipeline depth 1: while batch N runs on the device, the loop
is already draining and coalescing batch N+1; N's outputs are fenced
and fanned back to their requests only after N+1 is dispatched.

Supervision: a ``HeartbeatWatchdog`` (train/watchdog.py) watches the
dispatch thread through the existing beat/region API.  A hang anywhere
in the cycle (a wedged dispatch, a dead device) becomes a
``WatchdogTimeout`` raised ON the dispatch thread; the loop fails every
in-flight and queued request with the typed error (never a silent
hang), re-arms a fresh watchdog, and keeps serving.  A cycle that
RAISES rather than hangs (a malformed coalesced batch that slipped past
submit validation, a device failure) fails that batch's in-flight
requests with a typed ``DispatchError`` and keeps serving — the
dispatch thread never dies while ``submit`` keeps admitting; if it
somehow still does, the exit path closes the admission queue and
answers everything outstanding, so ``running`` turning False and
"requests stop being accepted" happen together.

Weight hot-swap: ``refresh()`` flags the loop to re-snapshot the
graph's params (``ParallelInference.refresh_params``) between batches —
same shapes, same compiled programs, zero recompiles; ``hotswap_from``
first loads the newest VERIFIED checkpoint into the graph
(checkpoint/checkpointer.py) and then flags the refresh.

Ops surface: ``report()`` feeds ``MetricsRegistry.observe_serve`` (the
``gan4j_serve_*`` series and the ``/healthz`` serving block), every
dispatch is a ``serve.dispatch`` span and every shed a ``serve.shed``
instant (telemetry/events.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gan_deeplearning4j_tpu.parallel.inference import (
    DEFAULT_SERVING_BUCKETS,
    ParallelInference,
)
from gan_deeplearning4j_tpu.serve.admission import AdmissionQueue, Request
from gan_deeplearning4j_tpu.serve.loadgen import percentiles
from gan_deeplearning4j_tpu.telemetry import events, tracing
from gan_deeplearning4j_tpu.train.watchdog import (
    HeartbeatWatchdog,
    WatchdogTimeout,
)
from gan_deeplearning4j_tpu.utils.device import (
    device_fence,
    overlap_device_get,
)

# fault-injection seam (testing/chaos.py hang_at_dispatch): called at
# the top of every batch dispatch so a chaos test can simulate a
# dispatch that never completes — the serving-plane hang class the
# watchdog converts into typed request failures.  None in production.
_chaos_dispatch_hook: Optional[Callable[[], None]] = None

# one in-flight batch: (requests, per-segment output arrays still on
# device, dispatch-start time, real rows, padded device rows, stage
# timings — the per-stage perf_counter durations trace spans are cut
# from when any request in the batch carries a trace context)
_Batch = Tuple[List[Request], List[List], float, int, int, Dict]


class DispatchError(RuntimeError):
    """A dispatch cycle raised on the serving thread (malformed
    coalesced batch, device failure) — NOT a hang.  Attached to every
    in-flight request of the failed batch as its typed answer; queued
    requests are untouched and the engine keeps serving (the blast
    radius of a poison batch is that batch).  The original exception is
    chained as ``__cause__``."""


def _array_trailing(spec) -> Tuple[int, ...]:
    """The trailing (non-batch) shape of the HOST array a graph input
    spec expects: flat inputs arrive flattened (``_forward`` reshapes
    ``cnn_flat`` to NCHW itself), everything else arrives as the spec's
    declared shape."""
    if spec.kind == "cnn_flat":
        h, w, c = spec.shape
        return (h * w * c,)
    return tuple(spec.shape)


class ServeEngine:
    """Continuous-batching generation service over one
    ``ParallelInference`` dispatch.

    ``graph``: the generator ``ComputationGraph`` to serve.
    ``buckets``: the closed dispatch-shape set (defaults to
    ``DEFAULT_SERVING_BUCKETS`` — the gan4j-prove ``serving_infer``
    contract shapes).  ``admission``: the bounded front door (default:
    an ``AdmissionQueue()``).  ``watchdog_deadline_s``: explicit hang
    deadline for the dispatch loop (None = the watchdog's auto-scaled
    deadline); ``supervise=False`` disables the watchdog entirely
    (single-threaded tests)."""

    def __init__(self, graph=None, mesh=None,
                 buckets: Sequence[int] = DEFAULT_SERVING_BUCKETS,
                 admission: Optional[AdmissionQueue] = None,
                 supervise: bool = True,
                 watchdog_deadline_s: Optional[float] = None,
                 idle_poll_s: float = 0.01,
                 latency_window: int = 4096,
                 infer: Optional[ParallelInference] = None):
        if infer is not None:
            if infer.buckets is None:
                raise ValueError(
                    "the engine needs a bucketed ParallelInference — "
                    "an unbucketed one has no closed dispatch-shape "
                    "set to serve from")
            self._infer = infer
            graph = infer.graph
        else:
            if graph is None:
                raise ValueError("ServeEngine needs a graph or a "
                                 "prebuilt ParallelInference")
            self._infer = ParallelInference(graph, mesh=mesh,
                                            buckets=buckets)
        self.admission = admission if admission is not None \
            else AdmissionQueue()
        self._supervise = bool(supervise)
        self._wd_deadline_s = watchdog_deadline_s
        self._idle_poll_s = float(idle_poll_s)
        self._max_rows = self._infer.buckets[-1]
        self._n_inputs = len(graph.input_names)
        self._input_names = list(graph.input_names)
        # per-input admission contract: the trailing (non-batch) array
        # shape from the graph's InputSpec and the served dtype
        # (float32 — the stack's parameter dtype — until warmup
        # captures the real one from its examples).  submit() rejects
        # a mismatch BEFORE admission: one tenant's malformed request
        # must fail that tenant's call, never reach the shared
        # dispatch thread's coalescing (where parts[0]'s shape/dtype
        # would be assumed for the whole batch) — and a novel
        # dtype/trailing shape would also mint a novel compile shape,
        # breaking the closed-program-set contract.
        self._input_trailing: List[Optional[Tuple[int, ...]]] = []
        self._input_dtypes: List[np.dtype] = []
        specs = getattr(graph, "input_specs", {}) or {}
        for name in self._input_names:
            spec = specs.get(name)
            self._input_trailing.append(
                None if spec is None else _array_trailing(spec))
            self._input_dtypes.append(np.dtype(np.float32))
        self._lock = threading.Lock()
        # the swap lock serializes host-side param mutation (a
        # checkpoint restore on a caller thread) against the dispatch
        # thread's re-snapshot; it nests with NOTHING (engine lock,
        # admission lock and swap lock are pairwise disjoint —
        # docs/STATIC_ANALYSIS.md, rule lock-order-cycle)
        self._swap_lock = threading.Lock()
        self._stop = threading.Event()
        self._refresh = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[HeartbeatWatchdog] = None
        self._open: List[Request] = []
        self._latencies: deque = deque(maxlen=int(latency_window))
        self._fills: deque = deque(maxlen=256)
        self._requests_total = 0
        self._batches_total = 0
        self._timeouts_total = 0
        self._errors_total = 0

    # -- producer API (any thread) ---------------------------------------------

    def submit(self, *xs, trace=None) -> Request:
        """Enqueue one generation request; returns the ``Request`` (its
        ``result()`` blocks for the outputs).  Raises ``ValueError``
        when the inputs don't match the served graph's input spec
        (count, trailing shape, dtype — rejected BEFORE admission so a
        malformed request can never poison the shared coalesced batch
        or mint a novel compile shape), ``ShedError`` when admission
        control rejects it, ``RuntimeError`` when the engine is not
        running (a dead engine must never accept work it can't
        finish).

        ``trace``: optional ``tracing.TraceContext`` — when set, the
        dispatch loop decomposes this request into ``trace.*`` stage
        spans (queue wait, coalesce, bucket pad, dispatch, readback)
        parented under it.  Untraced requests record nothing extra."""
        if not self.running:
            raise RuntimeError("serve engine is not running")
        req = Request(xs, trace=trace)
        self._validate(req)
        return self.admission.submit(req)

    def _validate(self, req: Request) -> None:
        if len(req.xs) != self._n_inputs:
            raise ValueError(
                f"request carries {len(req.xs)} input(s); the served "
                f"graph takes {self._n_inputs}")
        for i, x in enumerate(req.xs):
            want = self._input_trailing[i]
            if want is not None and tuple(x.shape[1:]) != want:
                raise ValueError(
                    f"input {i} ({self._input_names[i]!r}): trailing "
                    f"shape {tuple(x.shape[1:])} does not match the "
                    f"served graph's expected {want}")
            dt = self._input_dtypes[i]
            if np.dtype(x.dtype) != dt:
                raise ValueError(
                    f"input {i} ({self._input_names[i]!r}): dtype "
                    f"{np.dtype(x.dtype)} does not match the served "
                    f"{dt} — a novel dtype would be a novel compile "
                    f"shape")

    def generate(self, *xs, timeout: Optional[float] = 60.0) -> List:
        """Synchronous convenience: submit + bounded wait."""
        return self.submit(*xs).result(timeout=timeout)

    def refresh(self) -> None:
        """Flag a zero-recompile weight re-snapshot: the dispatch loop
        runs ``refresh_params`` between batches (same shapes, same
        compiled programs)."""
        self._refresh.set()

    def hotswap_from(self, directory: str, name: str = "gen", *,
                     step: Optional[int] = None,
                     max_step: Optional[int] = None) -> int:
        """Load the newest VERIFIED checkpoint under ``directory`` into
        the served graph, then flag the refresh.  Returns the restored
        step.

        A corrupt/unverifiable newest checkpoint is SKIPPED — with a
        ``serve.hotswap_rejected`` event naming the step and why — and
        the walk falls back to the newest verified one, so a torn save
        landing mid-swap degrades the swap to the previous weights
        instead of failing it.  Raises ``NoVerifiedCheckpointError``
        when nothing verifiable exists (the engine keeps serving the
        old weights).  Structure mismatches (``ValueError``) are a
        caller bug, not corruption, and always propagate.

        ``step``: explicit pin — verification failure raises
        ``CheckpointCorruptError``, no silent substitution (the
        checkpointer's explicit-step contract).  ``max_step``: bound
        the newest-first walk (the control plane's rollback path
        restores strictly at-or-below the last known-good step)."""
        from gan_deeplearning4j_tpu.checkpoint.checkpointer import (
            NoVerifiedCheckpointError,
            TrainCheckpointer,
        )

        # read-side handle: this engine only OBSERVES the trainer's
        # directory; sweeping would tear an in-flight save's tmp dir
        ckpt = TrainCheckpointer(directory, sweep_debris=False)
        if step is not None:
            with self._swap_lock:
                # target_mesh=None: a serving host loads onto ITSELF —
                # checkpoints written on a bigger training mesh reshard
                # down to this host's single device instead of refusing
                got, _ = ckpt.restore({name: self._infer.graph},
                                      step=step, target_mesh=None)
            self.refresh()
            events.instant("serve.hotswap", step=got,
                           directory=directory)
            return got
        candidates = ckpt.steps()
        if max_step is not None:
            candidates = [s for s in candidates if s <= max_step]
        for s in reversed(candidates):
            # verify OUTSIDE the swap lock (sha256 over every file);
            # only the in-place load itself excludes the dispatch
            # loop's re-snapshot
            if not ckpt.verify(s):
                events.instant("serve.hotswap_rejected", step=s,
                               directory=directory,
                               reason="fails manifest verification "
                                      "(torn or corrupt)")
                continue
            try:
                with self._swap_lock:
                    got, _ = ckpt.restore({name: self._infer.graph},
                                          step=s, target_mesh=None)
            except ValueError:
                raise  # structure mismatch: fatal, not corruption
            except Exception as e:  # unreadable despite the manifest
                events.instant("serve.hotswap_rejected", step=s,
                               directory=directory,
                               reason=f"failed to load: {e!r}")
                continue
            self.refresh()
            events.instant("serve.hotswap", step=got,
                           directory=directory)
            return got
        raise NoVerifiedCheckpointError(
            f"no VERIFIED checkpoint in {directory}"
            + (f" at or below step {max_step}"
               if max_step is not None else "")
            + f" (candidates: {candidates})")

    def hotswap_params(self, params) -> None:
        """Swap an already-materialized parameter tree into the served
        graph (same structure, same shapes), then flag the refresh.
        This is the in-memory sibling of ``hotswap_from`` for callers
        that restore weights themselves — ``FleetTenantBank`` restores
        a whole fleet once and pushes each tenant's slice here —
        keeping the engine object (and every router holding it)
        stable across the swap."""
        with self._swap_lock:
            self._infer.graph.params = params
        self.refresh()

    # -- lifecycle -------------------------------------------------------------

    def warmup(self, *example_xs) -> None:
        """Compile every bucket shape before taking traffic: one
        dispatch per declared bucket with zero-filled inputs shaped
        like ``example_xs`` (any row count; only trailing dims and
        dtypes matter).  After this, steady-state serving pays zero
        compiles (the RecompileSentinel-pinned contract)."""
        if len(example_xs) != self._n_inputs:
            raise ValueError(
                f"warmup needs {self._n_inputs} example input(s)")
        examples = [np.asarray(x) for x in example_xs]
        trailing = list(self._input_trailing)
        dtypes = list(self._input_dtypes)
        for i, x in enumerate(examples):
            want = trailing[i]
            if want is not None and tuple(x.shape[1:]) != want:
                raise ValueError(
                    f"warmup example {i} ({self._input_names[i]!r}): "
                    f"trailing shape {tuple(x.shape[1:])} does not "
                    f"match the graph's input spec {want}")
            trailing[i] = tuple(x.shape[1:])
            dtypes[i] = np.dtype(x.dtype)
        # the warmed shapes/dtypes ARE the compiled-program set: they
        # become the admission contract submit() enforces
        with self._lock:
            self._input_trailing = trailing
            self._input_dtypes = dtypes
        outs = None
        for b in self._infer.buckets:
            xs = [np.zeros((b,) + tuple(x.shape[1:]), dtype=x.dtype)
                  for x in examples]
            outs = self._infer.output(*xs)
        if outs is not None:
            device_fence(outs)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    @property
    def stalled(self) -> bool:
        """True while the dispatch loop is past its watchdog deadline
        (the ``/healthz`` serve block's failure condition).  Cheap —
        one lock and a flag read, no percentile math — so the router's
        per-request health probe can call it on the hot path."""
        with self._lock:
            wd = self._watchdog
        return bool(wd is not None and wd.stalled)

    def start(self) -> "ServeEngine":
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("serve engine already started")
            self._stop.clear()
            thread = threading.Thread(
                target=self._loop, name="gan4j-serve-dispatch",
                daemon=True)
            self._thread = thread
        self.admission.reopen()  # a restart after stop() serves again
        thread.start()
        self._arm_watchdog(thread)
        return self

    def stop(self) -> None:
        """Stop the dispatch loop (bounded join) and fail anything
        still queued with a typed error — a stopped engine answers
        every outstanding request, it never strands one.  The
        admission queue is closed FIRST (under its own lock), so a
        submit racing this method either lands before the fail_all
        sweep (and is failed by it) or raises — it can never enqueue
        after the sweep and strand until the caller's timeout."""
        self._stop.set()
        self.admission.close()
        self.admission.wake.set()  # break the idle park
        with self._lock:
            thread, self._thread = self._thread, None
            wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.stop()
        if thread is not None:
            thread.join(timeout=30.0)
        err = RuntimeError("serve engine stopped")
        self.admission.fail_all(err)
        with self._lock:
            leftovers, self._open = self._open, []
        for r in leftovers:
            if not r.done.is_set():
                r.error = err
                r.done.set()

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the dispatch loop (gan4j-serve-dispatch thread) -----------------------

    def _loop(self) -> None:
        try:
            self._serve()
        finally:
            if not self._stop.is_set():
                # the dispatch thread is dying OUTSIDE an orderly
                # stop() — an async watchdog raise escaped even the
                # recovery shield.  A dead engine must never keep
                # admitting work nothing will serve: close the front
                # door, answer everything outstanding, and drop the
                # thread handle so ``running`` turns False.
                err = RuntimeError(
                    "serve dispatch thread died unexpectedly — the "
                    "engine is stopped; outstanding requests failed "
                    "with this typed error")
                self.admission.close()
                with self._lock:
                    open_reqs, self._open = self._open, []
                    if self._thread is threading.current_thread():
                        self._thread = None
                for r in open_reqs:
                    if not r.done.is_set():
                        r.error = err
                        r.done.set()
                self.admission.fail_all(err)

    def _serve(self) -> None:
        pending: Optional[_Batch] = None
        cycle = 0
        while not self._stop.is_set():
            try:
                try:
                    wd = self._wd()
                    if wd is not None:
                        wd.beat()
                    if self._refresh.is_set():
                        self._refresh.clear()
                        with self._swap_lock:
                            self._infer.refresh_params()
                    reqs = self.admission.drain(self._max_rows)
                    inflight: Optional[_Batch] = None
                    if reqs:
                        with self._lock:
                            self._open.extend(reqs)
                        inflight = self._dispatch(reqs, wd)
                    # pipeline depth 1: batch N+1 is already on the
                    # device before batch N's outputs are fenced and
                    # fanned out
                    if pending is not None:
                        self._complete(pending, wd)
                    pending = inflight
                    if reqs or pending is not None:
                        cycle += 1
                        if wd is not None:
                            wd.beat(step=cycle)
                    else:
                        self.admission.wake.wait(self._idle_poll_s)
                except WatchdogTimeout:
                    pending = None
                    self._on_timeout()
                except Exception as e:
                    pending = None
                    self._on_error(e)
            except BaseException:
                # async-raise lands at ANY bytecode boundary, so a
                # second WatchdogTimeout can hit INSIDE the recovery
                # handlers above (not just inside _on_timeout, which
                # the old code guarded).  The first delivery is
                # already being handled: finish the recovery
                # best-effort — every open/queued request answered,
                # watchdog re-armed — and keep serving; the dispatch
                # thread dying is the one unacceptable outcome.
                pending = None
                try:
                    self._on_timeout()
                except BaseException:  # gan4j-lint: disable=swallowed-exception — last-resort shield, see above
                    pass
        # orderly exit: the batch already on the device completes;
        # stop() fails whatever is still queued
        if pending is not None:
            self._complete(pending, None)

    def _wd(self) -> Optional[HeartbeatWatchdog]:
        with self._lock:
            return self._watchdog

    def _plan(self, rows: int) -> List[int]:
        """The bucket segments a ``rows``-row batch dispatches as —
        the same policy as ``ParallelInference.output`` (one covering
        bucket, or largest-bucket chunks with the tail covered),
        computed HOST-side so the engine can pad in numpy and every
        device program is exactly a declared bucket forward (zero
        eager-op compiles, no matter what row counts traffic
        coalesces into)."""
        bucket = self._infer.bucket_for(rows)
        if bucket is not None:
            return [bucket]
        chunk = self._infer.buckets[-1]
        segments: List[int] = []
        for lo in range(0, rows, chunk):
            n = min(chunk, rows - lo)
            segments.append(self._infer.bucket_for(n) or chunk)
        return segments

    def _dispatch(self, reqs: List[Request],
                  wd: Optional[HeartbeatWatchdog]) -> _Batch:
        hook = _chaos_dispatch_hook
        t_drained = time.perf_counter()
        rows = sum(r.rows for r in reqs)
        segments = self._plan(rows)
        padded = sum(segments)
        region = wd.region("dispatch") if wd is not None \
            else nullcontext()
        with region, events.span("serve.dispatch",
                                 requests=len(reqs), rows=rows,
                                 padded=padded,
                                 segments=len(segments)):
            if hook is not None:
                hook()
            t0 = time.perf_counter()
            # coalesce + pad in HOST numpy: the device only ever sees
            # exact bucket shapes, so the compiled-program set is the
            # warmed bucket forwards and nothing else
            pad_s = 0.0
            xs = []
            for i in range(self._n_inputs):
                parts = [r.xs[i] for r in reqs]
                if padded > rows:
                    tp = time.perf_counter()
                    parts.append(np.zeros(
                        (padded - rows,) + parts[0].shape[1:],
                        dtype=parts[0].dtype))
                    pad_s += time.perf_counter() - tp
                xs.append(parts[0] if len(parts) == 1
                          else np.concatenate(parts))
            t_coalesced = time.perf_counter()
            outs: List[List] = []
            lo = 0
            for seg in segments:
                outs.append(self._infer.output(
                    *[x[lo:lo + seg] for x in xs]))
                lo += seg
            t_dispatched = time.perf_counter()
        stages = {"t_drained": t_drained,
                  "coalesce_s": (t_coalesced - t0) - pad_s,
                  "bucket_pad_s": pad_s,
                  "t_infer": t_coalesced,
                  "dispatch_s": t_dispatched - t_coalesced}
        return (reqs, outs, t0, rows, padded, stages)

    def _complete(self, batch: _Batch,
                  wd: Optional[HeartbeatWatchdog]) -> None:
        reqs, seg_outs, t0, rows, padded, stages = batch
        region = wd.region("readback") if wd is not None \
            else nullcontext()
        t_fence = time.perf_counter()
        with region:
            # the fence IS the materialization: one overlapped readback
            # of every segment's outputs; responses are then sliced in
            # numpy (no per-request device ops, no compile shapes)
            host_segs = overlap_device_get(seg_outs)
        t_fenced = time.perf_counter()
        full = (host_segs[0] if len(host_segs) == 1
                else [np.concatenate([seg[i] for seg in host_segs])
                      for i in range(len(host_segs[0]))])
        now = time.perf_counter()
        lo = 0
        for r in reqs:
            r.outputs = [o[lo:lo + r.rows] for o in full]
            lo += r.rows
            r.t_done = now
            r.done.set()
        self.admission.note_dispatch(rows, now - t0)
        with self._lock:
            self._requests_total += len(reqs)
            self._batches_total += 1
            self._fills.append(rows / padded)
            for r in reqs:
                self._latencies.append((now - r.t_submit) * 1000.0)
            del self._open[:len(reqs)]
        # trace stage spans for traced requests — emitted OUTSIDE every
        # lock (rule lock-held-blocking-call: the recorder may write),
        # and only when a trace context rode in, so the untraced hot
        # path (run_load straight into submit) records nothing extra
        for r in reqs:
            if r.trace is not None:
                self._emit_trace(r, rows, stages, t_fence,
                                 t_fenced - t_fence)

    def _emit_trace(self, r: Request, batch_rows: int, stages: Dict,
                    t_fence: float, readback_s: float) -> None:
        """Cut one traced request's stage spans from the batch's
        timings: queue wait is per-request, the rest are the batch's
        shared stages (continuous batching — the batch IS the unit of
        work, so its stage costs are every member's stage costs)."""
        ctx = r.trace
        base = {"trace": ctx.trace, "parent": ctx.span,
                "rows": r.rows, "batch_rows": batch_rows}
        events.complete("trace.queue_wait",
                        dur=stages["t_drained"] - r.t_submit,
                        t_start=r.t_submit,
                        span=tracing.new_span_id(), **base)
        events.complete("trace.coalesce", dur=stages["coalesce_s"],
                        t_start=stages["t_drained"],
                        span=tracing.new_span_id(), **base)
        events.complete("trace.bucket_pad", dur=stages["bucket_pad_s"],
                        t_start=stages["t_drained"],
                        span=tracing.new_span_id(), **base)
        events.complete("trace.dispatch", dur=stages["dispatch_s"],
                        t_start=stages["t_infer"],
                        span=tracing.new_span_id(), **base)
        events.complete("trace.readback", dur=readback_s,
                        t_start=t_fence,
                        span=tracing.new_span_id(), **base)

    # -- hang recovery ---------------------------------------------------------

    def _on_timeout(self) -> None:
        """The dispatch loop hung past the watchdog deadline: fail
        every in-flight and queued request with the typed error (the
        never-hang contract — a request always gets an answer), re-arm
        a fresh watchdog, keep serving."""
        self._disarm_watchdog()
        err = WatchdogTimeout(
            "serving dispatch hung past the watchdog deadline; "
            "in-flight and queued requests failed (see the "
            "serve.timeout event and gan4j_serve_* series)")
        with self._lock:
            open_reqs, self._open = self._open, []
            self._timeouts_total += 1
            thread = self._thread
        now = time.perf_counter()
        for r in open_reqs:
            if r.done.is_set():  # answered before the cycle fell over
                continue
            r.error = err
            r.t_done = now
            r.done.set()
        failed_queued = self.admission.fail_all(err)
        events.instant("serve.timeout", failed_inflight=len(open_reqs),
                       failed_queued=len(failed_queued))
        self._arm_watchdog(thread)

    def _on_error(self, exc: Exception) -> None:
        """A dispatch cycle RAISED (malformed coalesced batch that
        bypassed submit validation, a device error) — not a hang, so
        the watchdog stays armed.  Fail every in-flight request with a
        typed ``DispatchError`` and keep serving; queued requests are
        untouched (they dispatch next cycle — the blast radius of a
        poison batch is that batch).  The dispatch thread never dies
        silently while ``submit`` keeps admitting."""
        err = DispatchError(
            f"serving dispatch failed: {exc!r} — this batch's "
            "in-flight requests failed with the typed error; the "
            "engine keeps serving (see the serve.error event)")
        err.__cause__ = exc
        with self._lock:
            open_reqs, self._open = self._open, []
            self._errors_total += 1
        now = time.perf_counter()
        for r in open_reqs:
            if r.done.is_set():  # answered before the cycle fell over
                continue
            r.error = err
            r.t_done = now
            r.done.set()
        events.instant("serve.error", error=repr(exc),
                       failed_inflight=len(open_reqs))

    def _disarm_watchdog(self) -> None:
        with self._lock:
            wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.stop()  # no further async raises after this returns

    def _arm_watchdog(self,
                      thread: Optional[threading.Thread]) -> None:
        if not self._supervise or thread is None:
            return
        wd = HeartbeatWatchdog(deadline_s=self._wd_deadline_s)
        wd.start(thread=thread)
        with self._lock:
            self._watchdog = wd

    # -- ops surface -----------------------------------------------------------

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_serve`` (the
        ``gan4j_serve_*`` series and the ``/healthz`` serving block)."""
        adm = self.admission.report()
        with self._lock:
            lats = list(self._latencies)
            fills = list(self._fills)
            requests_total = self._requests_total
            batches_total = self._batches_total
            timeouts_total = self._timeouts_total
            errors_total = self._errors_total
            wd = self._watchdog
        p50, p95, p99 = percentiles(lats, (50.0, 95.0, 99.0))
        stalled = bool(wd is not None and wd.stalled)
        return {
            "requests_total": requests_total,
            "batches_total": batches_total,
            "shed_total": adm["shed_total"],
            "admitted_total": adm["admitted_total"],
            "queue_depth": adm["depth"],
            "batch_fill": (sum(fills) / len(fills)) if fills else 0.0,
            "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
            "timeouts_total": timeouts_total,
            "errors_total": errors_total,
            "rate_rows_per_s": adm["rate_rows_per_s"],
            "stalled": stalled,
            "ok": not stalled,
        }
