"""The HTTP front door of the serving plane.

Until a request can arrive over a socket, none of the admission,
shedding, or SLO machinery is reachable by an actual user.  ``Gateway``
closes that gap with the same stdlib ``ThreadingHTTPServer`` pattern
already proven in telemetry/exporter.py — no new dependencies, every
thread named, bounded shutdown — layered over a ``Router``
(serve/router.py) that balances replicas and routes fleet tenants.

Endpoints:

* ``POST /v1/generate`` — balanced across the replica set.
* ``POST /v1/tenants/{id}/generate`` — the tenant's own fleet-sliced
  model.
* ``GET /healthz`` — the gateway block (200 when the router has a
  healthy replica, 503 otherwise).  With a ``serve_report`` hook
  configured (the replica process), the reply carries a ``serve``
  block too and the status folds it in — the mesh probe's one-GET
  health read (serve/mesh.py).
* ``POST /admin/{name}`` — operator verbs registered via the
  ``admin`` hook dict (the replica process registers ``hotswap`` and
  ``chaos/wedge``): JSON params in, JSON result out, the same typed
  status mapping (400 validation / 404 unknown / 503 failed).

Error contract (the typed engine failures mapped to the wire):

* validation (bad JSON/npy, wrong shape/dtype/row count, oversized
  declared or actual body) → **400** (or **413** for an oversized
  body — rejected from the Content-Length header, BEFORE reading);
* unknown route / unknown tenant → **404**; wrong method → **405**;
* body slower than the read deadline (slow-loris) → **408**;
* per-tenant token-bucket exhausted, or ``ShedError`` from admission →
  **429** with ``Retry-After``;
* ``DispatchError`` / ``WatchdogTimeout`` / stopped engine / no
  healthy replica → **503**;
* the gateway's own result wait expiring → **504**.

Blast-radius discipline: everything about a request is validated
BEFORE it can touch an engine — size from the headers, shape/dtype
from the decoded arrays (plus the engine's own submit validation) —
so one tenant's malformed or hostile request costs one connection
thread a bounded amount of time and nothing else.  The body read
enforces a TOTAL wall-clock deadline (``read_timeout_s``), not a
per-recv timeout: a slow-loris dripping one byte per interval keeps
every per-recv timer happy forever, but not the total.

Ops surface: ``report()`` feeds ``MetricsRegistry.observe_gateway``
(the ``gan4j_gateway_*`` series and the ``/healthz`` gateway block).
"""

from __future__ import annotations

import io
import itertools
import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.serve.admission import ShedError
from gan_deeplearning4j_tpu.serve.engine import DispatchError
from gan_deeplearning4j_tpu.serve.router import (
    NoHealthyReplicaError,
    Router,
    TenantThrottledError,
)
from gan_deeplearning4j_tpu.telemetry import events, tracing
from gan_deeplearning4j_tpu.train.watchdog import WatchdogTimeout

_GENERATE = "/v1/generate"
_TENANT_PREFIX = "/v1/tenants/"
_ADMIN_PREFIX = "/admin/"


class _SlowBody(Exception):
    """The request body did not arrive within the total read
    deadline (the slow-loris failure mode) — answered 408."""


class _Disconnect(Exception):
    """The peer vanished mid-body — nothing to answer, counted."""


class TokenBucket:
    """One tenant's rate allowance: ``capacity`` tokens refilled at
    ``refill_per_s``.  ``take`` is lock-free arithmetic (the caller —
    the gateway — serializes per-bucket access under its own lock) and
    returns the seconds until a token exists when empty — the 429's
    ``Retry-After``."""

    __slots__ = ("capacity", "refill_per_s", "tokens", "t_last")

    def __init__(self, capacity: float, refill_per_s: float):
        if capacity <= 0 or refill_per_s <= 0:
            raise ValueError("capacity and refill_per_s must be > 0")
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self.tokens = float(capacity)
        self.t_last = time.monotonic()

    def take(self, now: Optional[float] = None
             ) -> Tuple[bool, float]:
        if now is None:
            now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens
                          + (now - self.t_last) * self.refill_per_s)
        self.t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.refill_per_s


def _decode_json(body: bytes) -> List[np.ndarray]:
    try:
        payload = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"request body is not valid JSON: {e}") \
            from None
    if not isinstance(payload, dict) or "inputs" not in payload:
        raise ValueError('JSON body must be {"inputs": [...]}')
    inputs = payload["inputs"]
    if not isinstance(inputs, list) or not inputs:
        raise ValueError('"inputs" must be a non-empty list of arrays')
    out = []
    for i, v in enumerate(inputs):
        try:
            arr = np.asarray(v, dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise ValueError(f"inputs[{i}] is not numeric: {e}") \
                from None
        if arr.ndim < 1 or arr.size == 0:
            raise ValueError(f"inputs[{i}] must be a non-empty array")
        out.append(arr)
    return out


def _decode_npy(body: bytes) -> List[np.ndarray]:
    try:
        arr = np.load(io.BytesIO(body), allow_pickle=False)
    except (ValueError, OSError, EOFError) as e:
        raise ValueError(f"request body is not a valid .npy: {e}") \
            from None
    if arr.ndim < 1 or arr.size == 0:
        raise ValueError("npy input must be a non-empty array")
    return [arr]


def _encode_json(outs: List[np.ndarray]) -> Tuple[bytes, str]:
    body = json.dumps(
        {"outputs": [np.asarray(o).tolist() for o in outs]}
    ).encode("utf-8")
    return body, "application/json"


def _encode_npz(outs: List[np.ndarray]) -> Tuple[bytes, str]:
    buf = io.BytesIO()
    np.savez(buf, **{f"out{i}": np.asarray(o)
                     for i, o in enumerate(outs)})
    return buf.getvalue(), "application/x-npz"


class _Handler(BaseHTTPRequestHandler):
    server_version = "gan4j-gateway"
    protocol_version = "HTTP/1.1"

    # -- plumbing --------------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet: report() is the surface
        pass

    @property
    def gateway(self) -> "Gateway":
        return self.server.gateway  # type: ignore[attr-defined]

    def setup(self):
        super().setup()
        # bounds the HEADER read and any idle keep-alive gap; the body
        # read below enforces its own TOTAL deadline on top
        self.connection.settimeout(self.gateway.read_timeout_s)
        self.gateway._conn_delta(+1)

    def finish(self):
        try:
            super().finish()
        finally:
            self.gateway._conn_delta(-1)

    def _reply(self, status: int, body: bytes, content_type: str,
               headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            # EVERY traced reply — success AND typed error — echoes
            # the trace header, so a shed/timeout caller can still
            # find its request in the merged timeline
            headers = tuple(headers) + (
                (tracing.TRACE_HEADER, tracing.to_header(ctx)),)
            self._trace_status = status
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            # the peer hung up mid-write; there is no one left to
            # answer, only the connection to close
            self.close_connection = True

    def _reply_error(self, status: int, error_type: str, message: str,
                     retry_after: Optional[float] = None) -> None:
        self.gateway._count_rejected(status, error_type)
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            # terminal span for the rejected request: without it the
            # trace would end mid-tree and vanish from merged views
            events.instant("trace.reject", trace=ctx.trace,
                           span=tracing.new_span_id(),
                           parent=ctx.span, status=status,
                           type=error_type)
        headers: Tuple[Tuple[str, str], ...] = ()
        if retry_after is not None:
            # integral seconds, always >= 1: a 0s hint just converts
            # one 429 into an immediate second 429
            headers = (("Retry-After",
                        str(max(1, math.ceil(retry_after)))),)
        self._reply(status,
                    json.dumps({"error": message,
                                "type": error_type}).encode("utf-8"),
                    "application/json", headers)

    def _stage(self, name: str, t0: float,
               ctx: "tracing.TraceContext") -> None:
        """Record one gateway-side stage both as a ``trace.*`` child
        span and as a ``Server-Timing`` entry on this response."""
        dur = time.perf_counter() - t0
        self._stage_ms[name] = dur * 1000.0
        events.complete(f"trace.{name}", dur=dur, t_start=t0,
                        trace=ctx.trace, span=tracing.new_span_id(),
                        parent=ctx.span)

    def _server_timing(self) -> str:
        return ", ".join(f"{k};dur={v:.3f}"
                         for k, v in self._stage_ms.items())

    def _read_body(self, length: int) -> bytes:
        """Read exactly ``length`` bytes under a TOTAL wall-clock
        deadline.  Raises ``_SlowBody`` past the deadline (slow-loris)
        and ``_Disconnect`` on EOF/reset (mid-body disconnect)."""
        deadline = time.monotonic() + self.gateway.read_timeout_s
        buf = bytearray()
        conn = self.connection
        while len(buf) < length:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise _SlowBody()
            # short per-recv timeout so the TOTAL deadline is checked
            # at least every 0.25s no matter how slowly bytes drip
            conn.settimeout(min(remaining, 0.25))
            try:
                chunk = self.rfile.read1(
                    min(65536, length - len(buf)))
            except TimeoutError:  # gan4j-lint: disable=swallowed-exception — a per-recv timeout is the POLLING TICK of the total deadline, not an error: the loop head re-checks the deadline and raises _SlowBody when it expires
                continue
            except OSError:
                raise _Disconnect() from None
            if not chunk:
                raise _Disconnect()
            buf += chunk
        conn.settimeout(self.gateway.read_timeout_s)
        return bytes(buf)

    # -- routes ----------------------------------------------------------------

    def do_GET(self):
        if self.path == "/healthz":
            status, doc = self.gateway.health_doc()
            self._reply(status,
                        json.dumps(doc, indent=2).encode("utf-8"),
                        "application/json")
            return
        if self.path == _GENERATE or (
                self.path.startswith(_TENANT_PREFIX)
                and self.path.endswith("/generate")):
            self._reply_error(405, "method", "generate is POST-only")
            return
        if self.path.startswith(_ADMIN_PREFIX):
            self._reply_error(405, "method", "admin verbs are POST-only")
            return
        self._reply_error(404, "route", f"no route {self.path}")

    def _do_admin(self):
        """``POST /admin/{name}``: JSON params in, JSON result out,
        dispatched to the ``admin`` hook dict.  Typed mapping mirrors
        generate: ``ValueError`` → 400, ``FileNotFoundError`` (incl.
        ``NoVerifiedCheckpointError``) → 404, ``RuntimeError``/
        ``OSError`` → 503.  Handlers run on THIS connection thread with
        no gateway lock held — a slow hotswap costs one thread, not
        the listener."""
        name = self.path[len(_ADMIN_PREFIX):]
        handler = self.gateway._admin_handler(name)
        if handler is None:
            self._reply_error(404, "route",
                              f"no admin route {self.path}")
            return
        raw_len = self.headers.get("Content-Length")
        try:
            length = int(raw_len) if raw_len is not None else 0
        except ValueError:
            self._reply_error(400, "validation",
                              "bad Content-Length")
            return
        if length > self.gateway.max_body_bytes:
            self._reply_error(
                413, "validation",
                f"declared body of {length} bytes exceeds the "
                f"{self.gateway.max_body_bytes} byte bound")
            self.close_connection = True
            return
        params: Dict = {}
        if length > 0:
            try:
                body = self._read_body(length)
            except _SlowBody:
                self._reply_error(
                    408, "slow_body",
                    f"request body did not arrive within "
                    f"{self.gateway.read_timeout_s:.1f}s")
                self.close_connection = True
                return
            except _Disconnect:
                self.gateway._count_rejected(0, "disconnect")
                self.close_connection = True
                return
            try:
                params = json.loads(body.decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                self._reply_error(400, "validation",
                                  f"admin body is not valid JSON: {e}")
                return
            if not isinstance(params, dict):
                self._reply_error(400, "validation",
                                  "admin body must be a JSON object")
                return
        try:
            result = handler(params)
        except ValueError as e:
            self._reply_error(400, "validation", str(e))
            return
        except FileNotFoundError as e:
            self._reply_error(404, "not_found", str(e))
            return
        except (RuntimeError, OSError) as e:
            self._reply_error(503, "admin_failed", str(e),
                              retry_after=1.0)
            return
        self._reply(200,
                    json.dumps({"result": result}).encode("utf-8"),
                    "application/json")

    def do_POST(self):
        if self.path.startswith(_ADMIN_PREFIX):
            self._do_admin()
            return
        # trace envelope: continue the caller's trace (header) or mint
        # a fresh root for untraced callers — EVERY generate request
        # lands in the merged timeline either way
        incoming = tracing.from_header(
            self.headers.get(tracing.TRACE_HEADER))
        ctx = (tracing.child(incoming) if incoming is not None
               else tracing.mint())
        self._trace_ctx = ctx
        self._trace_status: Optional[int] = None
        self._stage_ms: Dict[str, float] = {}
        t_req = time.perf_counter()
        try:
            self._do_generate(ctx)
        finally:
            attrs = {"trace": ctx.trace, "span": ctx.span,
                     "status": self._trace_status, "path": self.path}
            if incoming is not None:
                attrs["parent"] = incoming.span
            events.complete("trace.request",
                            dur=time.perf_counter() - t_req,
                            t_start=t_req, **attrs)
            self._trace_ctx = None

    def _do_generate(self, ctx: "tracing.TraceContext"):
        tenant: Optional[str] = None
        if self.path == _GENERATE:
            # the limiter key for untenanted traffic: the declared
            # tenant header when present, else one shared bucket
            limiter_key = self.headers.get("X-Tenant", "")
        elif (self.path.startswith(_TENANT_PREFIX)
              and self.path.endswith("/generate")):
            tenant = self.path[len(_TENANT_PREFIX):-len("/generate")]
            if not tenant or "/" in tenant:
                self._reply_error(404, "route",
                                  f"no route {self.path}")
                return
            limiter_key = tenant
        else:
            self._reply_error(404, "route", f"no route {self.path}")
            return
        self.gateway._count_request()
        t0 = time.perf_counter()
        ok, retry_after = self.gateway._rate_check(limiter_key)
        self._stage("rate_limit", t0, ctx)
        if not ok:
            self._reply_error(
                429, "rate_limit",
                f"tenant {limiter_key or '<default>'!s} is over its "
                f"request rate; retry after {retry_after:.2f}s",
                retry_after=retry_after)
            return
        raw_len = self.headers.get("Content-Length")
        try:
            length = int(raw_len)
        except (TypeError, ValueError):
            self._reply_error(400, "validation",
                              "Content-Length is required")
            return
        if length <= 0:
            self._reply_error(400, "validation",
                              "request body must be non-empty")
            return
        if length > self.gateway.max_body_bytes:
            # rejected from the HEADER — the oversized body is never
            # read, so the caller pays for their mistake, not us
            self._reply_error(
                413, "validation",
                f"declared body of {length} bytes exceeds the "
                f"{self.gateway.max_body_bytes} byte bound")
            self.close_connection = True
            return
        t0 = time.perf_counter()
        try:
            body = self._read_body(length)
        except _SlowBody:
            self._reply_error(
                408, "slow_body",
                f"request body did not arrive within "
                f"{self.gateway.read_timeout_s:.1f}s")
            self.close_connection = True
            return
        except _Disconnect:
            # the peer is gone; count it and release the thread
            self.gateway._count_rejected(0, "disconnect")
            self.close_connection = True
            return
        self._stage("wire_recv", t0, ctx)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        npy = ctype == "application/x-npy"
        t0 = time.perf_counter()
        try:
            xs = (_decode_npy if npy else _decode_json)(body)
            for x in xs:
                if x.shape[0] > self.gateway.max_rows:
                    raise ValueError(
                        f"{x.shape[0]} rows exceeds the per-request "
                        f"bound of {self.gateway.max_rows}")
        except ValueError as e:
            self._reply_error(400, "validation", str(e))
            return
        self._stage("decode", t0, ctx)
        t0 = time.perf_counter()
        status, payload, content_type, error = \
            self.gateway._dispatch(xs, tenant, npy, trace=ctx)
        self._stage_ms["dispatch"] = \
            (time.perf_counter() - t0) * 1000.0
        if error is not None:
            self._reply_error(status, error[0], error[1],
                              retry_after=error[2])
            return
        self._reply(status, payload, content_type,
                    headers=((tracing.TIMING_HEADER,
                              self._server_timing()),))


class _GatewayServer(ThreadingHTTPServer):
    daemon_threads = True
    block_on_close = False
    allow_reuse_address = True

    def __init__(self, addr, handler, gateway: "Gateway"):
        self.gateway = gateway
        self._conn_seq = itertools.count()
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        # ThreadingMixIn spawns anonymous threads; name ours so a
        # stack dump under load reads as a service, not a mystery
        t = threading.Thread(
            target=self.process_request_thread,
            args=(request, client_address),
            name=f"gan4j-gateway-conn-{next(self._conn_seq)}",
            daemon=True)
        t.start()

    def handle_error(self, request, client_address):
        # a connection thread must never die loudly on a peer reset;
        # the typed surfaces (counters, /healthz) carry the signal
        self.gateway._count_rejected(0, "connection_error")


class Gateway:
    """The HTTP server: owns the listener, the per-tenant token
    buckets, and the wire counters; delegates placement to ``router``.

    ``rate_limit``: ``(capacity, refill_per_s)`` applied PER TENANT in
    front of admission (None disables).  ``max_body_bytes`` /
    ``max_rows``: the strict size bounds enforced before anything is
    read or dispatched.  ``read_timeout_s``: TOTAL body-read deadline
    (the slow-loris bound).  ``result_timeout_s``: bounded wait for
    the engine's answer (expiry → 504 — the gateway never strands a
    connection on a wedged backend; the engine's own watchdog is the
    primary never-hang layer).

    ``serve_report``: optional zero-arg hook returning the local
    engine's report — when set, ``/healthz`` carries a ``serve`` block
    and the status folds its ``ok`` in (the replica-process contract
    the mesh probes).  ``admin``: optional ``{name: handler}`` dict of
    operator verbs exposed as ``POST /admin/{name}`` (handler takes
    the decoded JSON params dict, returns a JSON-able result).  Both
    are fixed at construction — reads need no lock."""

    def __init__(self, router: Router, *,
                 host: str = "127.0.0.1", port: int = 0,
                 max_body_bytes: int = 8 << 20, max_rows: int = 4096,
                 read_timeout_s: float = 5.0,
                 rate_limit: Optional[Tuple[float, float]] = None,
                 result_timeout_s: float = 60.0,
                 serve_report=None, admin=None):
        self.router = router
        self._serve_report = serve_report
        self._admin: Dict[str, Callable] = dict(admin or {})
        self._host = host
        self._port = int(port)
        self.max_body_bytes = int(max_body_bytes)
        self.max_rows = int(max_rows)
        self.read_timeout_s = float(read_timeout_s)
        self.result_timeout_s = float(result_timeout_s)
        self._rate_limit = rate_limit
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._requests_total = 0
        self._rejected_total = 0
        self._rejected_by_type: Dict[str, int] = {}
        self._active_connections = 0
        self._server: Optional[_GatewayServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "Gateway":
        with self._lock:
            if self._server is not None:
                raise RuntimeError("gateway already started")
            server = _GatewayServer((self._host, self._port),
                                    _Handler, self)
            self._server = server
            thread = threading.Thread(
                target=server.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="gan4j-gateway-http", daemon=True)
            self._thread = thread
        thread.start()
        events.instant("gateway.start", host=self._host,
                       port=server.server_address[1])
        return self

    def stop(self) -> None:
        with self._lock:
            server, self._server = self._server, None
            thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()  # bounded: serve_forever polls at 0.1s
            server.server_close()
        if thread is not None:
            thread.join(timeout=10.0)

    def __enter__(self) -> "Gateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def port(self) -> int:
        with self._lock:
            if self._server is None:
                raise RuntimeError("gateway is not running")
            return self._server.server_address[1]

    @property
    def host(self) -> str:
        return self._host

    # -- per-request internals (connection threads) ----------------------------

    def _conn_delta(self, d: int) -> None:
        with self._lock:
            self._active_connections += d

    def _count_request(self) -> None:
        with self._lock:
            self._requests_total += 1

    def _count_rejected(self, status: int, error_type: str) -> None:
        with self._lock:
            self._rejected_total += 1
            self._rejected_by_type[error_type] = \
                self._rejected_by_type.get(error_type, 0) + 1
        events.instant("gateway.reject", status=status,
                       type=error_type)

    def _rate_check(self, key: str) -> Tuple[bool, float]:
        if self._rate_limit is None:
            return True, 0.0
        cap, refill = self._rate_limit
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = TokenBucket(cap, refill)
            return bucket.take()

    def _dispatch(self, xs: List[np.ndarray], tenant: Optional[str],
                  npy: bool, trace=None):
        """Place one decoded request and wait (bounded) for its
        answer.  Returns ``(status, payload, content_type, error)``
        where ``error`` is ``None`` on success and
        ``(type, message, retry_after)`` otherwise — the handler
        stays a thin wire adapter.  ``trace`` rides through to the
        engine (the replica-side stage spans parent under it) and
        cuts the gateway's own wait/encode spans."""
        t0 = time.perf_counter()
        try:
            req = self.router.submit(xs, tenant=tenant, trace=trace)
            outs = req.result(timeout=self.result_timeout_s)
        except ShedError as e:
            wait_ms = e.est_wait_ms if e.est_wait_ms is not None \
                else e.budget_ms
            return 429, b"", "", (
                "shed", str(e), max(0.05, wait_ms / 1000.0))
        except TenantThrottledError as e:
            # the bank's per-tenant quota: this tenant's fault domain
            # only — same 429 wire shape as the gateway's own limiter
            return 429, b"", "", (
                "tenant_throttled", str(e),
                max(0.05, e.retry_after_s))
        except KeyError:
            return 404, b"", "", (
                "unknown_tenant", f"unknown tenant {tenant!r}", None)
        except ValueError as e:
            return 400, b"", "", ("validation", str(e), None)
        except (DispatchError, WatchdogTimeout,
                NoHealthyReplicaError) as e:
            return 503, b"", "", ("unavailable", str(e), 1.0)
        except TimeoutError as e:
            return 504, b"", "", ("result_timeout", str(e), None)
        except RuntimeError as e:
            # "engine is not running" / "queue is closed": a replica
            # died after routing — still a typed unavailable
            return 503, b"", "", ("unavailable", str(e), 1.0)
        t1 = time.perf_counter()
        payload, content_type = (_encode_npz if npy
                                 else _encode_json)(outs)
        if trace is not None:
            events.complete("trace.dispatch_wait", dur=t1 - t0,
                            t_start=t0, trace=trace.trace,
                            span=tracing.new_span_id(),
                            parent=trace.span)
            events.complete("trace.response_encode",
                            dur=time.perf_counter() - t1, t_start=t1,
                            trace=trace.trace,
                            span=tracing.new_span_id(),
                            parent=trace.span)
        return 200, payload, content_type, None

    # -- ops surface -----------------------------------------------------------

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_gateway`` (the
        ``gan4j_gateway_*`` series and the ``/healthz`` gateway
        block)."""
        r = self.router.report()
        with self._lock:
            out = {
                "requests_total": self._requests_total,
                "rejected_total": self._rejected_total,
                "rejected_by_type": dict(self._rejected_by_type),
                "active_connections": self._active_connections,
            }
        out.update({
            "replicas": r["replicas"],
            "replicas_healthy": r["replicas_healthy"],
            "ejected_total": r["ejected_total"],
            "tenants_live": r["tenants_live"],
            "ok": r["ok"],
        })
        return out

    def health_block(self) -> Dict:
        return self.report()

    def _admin_handler(self, name: str) -> Optional[Callable]:
        return self._admin.get(name)  # fixed at construction

    def health_doc(self) -> Tuple[int, Dict]:
        """The full ``/healthz`` reply: the gateway block, plus the
        local engine's ``serve`` block when a ``serve_report`` hook is
        configured.  The status folds BOTH oks in, so a remote probe
        reads replica health from the status line alone (a wedged
        engine answers 503 while still listening)."""
        block = self.health_block()
        doc: Dict = {"gateway": block}
        ok = bool(block["ok"])
        if self._serve_report is not None:
            try:
                sblock = self._serve_report()
            except Exception as e:
                # a broken report hook is an UNHEALTHY replica, not a
                # crashed health endpoint
                sblock = {"ok": False, "error": repr(e)}
            doc["serve"] = sblock
            ok = ok and bool(sblock.get("ok"))
        return (200 if ok else 503), doc
