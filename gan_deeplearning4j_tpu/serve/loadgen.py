"""Synthetic open-loop load — the SLO measurement harness.

Closed-loop load (submit, wait, submit) measures the SERVER's pace, not
the users': under overload a closed loop politely slows down with the
service and the latency numbers look fine right up to the cliff.  Real
traffic is open-loop — arrivals keep coming at their own rate whether
or not the service keeps up — so this harness schedules Poisson
arrivals on an ABSOLUTE timeline (seeded exponential gaps summed from
t0; a slow submit doesn't stretch the schedule, the loop just finds
itself behind and fires the backlog immediately, exactly like a real
arrival process) with a configurable request-size mix.

Memory is O(outstanding), not O(requests): completed requests are
reaped from the left of the outstanding deque every iteration and only
their latency (one float) is kept, so "millions of requests" is a
duration, not an allocation.

``run_load`` measures one rate; ``measure_saturation`` ramps the rate
geometrically until the service provably can't keep up (shed fraction
breaks, or the post-stage drain of in-flight work stops being bounded
— a growing backlog) and returns the last sustained rate — the saturation headline ``bench --serve`` reports,
with p50/p95/p99 at a chosen fraction of it (RESULTS.md).
"""

from __future__ import annotations

import random
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gan_deeplearning4j_tpu.serve.admission import ShedError

# the default request-size mix: mostly single-row lookups, a tail of
# batchy callers — enough shape diversity to exercise pad-up on every
# declared bucket without any size being oversized
DEFAULT_SIZE_MIX: Tuple[Tuple[int, float], ...] = (
    (1, 0.55), (4, 0.25), (16, 0.15), (48, 0.05))


def percentiles(samples: Sequence[float],
                qs: Sequence[float]) -> List[Optional[float]]:
    """Nearest-rank percentiles of ``samples`` (None per q when
    empty) — the one definition every latency number in the serving
    plane uses (engine report, load harness, bench)."""
    if not samples:
        return [None] * len(qs)
    s = sorted(samples)
    out: List[Optional[float]] = []
    for q in qs:
        rank = max(0, min(len(s) - 1, int(round(q / 100.0 * len(s))) - 1))
        out.append(float(s[rank]))
    return out


def z_inputs(dim: int, seed: int = 0,
             low: float = -1.0, high: float = 1.0
             ) -> Callable[[int], Tuple[np.ndarray]]:
    """Input factory for a generator taking one ``(rows, dim)`` latent:
    returns ``make_inputs(rows)`` serving seeded uniform noise from a
    per-size cache — O(1) memory and O(1) time per request no matter
    how many millions of requests the harness fires."""
    rng = np.random.RandomState(seed)
    cache: Dict[int, np.ndarray] = {}

    def make_inputs(rows: int) -> Tuple[np.ndarray]:
        z = cache.get(rows)
        if z is None:
            z = (rng.rand(rows, dim).astype(np.float32)
                 * (high - low) + low)
            cache[rows] = z
        return (z,)

    return make_inputs


def _reap(outstanding: deque, latencies: List[float]) -> int:
    """Pop completed requests off the FRONT of the FIFO (completion is
    FIFO too — the engine dispatches in admission order), keeping only
    their latency.  Returns the number of request-level errors seen."""
    errors = 0
    while outstanding and outstanding[0].done.is_set():
        r = outstanding.popleft()
        if r.error is not None:
            errors += 1
        elif r.latency_ms is not None:
            latencies.append(r.latency_ms)
    return errors


def run_load(engine, rate_rps: float,
             duration_s: Optional[float] = None,
             n_requests: Optional[int] = None,
             size_mix: Sequence[Tuple[int, float]] = DEFAULT_SIZE_MIX,
             make_inputs: Optional[Callable] = None,
             seed: int = 0,
             drain_timeout_s: float = 60.0) -> Dict:
    """Fire open-loop Poisson arrivals at ``rate_rps`` for
    ``duration_s`` seconds (or ``n_requests`` arrivals — at least one
    bound is required), reap latencies, and return the verdict:
    offered/achieved request and row rates, shed/error counts, and
    p50/p95/p99 of ADMITTED request latency (shed requests failed fast
    by design — they are counted, not averaged in)."""
    if duration_s is None and n_requests is None:
        raise ValueError("run_load needs duration_s or n_requests")
    if rate_rps <= 0:
        raise ValueError("rate_rps must be > 0")
    if make_inputs is None:
        raise ValueError("run_load needs a make_inputs factory "
                         "(e.g. serve.loadgen.z_inputs(dim))")
    rng = random.Random(seed)
    sizes = [s for s, _ in size_mix]
    weights = [w for _, w in size_mix]
    outstanding: deque = deque()
    latencies: List[float] = []
    submitted = shed = errors = rows_admitted = 0
    t0 = time.perf_counter()
    t_next = t0
    while True:
        if n_requests is not None and submitted + shed >= n_requests:
            break
        now = time.perf_counter()
        if duration_s is not None and now - t0 >= duration_s:
            break
        if t_next > now:
            # sleep in bounded ticks so a stop/interrupt lands promptly
            time.sleep(min(t_next - now, 0.05))
            continue
        rows = rng.choices(sizes, weights=weights)[0]
        try:
            req = engine.submit(*make_inputs(rows))
            outstanding.append(req)
            submitted += 1
            rows_admitted += rows
        except ShedError:
            shed += 1
        # the ABSOLUTE schedule: a slow submit doesn't slow arrivals
        t_next += rng.expovariate(rate_rps)
        errors += _reap(outstanding, latencies)
    gen_end = time.perf_counter()
    deadline = gen_end + drain_timeout_s
    while outstanding and time.perf_counter() < deadline:
        outstanding[0].done.wait(0.1)
        errors += _reap(outstanding, latencies)
    undrained = len(outstanding)
    wall_s = time.perf_counter() - t0
    gen_s = gen_end - t0
    drain_s = wall_s - gen_s
    p50, p95, p99 = percentiles(latencies, (50.0, 95.0, 99.0))
    completed = len(latencies)
    return {
        "offered_rps": rate_rps,
        # completed over the FULL wall including the drain tail — an
        # honest throughput, but biased low for short stages (the tail
        # is in-flight queue, not lost work), which is why saturation
        # detection uses shed/drain bounds rather than this ratio
        "achieved_rps": completed / wall_s if wall_s > 0 else 0.0,
        "gen_s": gen_s,
        "drain_s": drain_s,
        "rows_per_sec": rows_admitted / wall_s if wall_s > 0 else 0.0,
        "submitted": submitted,
        "completed": completed,
        "shed": shed,
        "errors": errors,
        "undrained": undrained,
        "wall_s": wall_s,
        "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
    }


def run_socket_load(client, rate_rps: float,
                    duration_s: Optional[float] = None,
                    n_requests: Optional[int] = None,
                    size_mix: Sequence[Tuple[int, float]]
                    = DEFAULT_SIZE_MIX,
                    make_inputs: Optional[Callable] = None,
                    seed: int = 0,
                    tenant: Optional[str] = None,
                    encoding: str = "npy",
                    max_workers: int = 32,
                    drain_timeout_s: float = 60.0) -> Dict:
    """``run_load`` over a real socket: the same open-loop Poisson
    arrival process, driven through a ``GatewayClient`` against the
    HTTP gateway so the measurement covers the FULL network path —
    parse, validate, rate limit, route, admit, dispatch, encode.

    Each arrival fires a blocking ``client.generate`` on a worker pool
    (HTTP has no submit/result split, so concurrency comes from
    threads; size ``max_workers`` above the expected outstanding count
    or pool queueing bleeds into the latency numbers).  Outcomes are
    classified by the gateway's typed wire contract:

    * 200 → completed (latency measured from the scheduled arrival);
    * 429 after the client's retries → ``shed``;
    * 503/504 after retries → ``unavailable`` (typed: a replica died
      or the backend timed out — distinct from shed so a chaos test
      can assert "zero NON-typed failures" exactly);
    * anything else (400s, transport errors) → ``errors``.

    Returns the ``run_load`` dict shape plus ``unavailable`` and the
    client's ``retried_total`` delta."""
    from gan_deeplearning4j_tpu.serve.client import GatewayHTTPError

    if duration_s is None and n_requests is None:
        raise ValueError("run_socket_load needs duration_s or "
                         "n_requests")
    if rate_rps <= 0:
        raise ValueError("rate_rps must be > 0")
    if make_inputs is None:
        raise ValueError("run_socket_load needs a make_inputs factory "
                         "(e.g. serve.loadgen.z_inputs(dim))")
    rng = random.Random(seed)
    sizes = [s for s, _ in size_mix]
    weights = [w for _, w in size_mix]
    retried_before = client.retried_total

    def _one(rows: int, t_sched: float):
        try:
            client.generate(make_inputs(rows), tenant=tenant,
                            encoding=encoding)
            return ("ok", (time.perf_counter() - t_sched) * 1000.0,
                    rows)
        except GatewayHTTPError as e:
            if e.status == 429:
                return ("shed", None, rows)
            if e.status in (503, 504):
                return ("unavailable", None, rows)
            return ("error", None, rows)
        except Exception:
            return ("error", None, rows)

    outstanding: deque = deque()
    latencies: List[float] = []
    submitted = shed = unavailable = errors = rows_ok = 0

    def _reap_done() -> None:
        nonlocal shed, unavailable, errors, rows_ok
        while outstanding and outstanding[0].done():
            kind, lat_ms, rows = outstanding.popleft().result()
            if kind == "ok":
                latencies.append(lat_ms)
                rows_ok += rows
            elif kind == "shed":
                shed += 1
            elif kind == "unavailable":
                unavailable += 1
            else:
                errors += 1

    with ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="gan4j-gateway-load") as pool:
        t0 = time.perf_counter()
        t_next = t0
        while True:
            if n_requests is not None and submitted >= n_requests:
                break
            now = time.perf_counter()
            if duration_s is not None and now - t0 >= duration_s:
                break
            if t_next > now:
                time.sleep(min(t_next - now, 0.05))
                continue
            rows = rng.choices(sizes, weights=weights)[0]
            outstanding.append(pool.submit(_one, rows,
                                           time.perf_counter()))
            submitted += 1
            # the ABSOLUTE schedule: a slow request doesn't slow arrivals
            t_next += rng.expovariate(rate_rps)
            _reap_done()
        gen_end = time.perf_counter()
        deadline = gen_end + drain_timeout_s
        while outstanding and time.perf_counter() < deadline:
            if not outstanding[0].done():
                time.sleep(0.05)
            _reap_done()
        undrained = len(outstanding)
        for f in outstanding:
            f.cancel()
    wall_s = time.perf_counter() - t0
    gen_s = gen_end - t0
    p50, p95, p99 = percentiles(latencies, (50.0, 95.0, 99.0))
    completed = len(latencies)
    return {
        "offered_rps": rate_rps,
        "achieved_rps": completed / wall_s if wall_s > 0 else 0.0,
        "gen_s": gen_s,
        "drain_s": wall_s - gen_s,
        "rows_per_sec": rows_ok / wall_s if wall_s > 0 else 0.0,
        "submitted": submitted,
        "completed": completed,
        "shed": shed,
        "unavailable": unavailable,
        "errors": errors,
        "undrained": undrained,
        "retried": client.retried_total - retried_before,
        "wall_s": wall_s,
        "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
    }


def measure_saturation(engine, make_inputs: Callable,
                       start_rps: float = 50.0,
                       growth: float = 1.6,
                       stage_s: float = 2.0,
                       max_stages: int = 12,
                       shed_frac_limit: float = 0.02,
                       drain_s_limit: Optional[float] = None,
                       size_mix: Sequence[Tuple[int, float]]
                       = DEFAULT_SIZE_MIX,
                       seed: int = 0) -> Dict:
    """Geometric rate ramp: run ``stage_s`` at each rate until the
    service stops keeping up.  A stage is SUSTAINED when the shed
    fraction stays under ``shed_frac_limit``, nothing errored or was
    left undrained, and the post-stage drain of in-flight work stays
    under ``drain_s_limit`` (default ``max(1.0, 0.75 * stage_s)``) —
    a bounded drain means the queue was in steady state, an unbounded
    one means the backlog was growing all stage (the open-loop
    overload signature even before admission starts shedding).
    Returns the last SUSTAINED rate (the saturation headline) with its
    stage stats, plus the first failing stage for the record."""
    if drain_s_limit is None:
        drain_s_limit = max(1.0, 0.75 * stage_s)
    sustained: Optional[Dict] = None
    failed: Optional[Dict] = None
    rate = float(start_rps)
    stage = -1
    for stage in range(max_stages):
        stats = run_load(engine, rate, duration_s=stage_s,
                         size_mix=size_mix, make_inputs=make_inputs,
                         seed=seed + stage)
        total = stats["submitted"] + stats["shed"]
        shed_frac = stats["shed"] / total if total else 0.0
        ok = (shed_frac <= shed_frac_limit
              and stats["drain_s"] <= drain_s_limit
              and stats["errors"] == 0
              and stats["undrained"] == 0)
        stats["shed_frac"] = shed_frac
        stats["sustained"] = ok
        if ok:
            sustained = stats
            rate *= growth
        else:
            failed = stats
            break
    return {
        # the headline is the OFFERED rate the service provably
        # sustained — achieved_rps is biased low by the drain tail
        "saturation_rps": sustained["offered_rps"] if sustained
        else 0.0,
        "sustained_stage": sustained,
        "failed_stage": failed,
        "stages_run": stage + 1,
    }
