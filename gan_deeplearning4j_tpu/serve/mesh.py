"""The multi-host routing tier: remote replicas over real sockets.

``Router`` (serve/router.py) balances ENGINES in this process;
``MeshRouter`` balances replica PROCESSES (serve/replica.py) by their
HTTP surface, with the same semantics the in-process tier proved:

* **typed routing** — a 429 from a replica means "alive, shedding":
  try the next one, re-raise the last shed when everyone sheds.  A
  503/504 or a transport failure means THAT replica is broken: eject
  it and try the next.  A 400/404 is the caller's bug and propagates
  unchanged.  Nobody healthy → ``NoHealthyReplicaError``.
* **ejection + re-probe** — an ejected replica is skipped for
  ``recheck_s``, then re-probed via ``GET /healthz`` (the replica
  process folds its engine's ok into the status line, so one GET
  answers "healthy?"); a 200 re-admits it.  A wedged replica answers
  503 while still listening — distinguished from a dead socket by the
  SAME probe.
* **lock discipline** — the mesh lock guards the replica list and the
  ejection map only; every probe and every proxied generate runs
  OUTSIDE it (rule lock-held-blocking-call: sockets never under
  locks).

The replica set is MUTABLE (``add``/``remove``) — the control plane
(serve/controlplane.py) grows and shrinks it live.  ``poll()`` runs
one full probe sweep and returns the aggregate the autoscaler feeds
on (queue-depth sum, p99 max, shed total), refreshing every
replica's health as a side effect.
"""

from __future__ import annotations

import json
import threading
import time
from http.client import HTTPException
from typing import Dict, List, Optional, Sequence

import numpy as np

from gan_deeplearning4j_tpu.serve.client import (
    GatewayClient,
    GatewayHTTPError,
)
from gan_deeplearning4j_tpu.serve.router import NoHealthyReplicaError
from gan_deeplearning4j_tpu.telemetry import events, tracing

# what a probe treats as "the socket is broken" (vs. an HTTP answer)
_TRANSPORT_ERRORS = (ConnectionError, HTTPException, OSError)


class ReplicaProbeError(RuntimeError):
    """A health probe could not get ANY HTTP answer from the replica
    (refused, reset, timeout) — the dead-socket failure, as opposed to
    a 503 from a listening-but-unhealthy one."""

    def __init__(self, message: str, *, replica: str):
        super().__init__(message)
        self.replica = replica


class RemoteReplica:
    """One replica process's HTTP surface: health probe, proxied
    generate, admin verbs.  Owns a pooled ``GatewayClient`` with
    ``retries=0`` — retry/failover policy belongs to the MESH (try
    the next replica), not to the edge."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, pool_size: int = 4):
        self.host = host
        self.port = int(port)
        self._client = GatewayClient(host, port, retries=0,
                                     timeout_s=timeout_s,
                                     pool_size=pool_size)

    @property
    def name(self) -> str:
        return f"{self.host}:{self.port}"

    def probe(self) -> Dict:
        """GET /healthz; returns the parsed doc with ``_status``.
        Raises ``ReplicaProbeError`` when no HTTP answer exists."""
        try:
            return self._client.healthz()
        except _TRANSPORT_ERRORS as e:
            raise ReplicaProbeError(
                f"replica {self.name} unreachable: {e!r}",
                replica=self.name) from e

    def generate(self, xs: Sequence[np.ndarray], *,
                 tenant: Optional[str] = None,
                 encoding: str = "json",
                 trace=None) -> List[np.ndarray]:
        return self._client.generate(xs, tenant=tenant,
                                     encoding=encoding, trace=trace)

    def admin(self, verb: str, params: Optional[Dict] = None) -> Dict:
        """POST /admin/{verb}; returns the result payload.  Raises
        ``GatewayHTTPError`` (typed status) on a non-200 answer and
        transport errors unchanged."""
        body = json.dumps(params or {}).encode("utf-8")
        status, headers, data = self._client._request(
            "POST", f"/admin/{verb}", body, "application/json")
        if status != 200:
            self._client._raise(status, headers, data)
        return json.loads(data.decode("utf-8"))["result"]

    def close(self) -> None:
        self._client.close()


class MeshRouter:
    """Round-robin over a MUTABLE set of remote replicas with typed
    ejection and bounded re-probe (semantics above).

    A replica starts healthy; it is ejected when a routed request
    fails at the replica level (503/504/transport) or a ``poll``
    sweep finds it unhealthy, and re-admitted when a re-probe — run
    at most every ``recheck_s`` per ejected replica, on the next
    request that considers it or the next sweep — answers 200."""

    def __init__(self, replicas: Sequence[RemoteReplica] = (), *,
                 recheck_s: float = 1.0):
        self.recheck_s = float(recheck_s)
        self._lock = threading.Lock()
        self._replicas: List[RemoteReplica] = list(replicas)
        self._down: Dict[str, float] = {}  # name -> t_ejected/reprobed
        self._rr = 0
        self._ejected_total = 0
        # requests re-offered to another replica after a failed
        # attempt (shed/eject failover) — read by run_socket_load the
        # way it reads GatewayClient.retried_total
        self.retried_total = 0

    # -- membership (the control plane's surface) ------------------------------

    def add(self, replica: RemoteReplica) -> None:
        with self._lock:
            if any(r.name == replica.name for r in self._replicas):
                raise ValueError(
                    f"replica {replica.name} already in the mesh")
            self._replicas.append(replica)
        events.instant("mesh.replica_added", replica=replica.name)

    def remove(self, name: str) -> Optional[RemoteReplica]:
        """Drop ``name`` from the set (closing its client); returns
        the removed replica or None.  Traffic in flight to it finishes
        or fails typed — removal only stops NEW placements."""
        with self._lock:
            found = None
            for i, r in enumerate(self._replicas):
                if r.name == name:
                    found = self._replicas.pop(i)
                    break
            self._down.pop(name, None)
        if found is not None:
            found.close()
            events.instant("mesh.replica_removed", replica=name)
        return found

    def get(self, name: str) -> Optional[RemoteReplica]:
        with self._lock:
            for r in self._replicas:
                if r.name == name:
                    return r
        return None

    def names(self) -> List[str]:
        with self._lock:
            return [r.name for r in self._replicas]

    # -- health bookkeeping ----------------------------------------------------

    def _mark(self, replica: RemoteReplica, ok: bool) -> None:
        """Fold one probe/request outcome into the ejection map (pure
        bookkeeping under the lock; events after)."""
        now = time.monotonic()
        flipped = None
        with self._lock:
            down = replica.name in self._down
            if ok and down:
                del self._down[replica.name]
                flipped = "mesh.replica_restored"
            elif not ok:
                self._down[replica.name] = now
                if not down:
                    self._ejected_total += 1
                    flipped = "mesh.replica_ejected"
        if flipped is not None:
            events.instant(flipped, replica=replica.name)

    def _healthy(self, replica: RemoteReplica) -> bool:
        """Routing-time health: a non-ejected replica is trusted (its
        failures eject it); an ejected one gets a real re-probe at
        most every ``recheck_s``."""
        now = time.monotonic()
        with self._lock:
            t = self._down.get(replica.name)
            if t is None:
                return True
            if (now - t) < self.recheck_s:
                return False
            # claim this re-probe window so concurrent callers don't
            # all probe at once
            self._down[replica.name] = now
        try:
            ok = replica.probe().get("_status") == 200
        except ReplicaProbeError:
            ok = False
        self._mark(replica, ok)
        return ok

    # -- routing ---------------------------------------------------------------

    def generate(self, xs: Sequence[np.ndarray], *,
                 tenant: Optional[str] = None,
                 encoding: str = "json",
                 trace=None) -> List[np.ndarray]:
        """Place one request on a healthy replica (semantics in the
        module docstring).

        Tracing: the mesh is the first hop for its direct callers —
        with ``trace=None`` it mints a root and wraps the whole
        routing decision in a ``trace.route`` span; a caller context
        parents the route span instead.  EVERY attempt (failed hops
        included) is its own ``trace.hop`` child span, and the hop's
        context rides the wire to the replica — so a failover's
        merged trace shows both hops under one trace id."""
        ctx = (tracing.child(trace) if trace is not None
               else tracing.mint())
        route_attrs = {"trace": ctx.trace, "span": ctx.span}
        if trace is not None:
            route_attrs["parent"] = trace.span
        with events.span("trace.route", **route_attrs):
            return self._generate_routed(xs, tenant, encoding, ctx)

    def _generate_routed(self, xs: Sequence[np.ndarray],
                         tenant: Optional[str], encoding: str,
                         ctx: "tracing.TraceContext"
                         ) -> List[np.ndarray]:
        with self._lock:
            replicas = list(self._replicas)
            start = self._rr
            self._rr += 1
        n = len(replicas)
        if n == 0:
            raise NoHealthyReplicaError(
                "no replicas configured in the mesh")
        last_shed: Optional[GatewayHTTPError] = None
        tried = 0
        for i in range(n):
            replica = replicas[(start + i) % n]
            if not self._healthy(replica):
                continue
            tried += 1
            hop = tracing.child(ctx)
            try:
                # the hop span closes with an ``error`` attribute when
                # the attempt raises — the failed hop stays visible in
                # the merged timeline next to the one that succeeded
                with events.span("trace.hop", trace=ctx.trace,
                                 span=hop.span, parent=ctx.span,
                                 replica=replica.name):
                    return replica.generate(xs, tenant=tenant,
                                            encoding=encoding,
                                            trace=hop)
            except GatewayHTTPError as e:
                if e.status == 429:
                    last_shed = e  # alive but shedding: try the next
                    with self._lock:
                        self.retried_total += 1
                    continue
                if e.status in (503, 504):
                    self._mark(replica, False)
                    with self._lock:
                        self.retried_total += 1
                    continue
                raise  # 400/404/...: the caller's bug, not routing
            except _TRANSPORT_ERRORS:
                self._mark(replica, False)
                with self._lock:
                    self.retried_total += 1
                continue
        if last_shed is not None:
            raise last_shed
        raise NoHealthyReplicaError(
            f"no healthy replica ({n} configured, {tried} accepting)")

    # -- sweeps + ops surface --------------------------------------------------

    def poll(self) -> Dict:
        """One full probe sweep: refresh every replica's health and
        return the autoscaler's aggregate — queue-depth SUM, p99 MAX,
        shed/error SUMs over the healthy serve blocks, plus the raw
        per-replica blocks."""
        with self._lock:
            replicas = list(self._replicas)
        agg: Dict = {"replicas": len(replicas), "healthy": 0,
                     "queue_depth": 0, "p99_ms": 0.0, "shed_total": 0,
                     "errors_total": 0, "requests_total": 0,
                     "reports": {}}
        for replica in replicas:
            try:
                doc = replica.probe()
            except ReplicaProbeError:
                self._mark(replica, False)
                agg["reports"][replica.name] = None
                continue
            ok = doc.get("_status") == 200
            self._mark(replica, ok)
            serve = doc.get("serve") or {}
            agg["reports"][replica.name] = serve
            if not ok:
                continue
            agg["healthy"] += 1
            agg["queue_depth"] += int(serve.get("queue_depth") or 0)
            agg["p99_ms"] = max(agg["p99_ms"],
                                float(serve.get("p99_ms") or 0.0))
            agg["shed_total"] += int(serve.get("shed_total") or 0)
            agg["errors_total"] += int(serve.get("errors_total") or 0)
            agg["requests_total"] += int(
                serve.get("requests_total") or 0)
        return agg

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_serving_mesh``
        (the ``gan4j_mesh_*`` series and the ``/healthz``
        serving_mesh block).  Pure bookkeeping — no probes."""
        with self._lock:
            names = [r.name for r in self._replicas]
            down = set(self._down) & set(names)
            ejected_total = self._ejected_total
        healthy = len(names) - len(down)
        return {"replicas": len(names),
                "replicas_healthy": healthy,
                "replica_ok": [n not in down for n in names],
                "ejected_total": ejected_total,
                "ok": healthy > 0}

    def close(self) -> None:
        with self._lock:
            taken = list(self._replicas)
            self._replicas = []
            self._down.clear()
        for r in taken:
            r.close()
