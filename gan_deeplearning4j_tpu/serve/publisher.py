"""Checkpoint publication: the train→serve bridge.

``CheckpointPublisher`` watches a trainer's checkpoint directory
(single-model or fleet — both write ``ckpt_{step}`` entries under the
same manifest protocol) and drives every VERIFIED new step through the
control plane's canary deployment, so weights flow from the training
plane to the serving plane with zero manual steps:

* **Verification before announcement** — a step is published only
  after (1) its manifest verifies (SHA-256 over every file: the
  checkpointer's own torn/corrupt detector) and (2) a finite-params
  probe: every float array in the checkpoint's model zips and
  ``state.npz`` must be finite.  A poisoned or torn checkpoint is
  rejected AT PUBLICATION — it never reaches a replica, the canary
  never sees it (``publish.rejected`` event,
  ``gan4j_publish_rejected_total``).
* **The newest step gets the benefit of the doubt** — an unverifiable
  NEWEST step may simply be mid-write (the manifest is the commit
  point); the watcher skips it and re-polls.  An unverifiable step
  with a newer sibling already committed is torn forever: rejected.
* **Canary, not blind push** — publication calls
  ``ControlPlane.deploy(directory, step=N)`` (the step PIN: the exact
  checkpoint the publisher verified is the one that canaries) and
  waits for the deployment to settle.  The control plane's existing
  machinery does the rest: probe baseline → canary hotswap →
  SLO-clean hold window → promote to the mesh, auto-rollback on
  regression.
* **Graceful degradation** — while the trainer is down (preempted,
  rolling back, crashed) no new steps appear; replicas keep serving
  the last promoted weights and ``report()`` turns ``stale`` once the
  promoted checkpoint's age exceeds ``stale_after_s`` — surfaced as
  ``serving_stale`` in ``/healthz`` and the
  ``gan4j_publish_age_seconds`` gauge (docs/OBSERVABILITY.md).
* **Restart without a re-deploy storm** — the publisher persists
  ``{promoted step, rejected/rolled-back steps}`` to
  ``PUBLISHED.json`` (atomic tmp+fsync+rename, same discipline as the
  checkpoints it watches); a restarted publisher resumes from the
  last promoted step instead of replaying history.
* **Rollback is sticky** — a step the canary rolled back is not
  auto-retried (the weights did not change; neither would the
  verdict).  ``republish(step)`` is the explicit operator override.

docs/SCENARIO.md walks the full pipeline lifecycle; tests/
test_publisher.py pins the edge cases (torn manifest mid-write,
checkpoint deleted between discovery and verify, rollback-then-
republish, restart resume).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
import zipfile
from typing import Callable, Dict, List, Optional

import numpy as np

from gan_deeplearning4j_tpu.telemetry import events

STATE_NAME = "PUBLISHED.json"

# deployment outcomes _publish understands; "busy"/"failed"/"timeout"
# are transient (retried on a later poll), the rest are recorded
_TERMINAL = ("promoted", "rolled_back", "fatal")


def finite_params_probe(path: str) -> Optional[str]:
    """Probe every float array in ``path``'s model zips (``params.npz``
    members) and ``state.npz`` for non-finite values.  Returns None
    when clean, else a reason naming the offending file/array.  Raises
    ``FileNotFoundError`` when the checkpoint vanished under us (keep
    rotation) — the caller treats "gone" as skip, not reject.

    File-level and graph-free on purpose: the publisher must not need
    a model definition to veto a checkpoint, and the same probe covers
    single-model checkpoints (poisoned zip params) and fleet
    checkpoints (a poisoned tenant slice lives in ``state.npz``).
    """
    if not os.path.isdir(path):
        raise FileNotFoundError(path)
    try:
        names = sorted(os.listdir(path))
    except OSError:
        raise FileNotFoundError(path) from None
    for name in names:
        if not name.endswith("_model.zip"):
            continue
        full = os.path.join(path, name)
        try:
            with zipfile.ZipFile(full) as zf:
                if "params.npz" not in zf.namelist():
                    continue
                raw = zf.read("params.npz")
        except FileNotFoundError:
            raise
        except (OSError, zipfile.BadZipFile, KeyError) as e:
            return f"{name} unreadable: {e!r}"
        why = _probe_npz_bytes(raw, f"{name}:params.npz")
        if why:
            return why
    state_path = os.path.join(path, "state.npz")
    if os.path.isfile(state_path):
        try:
            with open(state_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raise
        except OSError as e:
            return f"state.npz unreadable: {e!r}"
        why = _probe_npz_bytes(raw, "state.npz")
        if why:
            return why
    return None


def _probe_npz_bytes(raw: bytes, label: str) -> Optional[str]:
    try:
        with np.load(io.BytesIO(raw)) as data:
            for key in data.files:
                arr = data[key]
                if (np.issubdtype(arr.dtype, np.floating)
                        and not bool(np.isfinite(arr).all())):
                    return (f"{label}:{key} holds non-finite values "
                            f"(poisoned or corrupt)")
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        return f"{label} unreadable: {e!r}"
    return None


class CheckpointPublisher:
    """Watch ``directory`` for new verified checkpoints and publish
    each through the control plane's canary deployment.

    Exactly one of ``controlplane``/``deploy_fn`` drives deployment:
    ``deploy_fn(directory, step)`` must return one of ``"promoted"``,
    ``"rolled_back"``, ``"failed"``, ``"busy"``, ``"fatal"`` (or a
    ``(outcome, detail)`` pair) — the seam the edge-case tests use.
    ``poll_once()`` is the synchronous unit of work (deterministic
    tests); ``start()`` runs it on the ``gan4j-publisher`` thread
    every ``poll_s`` seconds.
    """

    def __init__(self, directory: str, *,
                 controlplane=None,
                 deploy_fn: Optional[Callable] = None,
                 poll_s: float = 0.5,
                 stale_after_s: float = 120.0,
                 deploy_timeout_s: float = 120.0,
                 state_path: Optional[str] = None):
        if (controlplane is None) == (deploy_fn is None):
            raise ValueError(
                "exactly one of controlplane/deploy_fn is required")
        self.directory = str(directory)
        self.controlplane = controlplane
        self._deploy_fn = deploy_fn
        self.poll_s = float(poll_s)
        self.stale_after_s = float(stale_after_s)
        self.deploy_timeout_s = float(deploy_timeout_s)
        self.state_path = (state_path if state_path is not None
                           else os.path.join(self.directory,
                                             STATE_NAME))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._promoted_step: Optional[int] = None
        self._last_promote_wall: Optional[float] = None
        self._promoted_steps: List[int] = []
        self._rejected: Dict[int, str] = {}
        self._rolled_back: Dict[int, str] = {}
        self._gone: set = set()
        self._force: set = set()
        self._rejected_total = 0
        self._promoted_total = 0
        self._rollback_total = 0
        self._errors_total = 0
        self._fatal: Optional[str] = None
        self._started_wall = time.time()
        self._load_state()

    # -- persisted state -------------------------------------------------------

    def _load_state(self) -> None:
        try:
            with open(self.state_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):  # gan4j-lint: disable=swallowed-exception — no/corrupt state file means a fresh publisher; first run on a fresh checkout must not fail
            return
        if not isinstance(doc, dict):
            return
        step = doc.get("promoted_step")
        wall = doc.get("promoted_wall")
        with self._lock:
            if isinstance(step, int):
                self._promoted_step = step
            if isinstance(wall, (int, float)):
                self._last_promote_wall = float(wall)
        for key, sink in (("rejected", self._rejected),
                          ("rolled_back", self._rolled_back)):
            entries = doc.get(key)
            if isinstance(entries, dict):
                for s, why in entries.items():
                    try:
                        sink[int(s)] = str(why)
                    except ValueError:  # gan4j-lint: disable=swallowed-exception — a non-numeric key in a hand-edited state file must not kill the publisher
                        continue

    def _save_state(self) -> None:
        with self._lock:
            doc = {
                "promoted_step": self._promoted_step,
                "promoted_wall": self._last_promote_wall,
                "rejected": {str(k): v
                             for k, v in self._rejected.items()},
                "rolled_back": {str(k): v
                                for k, v in self._rolled_back.items()},
            }
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "CheckpointPublisher":
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("publisher already started")
            t = threading.Thread(
                target=self._run, name="gan4j-publisher", daemon=True)
            self._thread = t
        t.start()
        events.instant("publish.start", directory=self.directory)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.deploy_timeout_s + 10.0)
        events.instant("publish.stop", directory=self.directory)

    def __enter__(self) -> "CheckpointPublisher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # gan4j-lint: disable=swallowed-exception — the watcher thread must survive any single poll (dir vanishing mid-listdir, a deploy raising unexpectedly); the error is counted and on the timeline
                with self._lock:
                    self._errors_total += 1
                events.instant("publish.error", error=repr(e))
            self._stop.wait(self.poll_s)

    # -- the watch loop --------------------------------------------------------

    def _checkpointer(self):
        from gan_deeplearning4j_tpu.checkpoint.checkpointer import (
            TrainCheckpointer,
        )
        # read-side handle: the trainer is ACTIVELY saving into this
        # directory — a sweeping observer would tear its in-flight tmp
        return TrainCheckpointer(self.directory, sweep_debris=False)

    def _candidate(self, step: int) -> bool:
        with self._lock:
            if step in self._force:
                return True
            if step in self._rejected or step in self._rolled_back:
                return False
            if step in self._gone:
                # "gone" is an observation, not a verdict: a re-save of
                # an existing step (emergency preempt checkpoint over a
                # cadence one) swaps via rename/rename, and a poll
                # landing between the renames sees the dir absent for
                # one cycle — when it reappears, reconsider it
                if not os.path.isdir(os.path.join(
                        self.directory, f"ckpt_{step}")):
                    return False
                self._gone.discard(step)
            if (self._promoted_step is not None
                    and step <= self._promoted_step):
                return False
        return True

    def poll_once(self) -> None:
        """One synchronous watch cycle: discover, verify, probe, and
        publish every new step (oldest first — every verified step
        reaches serving, not just the newest).  Stops early when a
        deploy reports busy/failed (retried next cycle) or the newest
        step is still mid-write."""
        if self._fatal is not None:
            return
        try:
            ck = self._checkpointer()
            steps = ck.steps()
        except OSError:  # gan4j-lint: disable=swallowed-exception — the checkpoint dir not existing yet (trainer still booting) is the steady state of a fresh scenario, not an error
            return
        if not steps:
            return
        newest = steps[-1]
        for step in steps:
            if self._stop.is_set():
                return
            if not self._candidate(step):
                continue
            if not self._consider(ck, step, newest):
                return

    def _consider(self, ck, step: int, newest: int) -> bool:
        """Returns False to stop this cycle's scan (mid-write newest,
        busy control plane)."""
        path = os.path.join(self.directory, f"ckpt_{step}")
        if not os.path.isdir(path):
            self._mark_gone(step)
            return True
        try:
            verified = bool(ck.verify(step))
        except Exception as e:  # gan4j-lint: disable=swallowed-exception — verify() reading a dir being deleted under it can raise anything; unverifiable is the answer either way
            events.instant("publish.verify_error", step=step,
                           error=repr(e))
            verified = False
        if not verified:
            if step < newest:
                # a newer sibling committed after this one: this
                # manifest will never complete — torn forever
                self._reject(step, "fails manifest verification "
                                   "(torn or corrupt)")
                return True
            # the newest step may simply be mid-write (the manifest
            # rename is the commit point): skip, re-poll
            events.instant("publish.pending", step=step,
                           reason="newest step unverified "
                                  "(possibly mid-write)")
            return False
        try:
            why = finite_params_probe(path)
        except FileNotFoundError:
            self._mark_gone(step)
            return True
        if why is not None:
            self._reject(step, why)
            return True
        return self._publish(step)

    def _mark_gone(self, step: int) -> None:
        with self._lock:
            self._gone.add(step)
        events.instant("publish.skipped", step=step,
                       reason="checkpoint deleted between discovery "
                              "and verification (keep rotation)")

    def _reject(self, step: int, reason: str) -> None:
        with self._lock:
            self._rejected[step] = reason
            self._rejected_total += 1
        self._save_state()
        events.instant("publish.rejected", step=step, reason=reason,
                       directory=self.directory)

    # -- deployment ------------------------------------------------------------

    def _publish(self, step: int) -> bool:
        """Deploy one verified step; returns False when the cycle
        should stop scanning (busy/transient failure)."""
        events.instant("publish.deploy", step=step,
                       directory=self.directory)
        if self._deploy_fn is not None:
            outcome = self._deploy_fn(self.directory, step)
        else:
            outcome = self._deploy_via_controlplane(step)
        detail = ""
        if isinstance(outcome, tuple):
            outcome, detail = outcome[0], str(outcome[1])
        if outcome == "promoted":
            now = time.time()
            with self._lock:
                self._promoted_step = step
                self._last_promote_wall = now
                self._promoted_total += 1
                self._promoted_steps.append(step)
                self._force.discard(step)
                self._rolled_back.pop(step, None)
            self._save_state()
            events.instant("publish.promoted", step=step,
                           directory=self.directory)
            return True
        if outcome == "rolled_back":
            with self._lock:
                self._rolled_back[step] = detail or "canary rollback"
                self._rollback_total += 1
                self._force.discard(step)
            self._save_state()
            events.instant("publish.rolled_back", step=step,
                           reason=detail or "canary rollback")
            return True
        if outcome == "fatal":
            with self._lock:
                self._fatal = detail or "deployment budget exhausted"
            events.instant("publish.fatal",
                           reason=detail or "deployment budget "
                                            "exhausted")
            return False
        # busy / failed / timeout: transient — nothing recorded, the
        # step stays a candidate for the next cycle
        events.instant("publish.retry", step=step,
                       outcome=str(outcome), reason=detail)
        return False

    def _deploy_via_controlplane(self, step: int):
        from gan_deeplearning4j_tpu.serve.controlplane import (
            DeploymentRollbackError,
        )
        cp = self.controlplane
        try:
            cp.deploy(self.directory, step=step)
        except DeploymentRollbackError as e:
            return ("fatal", str(e))
        except RuntimeError as e:
            return ("busy", str(e))
        deadline = time.monotonic() + self.deploy_timeout_s
        while time.monotonic() < deadline:
            status = cp.deployment_status()
            state = status.get("state")
            if state == "promoted":
                return "promoted"
            if state == "rolled_back":
                if status.get("environmental"):
                    # the canary DIED (chaos, preemption) before the
                    # SLO probes could refute the weights — nothing
                    # was learned about the artifact, so retry it
                    # next cycle instead of stickying it
                    return ("failed",
                            "environmental rollback: "
                            + str(status.get("reason", "")))
                return ("rolled_back", str(status.get("reason", "")))
            if state == "failed":
                return ("failed", str(status.get("reason", "")))
            if state == "failed_fatal":
                return ("fatal", str(status.get("reason", "")))
            if self._stop.wait(min(0.05, self.poll_s)):
                break
        return ("timeout", f"deployment of step {step} did not "
                           f"settle in {self.deploy_timeout_s:.0f}s")

    # -- operator surface ------------------------------------------------------

    def republish(self, step: int) -> None:
        """Clear a step's rejected/rolled-back verdict so the next
        poll re-deploys it — the explicit override for weights an
        operator has inspected (rollback is otherwise sticky: the
        bytes did not change, neither would the canary's verdict)."""
        step = int(step)
        with self._lock:
            self._rejected.pop(step, None)
            self._rolled_back.pop(step, None)
            self._gone.discard(step)
            self._force.add(step)
        self._save_state()
        events.instant("publish.republish", step=step)

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_publication``
        (the ``gan4j_publish_*`` series and the ``/healthz``
        ``publication`` block)."""
        now = time.time()
        with self._lock:
            anchor = (self._last_promote_wall
                      if self._last_promote_wall is not None
                      else self._started_wall)
            age = max(0.0, now - anchor)
            return {
                "last_step": int(self._promoted_step or 0),
                "age_seconds": round(age, 3),
                "stale": bool(age > self.stale_after_s),
                "promoted_total": self._promoted_total,
                "rejected_total": self._rejected_total,
                "rollback_total": self._rollback_total,
                "errors_total": self._errors_total,
                "promoted_steps": list(self._promoted_steps),
                "fatal": self._fatal,
                "ok": self._fatal is None,
            }
