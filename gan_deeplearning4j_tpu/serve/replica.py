"""One serving replica as a standalone PROCESS.

``python -m gan_deeplearning4j_tpu.serve.replica --port N
[--checkpoint DIR]`` builds the full single-host serving stack —
generator graph → ``ParallelInference`` → ``ServeEngine`` →
``Router`` → ``Gateway`` — and runs it until SIGTERM/SIGINT.  This is
the unit the mesh tier (serve/mesh.py) load-balances over and the
control plane (serve/controlplane.py) spawns, probes, retires, and
replaces: the process boundary is what makes a SIGKILL survivable and
a scale-up real.

The process contract (what spawners and probes rely on):

* **ready line** — after the gateway is listening, EXACTLY one JSON
  line goes to stdout: ``{"event": "replica_ready", "host": ...,
  "port": P, "pid": ...}`` (then a flush).  ``--port 0`` binds an
  ephemeral port, so the spawner learns the real one from this line —
  no port-collision races across a fleet of spawns.
* **health** — ``GET /healthz`` answers 200 only while BOTH the
  gateway and the engine report ok (the gateway's ``serve_report``
  hook); a wedged engine answers 503 while still accepting
  connections — exactly the stalled-but-listening failure the mesh
  probe must distinguish from a dead socket.
* **admin verbs** — ``POST /admin/hotswap``
  (``{"directory": ..., ["step"], ["max_step"]}`` → ``{"step": N}``,
  the control plane's canary/promote/rollback lever) and
  ``POST /admin/chaos/wedge`` (``{"seconds": S}`` — report unhealthy
  for S seconds while still listening; the chaos injector behind
  ``testing.chaos.wedge_replica``).
* **shutdown** — SIGTERM/SIGINT drains: gateway stops taking
  connections, the engine fails open requests typed, exit code 0.

A ``--checkpoint`` directory is restored via ``hotswap_from`` BEFORE
the ready line (newest verified checkpoint, corrupt ones skipped with
``serve.hotswap_rejected``); an empty/unverifiable directory serves
the fresh initialization instead of refusing to boot — the control
plane may spawn replicas before the first deploy ever happens.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

import numpy as np

from gan_deeplearning4j_tpu.models import dcgan_mnist as M
from gan_deeplearning4j_tpu.parallel.inference import (
    DEFAULT_SERVING_BUCKETS,
    ParallelInference,
)
from gan_deeplearning4j_tpu.serve.engine import ServeEngine
from gan_deeplearning4j_tpu.serve.gateway import Gateway
from gan_deeplearning4j_tpu.serve.router import Router
from gan_deeplearning4j_tpu.telemetry import events


class WedgeState:
    """A chaos latch: ``wedge(seconds)`` makes ``wedged()`` true until
    the deadline passes.  Pure bookkeeping under its lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._until = 0.0

    def wedge(self, seconds: float) -> None:
        if seconds <= 0:
            raise ValueError("wedge seconds must be > 0")
        with self._lock:
            self._until = time.monotonic() + float(seconds)

    def wedged(self) -> bool:
        with self._lock:
            return time.monotonic() < self._until


def _parse_buckets(text: str):
    try:
        buckets = tuple(int(b) for b in text.split(",") if b.strip())
    except ValueError:
        raise ValueError(f"bad --buckets {text!r} (want e.g. '8,32')") \
            from None
    if not buckets or any(b <= 0 for b in buckets):
        raise ValueError(f"bad --buckets {text!r} (want positive ints)")
    return buckets


def build_replica(*, port: int = 0, host: str = "127.0.0.1",
                  checkpoint=None, buckets=DEFAULT_SERVING_BUCKETS,
                  max_rows: int = 4096, read_timeout_s: float = 5.0,
                  result_timeout_s: float = 60.0):
    """Build (engine, gateway, wedge) — the replica stack minus the
    process scaffolding, so tests can run one in-process too."""
    graph = M.build_generator()
    infer = ParallelInference(graph, buckets=tuple(buckets))
    engine = ServeEngine(infer=infer)
    wedge = WedgeState()

    def serve_report():
        rep = engine.report()
        rep["wedged"] = wedge.wedged()
        if rep["wedged"]:
            # stalled-but-listening: the report says unhealthy while
            # the socket keeps accepting — the probe must see a 503,
            # not a refused connection
            rep["ok"] = False
            rep["stalled"] = True
        return rep

    def admin_hotswap(params):
        directory = params.get("directory")
        if not directory:
            raise ValueError(
                'hotswap needs {"directory": "<checkpoint dir>"}')
        step = params.get("step")
        max_step = params.get("max_step")
        got = engine.hotswap_from(
            str(directory), name=str(params.get("name", "gen")),
            step=None if step is None else int(step),
            max_step=None if max_step is None else int(max_step))
        return {"step": got}

    def admin_wedge(params):
        seconds = float(params.get("seconds", 5.0))
        wedge.wedge(seconds)
        return {"wedged_s": seconds}

    gateway = Gateway(
        Router([engine]), host=host, port=port, max_rows=max_rows,
        read_timeout_s=read_timeout_s,
        result_timeout_s=result_timeout_s,
        serve_report=serve_report,
        admin={"hotswap": admin_hotswap, "chaos/wedge": admin_wedge})
    engine.start()
    engine.warmup(np.zeros((1, graph.input_specs[
        graph.input_names[0]].shape[-1]), np.float32))
    if checkpoint:
        try:
            engine.hotswap_from(str(checkpoint))
        except FileNotFoundError as e:
            # incl. NoVerifiedCheckpointError: serve the fresh init —
            # the control plane spawns replicas before the first
            # deploy exists
            print(f"replica: no verified checkpoint in {checkpoint!r} "
                  f"({e}); serving fresh initialization",
                  file=sys.stderr, flush=True)
    gateway.start()
    return engine, gateway, wedge


def build_fleet_replica(*, port: int = 0, host: str = "127.0.0.1",
                        checkpoint=None, tenants: int = 4,
                        buckets=DEFAULT_SERVING_BUCKETS,
                        max_rows: int = 4096,
                        read_timeout_s: float = 5.0,
                        result_timeout_s: float = 60.0):
    """Build (engine, gateway, wedge, bank) — the FLEET serving stack:
    a ``FleetTenantBank`` over the insurance-protocol generators with
    ``/v1/tenants/{id}/generate`` routing, behind the same process
    contract as :func:`build_replica`.

    Tenant 0's engine doubles as the plain ``/v1/generate`` replica,
    so the control plane's model-agnostic canary probes (zero-latent
    rows — insurance ``z_size`` wide) exercise real fleet weights: a
    poisoned tenant-0 slice fails the canary, not just the publisher's
    file probe.  ``max_live`` is pinned above the tenant count so the
    probe engine can never be LRU-evicted out from under the router.

    ``checkpoint``: a fleet checkpoint dir — restored lazily when it
    holds a verified fleet checkpoint; otherwise (empty dir, first
    boot before the trainer's first save, or a non-fleet dir) the bank
    serves a freshly initialized ``tenants``-wide fleet, mirroring the
    single-model replica's serve-fresh-init boot contract.  Admin
    ``hotswap`` routes to ``FleetTenantBank.hotswap_from`` — every
    live tenant engine gets its new slice in place, zero recompile."""
    from gan_deeplearning4j_tpu.models import mlpgan_insurance as IM
    from gan_deeplearning4j_tpu.serve.router import FleetTenantBank
    from gan_deeplearning4j_tpu.train import fused_step as fused_lib
    from gan_deeplearning4j_tpu.train.fleet import (
        FleetCheckpointer,
        replicate_state,
    )

    cfg = IM.InsuranceConfig()

    def build_graph():
        return IM.build_generator(cfg)

    max_live = max(int(tenants), 4) + 1
    bank = None
    if checkpoint:
        # read-side handle: the trainer owns the checkpoint dir
        ck = FleetCheckpointer(str(checkpoint), sweep_debris=False)
        if ck.latest_verified_step() is not None:
            candidate = FleetTenantBank(
                build_graph, checkpointer=ck,
                buckets=tuple(buckets), max_live=max_live)
            try:
                candidate.num_tenants()  # force the restore NOW
                bank = candidate
            except (FileNotFoundError, ValueError) as e:
                # a verified-but-not-fleet checkpoint (or one pruned
                # between the verify and the restore): fresh init, as
                # the boot contract promises
                print(f"replica: cannot serve fleet from "
                      f"{checkpoint!r} ({e}); serving fresh "
                      f"initialization", file=sys.stderr, flush=True)
    if bank is None:
        dis = IM.build_discriminator(cfg)
        graphs = (dis, IM.build_generator(cfg), IM.build_gan(cfg),
                  IM.build_classifier(dis, cfg))
        state = replicate_state(
            fused_lib.state_from_graphs(*graphs), int(tenants))
        bank = FleetTenantBank(build_graph, state=state,
                               buckets=tuple(buckets),
                               max_live=max_live)
    engine = bank.engine(0)  # built, warmed, started
    wedge = WedgeState()

    def serve_report():
        rep = engine.report()
        rep["wedged"] = wedge.wedged()
        rep["tenants"] = bank.num_tenants()
        rep["tenants_live"] = bank.live_count()
        if rep["wedged"]:
            rep["ok"] = False
            rep["stalled"] = True
        return rep

    def admin_hotswap(params):
        directory = params.get("directory")
        if not directory:
            raise ValueError(
                'hotswap needs {"directory": "<checkpoint dir>"}')
        step = params.get("step")
        max_step = params.get("max_step")
        got = bank.hotswap_from(
            str(directory),
            step=None if step is None else int(step),
            max_step=None if max_step is None else int(max_step))
        return {"step": got}

    def admin_wedge(params):
        seconds = float(params.get("seconds", 5.0))
        wedge.wedge(seconds)
        return {"wedged_s": seconds}

    gateway = Gateway(
        Router([engine], tenants=bank), host=host, port=port,
        max_rows=max_rows, read_timeout_s=read_timeout_s,
        result_timeout_s=result_timeout_s,
        serve_report=serve_report,
        admin={"hotswap": admin_hotswap, "chaos/wedge": admin_wedge})
    gateway.start()
    return engine, gateway, wedge, bank


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gan_deeplearning4j_tpu.serve.replica",
        description="run one serving replica (gateway + engine) as a "
                    "standalone process")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral; read the ready "
                        "line for the real one)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint directory to hotswap from before "
                        "taking traffic")
    p.add_argument("--buckets", default=",".join(
        str(b) for b in DEFAULT_SERVING_BUCKETS))
    p.add_argument("--result-timeout-s", type=float, default=60.0)
    p.add_argument("--events", default=None,
                   help="write this process's events timeline to PATH "
                        "(jsonl)")
    p.add_argument("--fleet", action="store_true",
                   help="serve a multi-tenant FLEET (insurance "
                        "generators + /v1/tenants/{id}/generate) "
                        "instead of the single dcgan generator")
    p.add_argument("--fleet-tenants", type=int, default=4,
                   help="fresh-init fleet width when --checkpoint "
                        "holds no verified fleet checkpoint yet")
    args = p.parse_args(argv)

    if args.events:
        events.install(events.EventRecorder(path=args.events))

    bank = None
    if args.fleet:
        engine, gateway, _wedge, bank = build_fleet_replica(
            port=args.port, host=args.host,
            checkpoint=args.checkpoint, tenants=args.fleet_tenants,
            buckets=_parse_buckets(args.buckets),
            result_timeout_s=args.result_timeout_s)
    else:
        engine, gateway, _wedge = build_replica(
            port=args.port, host=args.host,
            checkpoint=args.checkpoint,
            buckets=_parse_buckets(args.buckets),
            result_timeout_s=args.result_timeout_s)

    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    print(json.dumps({"event": "replica_ready", "host": args.host,
                      "port": gateway.port, "pid": os.getpid()}),
          flush=True)
    events.instant("replica.ready", port=gateway.port,
                   pid=os.getpid())

    while not stop_evt.wait(0.5):
        pass

    gateway.stop()
    if bank is not None:
        bank.stop()  # every live tenant engine, the probe one included
    else:
        engine.stop()
    events.instant("replica.stopped", pid=os.getpid())
    # flush the events file's buffered tail: with fewer events than
    # the recorder's flush_every, NOTHING would hit disk otherwise —
    # and trace_merge would see a replica that served traffic but
    # recorded no spans
    events.current().close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
