"""Multi-engine routing: replica load balancing and fleet tenants.

One ``ServeEngine`` is one dispatch thread over one compiled model.
Production serving needs two more axes, and this module is both:

* **Replica balancing** — ``Router`` spreads plain generate traffic
  round-robin across N engine replicas with health-aware dispatch: a
  replica whose watchdog reports stalled (``engine.stalled`` — the
  ``/healthz`` serve block's failure condition) or whose engine is not
  running is EJECTED and re-probed at most every ``recheck_s`` until it
  recovers; requests drain to the survivors.  A replica that sheds
  (``ShedError``) is NOT unhealthy — it is at capacity — so the router
  offers the request to the next replica and only re-raises the shed
  when every healthy replica shed it (the service, not one engine, is
  full).  Graceful degradation, never a hang: with zero healthy
  replicas ``submit`` raises a typed ``NoHealthyReplicaError``
  immediately.

* **Fleet tenants** — ``FleetTenantBank`` wires PR 12's multi-tenant
  fleet into serving: ``/v1/tenants/{id}/generate`` routes to the
  tenant's own generator, built by assigning
  ``slice_tenant(fleet_state, id).gen_params`` onto a fresh generator
  graph (exactly the ``FleetCheckpointer.restore(tenants=id)``
  contract — bit-equal by the slicing pin in tests/test_fleet.py).
  The full fleet state is restored ONCE and cached host-side (MLP-GAN
  fleets are small — thousands of tenants of ~10k params each); live
  per-tenant engines are an LRU of at most ``max_live`` so a million
  tenants is a routing table, not a million dispatch threads.

Lock discipline: the router lock and the bank lock guard only their
own bookkeeping (round-robin cursor, ejection map, LRU); engine calls
— submit, warmup, stop — always happen OUTSIDE them
(docs/STATIC_ANALYSIS.md, rule lock-held-blocking-call), so neither
lock can participate in a cycle with the engine/admission locks.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gan_deeplearning4j_tpu.parallel.inference import (
    DEFAULT_SERVING_BUCKETS,
    ParallelInference,
)
from gan_deeplearning4j_tpu.serve.admission import Request, ShedError
from gan_deeplearning4j_tpu.serve.engine import ServeEngine, _array_trailing
from gan_deeplearning4j_tpu.telemetry import events


class NoHealthyReplicaError(RuntimeError):
    """Every replica is ejected (stalled or stopped): the typed
    "service unavailable" answer — the router never parks a request
    hoping a replica comes back."""


class TenantThrottledError(RuntimeError):
    """A tenant's serving quota bucket is empty — the typed 429 for
    per-tenant isolation: one tenant burning its allowance never
    degrades its neighbours.  ``retry_after_s`` is when one token is
    back (the gateway's Retry-After header)."""

    def __init__(self, tenant, retry_after_s: float):
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"tenant {tenant!r} is over its serving quota — retry in "
            f"{self.retry_after_s:.3f}s")


class Router:
    """Health-aware round-robin over engine replicas, plus optional
    tenant routing through a ``FleetTenantBank``.

    ``replicas``: started ``ServeEngine`` instances (may be empty when
    only tenant routing is used).  ``tenants``: a ``FleetTenantBank``
    (or anything with an ``engine(tenant_id) -> ServeEngine`` method).
    ``recheck_s``: how often an ejected replica is re-probed."""

    def __init__(self, replicas: Sequence[ServeEngine] = (),
                 tenants: Optional["FleetTenantBank"] = None,
                 recheck_s: float = 0.5):
        self.replicas: Tuple[ServeEngine, ...] = tuple(replicas)
        self.tenants = tenants
        self._recheck_s = float(recheck_s)
        self._lock = threading.Lock()
        self._rr = 0
        # replica index -> monotonic time of the last failed probe;
        # presence in the map IS the ejected state
        self._down: Dict[int, float] = {}
        self._ejected_total = 0

    # -- health ----------------------------------------------------------------

    def _probe(self, idx: int) -> bool:
        eng = self.replicas[idx]
        return eng.running and not eng.stalled

    def _healthy(self, idx: int) -> bool:
        """Probe gate for one replica: healthy replicas are checked
        every time (the probe is two flag reads); ejected replicas are
        re-probed at most every ``recheck_s`` so a dead engine costs
        one timestamp compare per request, not a probe."""
        now = time.monotonic()
        with self._lock:
            down_at = self._down.get(idx)
            if down_at is not None and now - down_at < self._recheck_s:
                return False
        ok = self._probe(idx)
        with self._lock:
            if ok:
                if idx in self._down:
                    del self._down[idx]
                    events.instant("router.replica_restored",
                                   replica=idx)
            else:
                if idx not in self._down:
                    self._ejected_total += 1
                    events.instant("router.replica_ejected",
                                   replica=idx)
                self._down[idx] = now
        return ok

    def _eject(self, idx: int) -> None:
        with self._lock:
            if idx not in self._down:
                self._ejected_total += 1
                events.instant("router.replica_ejected", replica=idx)
            self._down[idx] = time.monotonic()

    # -- dispatch --------------------------------------------------------------

    def submit(self, xs: Sequence[np.ndarray],
               tenant: Optional[str] = None, trace=None) -> Request:
        """Admit one request and return its ``Request`` handle.

        Tenant requests go to the tenant's own engine (``KeyError``
        for an unknown tenant).  Plain requests try each healthy
        replica once in round-robin order: a stopped/stalled replica
        is ejected and skipped, a shedding replica is passed over; the
        request fails with the LAST shed only when every healthy
        replica shed it, and with ``NoHealthyReplicaError`` when none
        was healthy at all.  ``trace`` (a ``tracing.TraceContext``)
        rides through to the engine unchanged."""
        if tenant is not None:
            if self.tenants is None:
                raise KeyError(tenant)
            # charge BEFORE the engine accessor: a throttled request
            # must not trigger a first-use engine build (compile), and
            # charge() validates the id, so unknown tenants still get
            # their KeyError without allocating a quota bucket
            charge = getattr(self.tenants, "charge", None)
            if charge is not None:
                charge(tenant)
            return self.tenants.engine(tenant).submit(*xs, trace=trace)
        if not self.replicas:
            raise NoHealthyReplicaError(
                "router has no replicas configured")
        n = len(self.replicas)
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % n
        last_shed: Optional[ShedError] = None
        tried = 0
        for off in range(n):
            idx = (start + off) % n
            if not self._healthy(idx):
                continue
            tried += 1
            try:
                return self.replicas[idx].submit(*xs, trace=trace)
            except ShedError as e:
                last_shed = e  # at capacity, not unhealthy: try next
            except ValueError:
                raise  # caller bug — identical on every replica
            except RuntimeError:
                # "engine is not running" / "queue is closed": the
                # replica died between the probe and the submit
                self._eject(idx)
        if last_shed is not None:
            raise last_shed
        raise NoHealthyReplicaError(
            f"no healthy replica ({n} configured, {tried} accepting)")

    # -- ops surface -----------------------------------------------------------

    def healthy_count(self) -> int:
        return sum(1 for i in range(len(self.replicas))
                   if self._healthy(i))

    def report(self) -> Dict:
        replica_ok = [self._healthy(i)
                      for i in range(len(self.replicas))]
        with self._lock:
            ejected_total = self._ejected_total
        ok = (any(replica_ok) if self.replicas
              else self.tenants is not None)
        return {
            "replicas": len(self.replicas),
            "replicas_healthy": sum(replica_ok),
            "replica_ok": replica_ok,
            "ejected_total": ejected_total,
            "tenants_live": (self.tenants.live_count()
                             if self.tenants is not None else 0),
            "ok": ok,
        }

    def stop(self) -> None:
        """Stop every replica and tenant engine (bounded — each
        ``ServeEngine.stop`` is)."""
        for eng in self.replicas:
            eng.stop()
        if self.tenants is not None:
            self.tenants.stop()


class FleetTenantBank:
    """Per-tenant serving engines sliced from one fleet state.

    ``build_graph``: zero-arg factory returning a fresh generator
    ``ComputationGraph`` whose parameter tree matches the fleet's
    ``gen_params`` entry (e.g. ``lambda: M.build_generator(cfg)``).
    ``checkpointer``: a ``FleetCheckpointer`` to restore the fleet
    state from (lazily, once); or pass ``state`` (a fleet
    ``ProtocolState`` with a leading tenant axis) directly.
    ``max_live``: LRU bound on concurrently-live tenant engines —
    the eviction victim is stopped (its engine answers everything
    outstanding first; ``ServeEngine.stop`` is bounded).

    Tenant ids are validated against the fleet size BEFORE slicing:
    jax index-clamping would otherwise silently serve the LAST tenant
    for any out-of-range id — an unacceptable cross-tenant leak.

    Lifecycle checkpoints (train/lifecycle.py) record a tenant-id →
    slot map in their manifest extras; when present the bank keys
    EVERYTHING on stable tenant ids and resolves the slot per request,
    so ``/v1/tenants/7/generate`` keeps serving tenant 7's params
    through onboard/offboard events that shuffle slot indices.
    Without a stored map, ids keep their PR-12 raw-slot-index meaning.

    ``quota_capacity``/``quota_refill_per_s`` arm a per-tenant serving
    token bucket (one token per request, charged by the router before
    the engine accessor): an exhausted tenant gets a typed
    ``TenantThrottledError`` — its own fault domain, not a shared
    shed — while its neighbours keep their full allowance."""

    def __init__(self, build_graph: Callable, *,
                 checkpointer=None, state=None,
                 mesh=None,
                 buckets: Sequence[int] = DEFAULT_SERVING_BUCKETS,
                 max_live: int = 4,
                 supervise: bool = False,
                 watchdog_deadline_s: Optional[float] = None,
                 admission_factory: Optional[Callable] = None,
                 quota_capacity: Optional[int] = None,
                 quota_refill_per_s: Optional[float] = None):
        if (checkpointer is None) == (state is None):
            raise ValueError(
                "FleetTenantBank needs exactly one of checkpointer= "
                "or state=")
        if max_live <= 0:
            raise ValueError("max_live must be > 0")
        self._build_graph = build_graph
        self._checkpointer = checkpointer
        self._state = state
        self._mesh = mesh
        self._buckets = tuple(buckets)
        self._max_live = int(max_live)
        self._supervise = bool(supervise)
        self._wd_deadline_s = watchdog_deadline_s
        self._admission_factory = admission_factory
        self._quota_capacity = quota_capacity
        self._quota_refill = (quota_refill_per_s
                              if quota_refill_per_s is not None
                              else quota_capacity)
        self._lock = threading.Lock()
        self._live: "OrderedDict[int, ServeEngine]" = OrderedDict()
        self._num_tenants: Optional[int] = None
        # tenant-id -> slot (from the checkpoint's fleet_tenant_map);
        # None means raw-slot-index ids (the PR-12 fleets)
        self._tenant_slots: Optional[List[Optional[int]]] = None
        self._quota: Dict[int, object] = {}

    # -- state -----------------------------------------------------------------

    def _ensure_state(self):
        """Restore the full fleet state once and cache it host-side.
        ``restore(tenants=t)`` is DEFINED as ``slice_tenant`` of the
        full restore (train/fleet.py), so slicing the cached state per
        tenant is bit-equal to a per-tenant restore without re-reading
        the checkpoint for every tenant."""
        with self._lock:
            state = self._state
        if state is not None:
            return state
        # target_mesh=None: a serving host restores onto ITSELF (one
        # device), whatever tenant mesh the trainer wrote the
        # checkpoint on — extras-only fleet restores carry no sharded
        # graphs, so the elastic path just lifts the host arrays
        _, state, extra = self._checkpointer.restore(target_mesh=None)
        n = extra.get("fleet_tenants")
        tmap = extra.get("fleet_tenant_map")
        with self._lock:
            if self._state is None:
                self._state = state
                if n is not None:
                    self._num_tenants = int(n)
                if isinstance(tmap, dict) and "slots" in tmap:
                    self._tenant_slots = list(tmap["slots"])
            state = self._state
        return state

    def num_tenants(self) -> int:
        state = self._ensure_state()
        with self._lock:
            if self._num_tenants is None:
                import jax

                leaf = jax.tree_util.tree_leaves(state.gen_params)[0]
                self._num_tenants = int(leaf.shape[0])
            return self._num_tenants

    def _resolve(self, t: int) -> int:
        """The state slot serving tenant id ``t`` — identity for
        raw-slot-index fleets, a ``slots.index`` lookup when the
        checkpoint recorded a lifecycle tenant map.  ``KeyError`` for
        an id the current state does not serve (offboarded ids fall
        out of the map: 404, not someone else's params)."""
        self._ensure_state()
        with self._lock:
            slots = self._tenant_slots
        if slots is not None:
            try:
                return slots.index(t)
            except ValueError:
                raise KeyError(t) from None
        if not 0 <= t < self.num_tenants():
            raise KeyError(t)
        return t

    # -- quotas ----------------------------------------------------------------

    def charge(self, tenant) -> None:
        """Take one token from ``tenant``'s serving quota bucket.

        A no-op when the bank was built without quotas.  Validates the
        id FIRST (unknown tenants get their ``KeyError`` without
        allocating a bucket), then charges under the bank lock (the
        bucket's ``take`` is caller-serialized arithmetic).  Raises
        :class:`TenantThrottledError` when the bucket is empty."""
        if self._quota_capacity is None:
            return
        try:
            t = int(tenant)
        except (TypeError, ValueError):
            raise KeyError(tenant) from None
        self._resolve(t)
        from gan_deeplearning4j_tpu.serve.gateway import TokenBucket

        with self._lock:
            bucket = self._quota.get(t)
            if bucket is None:
                bucket = TokenBucket(self._quota_capacity,
                                     self._quota_refill)
                self._quota[t] = bucket
            ok, retry_after = bucket.take()
        if not ok:
            events.instant("router.tenant_throttled", tenant=t,
                           retry_after_s=round(retry_after, 3))
            raise TenantThrottledError(t, retry_after)

    # -- engines ---------------------------------------------------------------

    def _build_engine(self, tenant: int, slot: int) -> ServeEngine:
        from gan_deeplearning4j_tpu.train.fleet import slice_tenant

        state = self._ensure_state()
        graph = self._build_graph()
        graph.params = slice_tenant(state, slot).gen_params
        infer = ParallelInference(graph, mesh=self._mesh,
                                  buckets=self._buckets)
        admission = (self._admission_factory()
                     if self._admission_factory is not None else None)
        eng = ServeEngine(infer=infer, admission=admission,
                          supervise=self._supervise,
                          watchdog_deadline_s=self._wd_deadline_s)
        # warm every bucket before the first request: tenant engines
        # obey the same closed-compiled-set contract as replicas
        examples = [
            np.zeros((1,) + _array_trailing(graph.input_specs[name]),
                     np.float32)
            for name in graph.input_names]
        eng.warmup(*examples)
        eng.start()
        return eng

    def engine(self, tenant) -> ServeEngine:
        """The live engine for ``tenant`` (built, warmed and started on
        first use; LRU thereafter).  Raises ``KeyError`` for an id the
        current fleet state does not serve — an integer outside
        ``[0, num_tenants)`` for raw-slot fleets, an id missing from
        the recorded tenant map for lifecycle fleets."""
        try:
            t = int(tenant)
        except (TypeError, ValueError):
            raise KeyError(tenant) from None
        with self._lock:
            eng = self._live.get(t)
            if eng is not None:
                self._live.move_to_end(t)
                return eng
        slot = self._resolve(t)  # KeyError for an unknown id
        # build OUTSIDE the lock (compile + thread start are slow);
        # a racing builder for the same tenant loses and is stopped
        built = self._build_engine(t, slot)
        evicted: List[ServeEngine] = []
        with self._lock:
            eng = self._live.get(t)
            if eng is None:
                self._live[t] = built
                eng = built
                while len(self._live) > self._max_live:
                    _, victim = self._live.popitem(last=False)
                    evicted.append(victim)
            else:
                evicted.append(built)
        for victim in evicted:
            victim.stop()
        if evicted:
            events.instant("router.tenant_evicted",
                           evicted=len(evicted), tenant=t)
        return eng

    # -- hotswap ---------------------------------------------------------------

    def hotswap_from(self, directory: Optional[str] = None, *,
                     step: Optional[int] = None,
                     max_step: Optional[int] = None) -> int:
        """Restore a newer fleet checkpoint and push each live
        tenant's fresh slice into its engine in place (zero
        recompile — ``ServeEngine.hotswap_params``; engine objects
        stay the same, so routers holding them stay valid).  The
        publication pipeline's fleet-serving analogue of
        ``ServeEngine.hotswap_from``, with the same contract:
        ``step`` pins exactly (``CheckpointCorruptError`` on
        verification failure), ``max_step`` bounds the newest-first
        verified walk, and ``NoVerifiedCheckpointError`` propagates
        when nothing loads (the bank keeps serving the old state).

        ``directory`` defaults to the bank's own checkpointer;
        state-mode banks must pass it explicitly.  Tenants at or above
        the new fleet size are evicted (their engines stopped outside
        the lock).  A concurrently-building engine that sliced the
        OLD state can land after the swap; the next hotswap refreshes
        it — the bank trades that narrow staleness window for never
        holding its lock across a restore."""
        from gan_deeplearning4j_tpu.train.fleet import (
            FleetCheckpointer,
            slice_tenant,
        )

        if directory is not None:
            # read-side handle: the trainer owns this directory and may
            # be mid-save — never sweep its in-flight tmp dirs
            ck = FleetCheckpointer(str(directory), sweep_debris=False)
        elif self._checkpointer is not None:
            ck = self._checkpointer
        else:
            raise ValueError(
                "a state-mode FleetTenantBank needs an explicit "
                "directory to hotswap from")
        # target_mesh=None: serve whatever mesh the trainer wrote on
        # (see _ensure_state) — hotswapping a 2-device fleet checkpoint
        # onto a 1-device replica is the NORMAL publication case
        got, state, extra = ck.restore(step=step, max_step=max_step,
                                       target_mesh=None)
        n = extra.get("fleet_tenants")
        if n is None:
            import jax

            leaf = jax.tree_util.tree_leaves(state.gen_params)[0]
            n = int(leaf.shape[0])
        n = int(n)
        tmap = extra.get("fleet_tenant_map")
        slots = (list(tmap["slots"])
                 if isinstance(tmap, dict) and "slots" in tmap
                 else None)

        def _slot_of(t: int) -> Optional[int]:
            if slots is not None:
                try:
                    return slots.index(t)
                except ValueError:
                    return None
            return t if 0 <= t < n else None

        evicted: List[ServeEngine] = []
        with self._lock:
            self._state = state
            self._num_tenants = n
            self._tenant_slots = slots
            # a tenant the NEW state no longer serves (offboarded, or
            # beyond the new raw fleet size) is evicted, never remapped
            # onto someone else's slot
            for t in [t for t in self._live if _slot_of(t) is None]:
                evicted.append(self._live.pop(t))
            live = list(self._live.items())
        for victim in evicted:
            victim.stop()
        # push the new slices OUTSIDE the lock (device transfers):
        # each engine's own swap lock serializes against its dispatch
        for t, eng in live:
            eng.hotswap_params(slice_tenant(state, _slot_of(t)).gen_params)
        events.instant("router.fleet_hotswap", step=got, tenants=n,
                       live=len(live), evicted=len(evicted))
        return got

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def stop(self) -> None:
        with self._lock:
            live, self._live = list(self._live.values()), OrderedDict()
        for eng in live:
            eng.stop()
