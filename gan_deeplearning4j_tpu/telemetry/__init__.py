"""Numerics + goodput + event telemetry — the three observability layers
every serious TPU training stack carries and the reference (slf4j step
logs + Spark's executor UI, SURVEY.md §5) never had:

* ``telemetry.ingraph`` — model numerics computed INSIDE the compiled
  step (gradient/param norms, update ratios, NaN/Inf counters), riding
  the existing dispatch as a few extra scalar outputs.  Zero extra
  dispatches, zero host syncs: the arrays materialize on the async
  MetricsLogger worker like the losses do.
* ``telemetry.goodput`` — host-side phase accounting that attributes
  every wall-clock second of a run to data-wait / dispatch / readback /
  checkpoint / eval / other (with per-phase entry counts, ``phase_n``),
  plus the per-run ``run_manifest.json`` (run id, config, versions,
  mesh) that metrics and bench JSONs reference.
* ``telemetry.events`` — the structured event TIMELINE: low-overhead
  spans/instants (monotonic + wall timestamps, thread/host labels) to a
  per-run ``events.jsonl``, a bounded recent-event ring dumped as a
  flight record next to every crash artifact, and a Chrome-trace export
  that merges with ``jax.profiler`` captures.  Served live by
  ``telemetry.exporter`` — a stdlib ``/metrics`` (Prometheus text) +
  ``/healthz`` endpoint behind ``--metrics-port``.
"""

from gan_deeplearning4j_tpu.telemetry import events
from gan_deeplearning4j_tpu.telemetry.events import (
    EventRecorder,
    export_chrome_trace,
)
from gan_deeplearning4j_tpu.telemetry.exporter import (
    MetricsRegistry,
    serve_exporter,
)
from gan_deeplearning4j_tpu.telemetry.goodput import (
    GoodputTimer,
    write_run_manifest,
)
from gan_deeplearning4j_tpu.telemetry.ingraph import (
    NanAlarm,
    NanAlarmError,
    count_nonfinite,
    graph_telemetry,
    tree_norm,
)

__all__ = ["GoodputTimer", "write_run_manifest", "NanAlarm",
           "NanAlarmError", "count_nonfinite", "graph_telemetry",
           "tree_norm", "events", "EventRecorder", "export_chrome_trace",
           "MetricsRegistry", "serve_exporter"]
