"""Numerics + goodput telemetry — the two observability layers every
serious TPU training stack carries and the reference (slf4j step logs +
Spark's executor UI, SURVEY.md §5) never had:

* ``telemetry.ingraph`` — model numerics computed INSIDE the compiled
  step (gradient/param norms, update ratios, NaN/Inf counters), riding
  the existing dispatch as a few extra scalar outputs.  Zero extra
  dispatches, zero host syncs: the arrays materialize on the async
  MetricsLogger worker like the losses do.
* ``telemetry.goodput`` — host-side phase accounting that attributes
  every wall-clock second of a run to data-wait / dispatch / readback /
  checkpoint / eval / other, plus the per-run ``run_manifest.json``
  (run id, config, versions, mesh) that metrics and bench JSONs
  reference.
"""

from gan_deeplearning4j_tpu.telemetry.goodput import (
    GoodputTimer,
    write_run_manifest,
)
from gan_deeplearning4j_tpu.telemetry.ingraph import (
    NanAlarm,
    NanAlarmError,
    count_nonfinite,
    graph_telemetry,
    tree_norm,
)

__all__ = ["GoodputTimer", "write_run_manifest", "NanAlarm",
           "NanAlarmError", "count_nonfinite", "graph_telemetry",
           "tree_norm"]
