"""Structured event tracing — the run's TIMELINE, third telemetry layer.

``ingraph.py`` says what the numerics did and ``goodput.py`` says where
the seconds went; neither says WHAT HAPPENED WHEN.  The reference's only
event record was interleaved slf4j lines and the Spark UI's task list
(SURVEY.md §5); here every notable host-side happening — a checkpoint
save's snapshot/serialize/commit stages, a preemption signal, a recovery
restart, a prefetch stall, a multihost collective — is a structured
event with monotonic AND wall timestamps, thread and host labels, and
arbitrary attributes (usually ``step``).

Three consumers, one recorder:

* ``events.jsonl`` — append-only per-run log (one JSON object per line)
  that tools tail (utils/live_ui.py markers), overlay (utils/
  plot_metrics.py) or post-process.
* a bounded in-memory ring of recent events — the **flight recorder**.
  ``dump_flight_record`` writes it (in-flight spans marked) next to a
  crash artifact, so the NaN snapshot, the preemption marker and a
  recovery restart each carry the timeline that led to them.  The ring
  costs a deque append per event, so it is ALWAYS on, even when no
  ``events.jsonl`` is configured.
* ``export_chrome_trace`` — Chrome-trace JSON of the same events,
  optionally MERGED with a ``jax.profiler`` capture so host events and
  the XLA timeline line up in one Perfetto view (``utils/profiling.py
  maybe_trace`` records the profiler span that anchors the alignment).

Overhead discipline: an event is two ``perf_counter`` reads, a dict, a
deque append and (file-backed only) a buffered line — no device contact,
no jax import, no background thread.  The bench A/B
(``gan_deeplearning4j_tpu.bench --no-events``) keeps the budget honest:
<2% of multistep time.

Instrumented modules call the MODULE-LEVEL ``span``/``instant``, which
forward to the currently installed recorder (``install``/``recording``)
— a trainer installs its run's file-backed recorder for the duration of
``train()`` and the checkpoint/prefetch/collective workers land in the
right file without any plumbing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Union

EVENTS_NAME = "events.jsonl"

# marker vocabulary shared by the plot/live-UI overlays: event name ->
# (legend label, color).  Only events that carry a ``step`` attribute
# can be placed on a step axis.
MARKER_EVENTS = {
    "checkpoint.save": ("checkpoint", "#1baf7a"),
    "checkpoint.emergency": ("emergency save", "#eda100"),
    "preempt.exit": ("preemption", "#4a3aa7"),
    "recovery.restart": ("restart", "#e87ba4"),
    "alarm.nan": ("nan alarm", "#e34948"),
    "alarm.divergence": ("divergence", "#c2571a"),
    "watchdog.timeout": ("watchdog timeout", "#7a1f1f"),
    "rollback.restore": ("rollback", "#8338ec"),
    # runtime trace sanitizers (analysis/sanitizers.py): a post-warmup
    # recompile on the step axis is a perf cliff worth SEEING next to
    # the losses it stalled
    "compile.recompile": ("recompile", "#b5651d"),
    # elastic resume (parallel/elastic.py): a restore that landed on a
    # different mesh and was resharded onto it — the moment the world
    # size changed, next to the losses that must stay banded across it
    "reshard.restore": ("reshard", "#2b6cb0"),
}


def marker_records(event_dicts) -> List[Dict]:
    """Filter raw event dicts down to the step-anchored overlay markers
    — the ONE mapping ``plot_metrics`` and the live UI both render:
    ``[{"step", "name", "label", "color"}]``."""
    out = []
    for ev in event_dicts:
        meta = MARKER_EVENTS.get(ev.get("name"))
        if meta is None or not isinstance(ev.get("step"), (int, float)):
            continue
        out.append({"step": ev["step"], "name": ev["name"],
                    "label": meta[0], "color": meta[1]})
    return out


def _host_label() -> str:
    try:
        import platform

        return f"{platform.node()}:{os.getpid()}"
    except Exception:
        return str(os.getpid())


class EventRecorder:
    """Low-overhead span/instant recorder (see module docstring).

    ``path``: append events as JSONL there (None = ring only).
    ``ring_size``: flight-recorder depth.  ``append=True`` continues an
    existing file (a resumed run keeps its pre-crash timeline, the same
    discipline as the metrics JSONL); default truncates — one file per
    run.  ``enabled=False`` turns the instance into a near-no-op (the
    A/B baseline for the overhead budget).  Thread-safe: checkpoint and
    prefetch workers record concurrently with the training thread."""

    def __init__(self, path: Optional[str] = None, ring_size: int = 256,
                 run_id: Optional[str] = None, flush_every: int = 32,
                 enabled: bool = True, append: bool = False):
        self.path = path
        self.run_id = run_id
        self.enabled = enabled
        self.host = _host_label()
        self.flush_every = flush_every
        self._lock = threading.RLock()
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._ring: "deque" = deque(maxlen=ring_size)
        self._pending: List[str] = []
        self._file = None
        self._header_written = False
        if path and enabled:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._file = open(path, "a" if append else "w")
            # a continued file gets a FRESH header too: each
            # incarnation is a new process (new pid in the host label,
            # new monotonic anchor), and the merger
            # (telemetry/tracing.merge_trace_files) segments the file
            # at every header so each incarnation's events anchor to
            # its own wall clock

    # -- recording ------------------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def _event(self, name: str, ph: str, attrs: Dict) -> Dict:
        ev = {"name": name, "ph": ph, "t": round(self._now(), 6),
              "wall": round(time.time(), 6),
              "thread": threading.current_thread().name}
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._ring.append(ev)
        return ev

    @contextmanager
    def span(self, name: str, **attrs):
        """Timed region.  The event enters the ring at OPEN (so an
        in-flight span is visible to the flight recorder) and gains
        ``dur`` — plus ``error`` if the body raised — at close, when it
        is also written to the JSONL."""
        if not self.enabled:
            yield None
            return
        ev = self._event(name, "X", attrs)
        try:
            yield ev
        except BaseException as e:
            ev["error"] = repr(e)
            raise
        finally:
            ev["dur"] = round(self._now() - ev["t"], 6)
            self._write(ev)

    def instant(self, name: str, **attrs) -> Optional[Dict]:
        """Point-in-time event."""
        if not self.enabled:
            return None
        ev = self._event(name, "i", attrs)
        self._write(ev)
        return ev

    def complete(self, name: str, dur: float,
                 t_start: Optional[float] = None,
                 **attrs) -> Optional[Dict]:
        """Already-timed span: a ph="X" event whose duration the
        caller measured itself (``time.perf_counter`` seconds).  This
        is how pipelined stages record — the serving engine learns a
        batch's dispatch time one cycle AFTER the dispatch, so the
        span cannot be an open ``with`` block.  ``t_start`` (a raw
        ``perf_counter`` value) back-dates the event to when the work
        actually began; ``wall`` is derived from the recorder's own
        anchor so merged timelines stay on one clock."""
        if not self.enabled:
            return None
        t = ((t_start - self._t0) if t_start is not None
             else self._now() - dur)
        ev = {"name": name, "ph": "X", "t": round(t, 6),
              "wall": round(self._wall0 + t, 6),
              "thread": threading.current_thread().name,
              "dur": round(float(dur), 6)}
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._ring.append(ev)
        self._write(ev)
        return ev

    def _write(self, ev: Dict) -> None:
        if self._file is None:
            return
        with self._lock:
            self._pending.append(json.dumps(ev, default=str))
            if len(self._pending) >= self.flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._pending and self._file is not None:
            if not self._header_written:
                # header line, deferred to the first flush so it carries
                # the run_id a caller set AFTER construction (the
                # trainer learns it from run_manifest.json); the run
                # metadata lives here once, keeping per-event lines small
                self._header_written = True
                self._file.write(json.dumps(
                    {"name": "recorder.start", "ph": "i", "t": 0.0,
                     "wall": round(self._wall0, 6),
                     "run_id": self.run_id, "host": self.host}) + "\n")
            self._file.write("\n".join(self._pending) + "\n")
            self._file.flush()
            self._pending = []

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        """Flush and close the file sink; the ring (and the flight
        recorder) stay readable — a post-run failure handler can still
        dump the timeline."""
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self) -> "EventRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- flight recorder ------------------------------------------------------

    def recent(self) -> List[Dict]:
        """Snapshot of the ring, oldest first.  Spans still open carry
        no ``dur`` — they are the "what was in flight" signal."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def dump_flight_record(self, directory: str, reason: str,
                           extra: Optional[Dict] = None) -> str:
        """Write ``flight_record_{reason}.json`` under ``directory``:
        the recent-event ring plus run metadata, fsynced (a crash dump
        that does not survive the crash recorded nothing).  Returns the
        path; never raises (the dump must not mask the failure being
        dumped)."""
        events = self.recent()
        for ev in events:
            if ev.get("ph") == "X" and "dur" not in ev:
                ev["in_flight"] = True
        payload = {
            "reason": reason,
            "run_id": self.run_id,
            "host": self.host,
            "wall": round(time.time(), 6),
            "events": events,
        }
        if extra:
            payload.update(extra)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)
        path = os.path.join(directory, f"flight_record_{safe}.json")
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            return path  # a read-only res dir must not mask the crash
        return path


# -- the installed recorder ---------------------------------------------------

# ring-only default: flight records work even before any run configures
# a file-backed recorder
_DEFAULT = EventRecorder()
_current: EventRecorder = _DEFAULT


def current() -> EventRecorder:
    return _current


def install(recorder: Optional[EventRecorder]) -> EventRecorder:
    """Make ``recorder`` the target of the module-level ``span``/
    ``instant``/``dump_flight_record``; returns the PREVIOUS recorder so
    callers can restore it (None restores the ring-only default)."""
    global _current
    prev = _current
    _current = recorder if recorder is not None else _DEFAULT
    return prev


@contextmanager
def recording(recorder: EventRecorder):
    """Install ``recorder`` for the duration of the block, then restore
    the previous one and close the file sink."""
    prev = install(recorder)
    try:
        yield recorder
    finally:
        install(prev)
        recorder.close()


def span(name: str, **attrs):
    return _current.span(name, **attrs)


def instant(name: str, **attrs):
    return _current.instant(name, **attrs)


def complete(name: str, dur: float, t_start: Optional[float] = None,
             **attrs):
    return _current.complete(name, dur, t_start=t_start, **attrs)


def dump_flight_record(directory: str, reason: str,
                       extra: Optional[Dict] = None) -> str:
    return _current.dump_flight_record(directory, reason, extra)


# -- chrome trace export ------------------------------------------------------


def read_events(path: str) -> List[Dict]:
    """Load an ``events.jsonl`` (malformed lines skipped — the file may
    be mid-append when read)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:  # gan4j-lint: disable=swallowed-exception — the file may be mid-append; a torn last line is expected, not evidence
                continue
    return out


def export_chrome_trace(source: Union[str, List[Dict], EventRecorder],
                        out_path: str,
                        jax_trace_dir: Optional[str] = None) -> str:
    """Write a Chrome-trace JSON of the recorded host events; with
    ``jax_trace_dir``, MERGE the ``jax.profiler`` capture under it so
    host spans and the XLA timeline share one Perfetto view.

    Host timestamps are wall-clock microseconds.  The profiler's own
    ``ts`` base is arbitrary, so alignment anchors on (in order): the
    ``host_anchor.json`` sidecar ``utils/profiling.maybe_trace`` drops
    into the capture dir (wall start of the capture), a
    ``profiler.trace`` span in the events, else the earliest host event
    — best-effort, but both clocks then at least share an origin.
    Captures whose ``ts`` is already epoch-scale (recent XProf) are
    detected and left unshifted."""
    if isinstance(source, EventRecorder):
        events = source.recent()
    elif isinstance(source, str):
        events = read_events(source)
    else:
        events = list(source)
    events = [e for e in events if "wall" in e]

    trace: List[Dict] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": "host events (gan4j)"}},
    ]
    tids: Dict[str, int] = {}
    for ev in events:
        thread = str(ev.get("thread", "main"))
        if thread not in tids:
            tids[thread] = len(tids) + 1
            trace.append({"ph": "M", "pid": 1, "tid": tids[thread],
                          "name": "thread_name",
                          "args": {"name": thread}})
        args = {k: v for k, v in ev.items()
                if k not in ("name", "ph", "t", "wall", "dur", "thread")}
        entry = {"name": ev["name"], "pid": 1, "tid": tids[thread],
                 "ts": ev["wall"] * 1e6, "args": args}
        if ev.get("ph") == "X":
            entry["ph"] = "X"
            entry["dur"] = float(ev.get("dur", 0.0)) * 1e6
        else:
            entry["ph"] = "i"
            entry["s"] = "t"
        trace.append(entry)

    if jax_trace_dir:
        from gan_deeplearning4j_tpu.utils.profiling import _trace_events

        jax_events = [e for e in _trace_events(jax_trace_dir)
                      if "ts" in e or e.get("ph") == "M"]
        ts_values = [e["ts"] for e in jax_events if "ts" in e]
        if ts_values:
            if min(ts_values) > 1e14:
                # the capture already uses epoch-scale microseconds
                # (XProf does on recent versions): both clocks share an
                # origin, no shift needed
                shift = 0.0
            else:
                anchor = None
                sidecar = os.path.join(jax_trace_dir,
                                       "host_anchor.json")
                try:
                    with open(sidecar) as f:
                        anchor = float(
                            json.load(f)["wall_start"]) * 1e6
                except (OSError, ValueError, KeyError, TypeError):  # gan4j-lint: disable=swallowed-exception — missing/garbled sidecar: alignment falls back to the anchor-span path below
                    pass
                if anchor is None:
                    for ev in events:
                        if ev.get("name") == "profiler.trace":
                            anchor = ev["wall"] * 1e6
                            break
                if anchor is None and events:
                    anchor = min(e["wall"] for e in events) * 1e6
                shift = ((anchor - min(ts_values))
                         if anchor is not None else 0.0)
            for e in jax_events:
                e = dict(e)
                if "ts" in e:
                    e["ts"] = e["ts"] + shift
                trace.append(e)

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return out_path
