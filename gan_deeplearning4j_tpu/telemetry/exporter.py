"""Live ``/metrics`` + ``/healthz`` endpoint — the scrape surface.

The reference's only live surface was the Spark UI; utils/live_ui.py
rebuilt the human half (a dashboard).  This module is the MACHINE half:
a stdlib ``ThreadingHTTPServer`` on a daemon thread serving

* ``/metrics``  — Prometheus text format (version 0.0.4) rendered from a
  thread-safe counter/gauge registry;
* ``/healthz``  — 200 + a small JSON liveness document (last-record age,
  run id) while the process serves, the conventional k8s liveness probe
  target.

The registry is fed from the things the stack already computes:
``MetricsLogger.on_record`` (every materialized step record updates the
step/loss/NaN series on the logger's worker thread — the training thread
pays nothing), ``GoodputTimer`` phase totals (a scrape-time callback
reads the live ledger), and the in-graph ``nonfinite`` counters.  Both
protocol mains, ``roadmap_main`` and ``bench.py`` expose it as
``--metrics-port`` (0 = ephemeral, the port is printed).

Metric names (all ``gan4j_``-prefixed):

  gan4j_steps_total            counter  materialized step records
  gan4j_step                   gauge    last step seen
  gan4j_nonfinite_total        counter  in-graph NaN/Inf counter sum
  gan4j_d_loss / gan4j_g_loss / gan4j_classifier_loss   gauges
  gan4j_examples_per_sec       gauge    last per-step throughput sample
  gan4j_goodput_seconds{phase} gauge    GoodputTimer phase totals
  gan4j_goodput_compute_fraction  gauge the headline goodput number
  gan4j_data_retries_total     counter  transient-I/O retries (resilient
                                        data plane, data/resilient.py)
  gan4j_data_quarantined_total counter  corrupt records quarantined
  gan4j_data_last_error_age_seconds  gauge  age of the last data incident
  gan4j_recompiles_total       counter  post-warmup XLA recompiles seen
                                        by the RecompileSentinel
                                        (analysis/sanitizers.py) — any
                                        increment after warmup means
                                        the fused hot path lost its
                                        cached program
  gan4j_mesh_devices           gauge    devices in the live training
                                        mesh (elastic resume,
                                        parallel/elastic.py — drops
                                        after a fleet shrink are the
                                        signal)
  gan4j_reshard_total          counter  checkpoint restores that landed
                                        on a DIFFERENT mesh and were
                                        resharded onto it
  gan4j_reshard_seconds        gauge    cumulative time paid resharding
  gan4j_lock_wait_seconds_total counter seconds threads spent BLOCKED
                                        acquiring tracked locks under
                                        the lockdep sanitizer
                                        (analysis/sanitizers.py) — the
                                        lock-contention trend
  gan4j_lock_inversions_total  counter  observed lock-order inversions
                                        (any increment = a potential
                                        deadlock witnessed at runtime;
                                        docs/STATIC_ANALYSIS.md,
                                        rule lock-order-cycle)
  gan4j_serve_requests_total   counter  generation requests served to
                                        completion (serve/engine.py)
  gan4j_serve_shed_total       counter  requests rejected by admission
                                        control (serve/admission.py) —
                                        any sustained increase means
                                        the service is at capacity
  gan4j_serve_queue_depth      gauge    admission queue depth now
  gan4j_serve_batch_fill       gauge    real rows / padded bucket rows
                                        of recent dispatches (low fill
                                        = paying for dead rows)
  gan4j_serve_p99_ms           gauge    p99 latency of the engine's
                                        recent-request window
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

_LOSS_GAUGES = ("d_loss", "g_loss", "classifier_loss", "examples_per_sec")


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


class MetricsRegistry:
    """Thread-safe counter/gauge registry with Prometheus text render.

    ``add_callback(fn)`` registers a scrape-time hook: ``fn(registry)``
    is called (under the registry lock — it may only ``set``/``inc``)
    at every ``render()``, so values that live elsewhere (the goodput
    ledger) are read when asked for, not mirrored on every step."""

    def __init__(self):
        self._lock = threading.RLock()
        # the headline counters exist at 0 from the first scrape — a
        # monitoring rule on gan4j_nonfinite_total must see the series
        # before the first (hopefully never) increment
        self._counters: Dict[Tuple[str, tuple], float] = {
            ("gan4j_steps_total", ()): 0.0,
            ("gan4j_nonfinite_total", ()): 0.0,
            ("gan4j_watchdog_timeouts_total", ()): 0.0,
            ("gan4j_rollback_total", ()): 0.0,
            ("gan4j_data_retries_total", ()): 0.0,
            ("gan4j_data_quarantined_total", ()): 0.0,
            # recompile sentinel (analysis/sanitizers.py): an alert
            # rule on this series must see it at 0 from the first
            # scrape — a recompile storm is exactly when a scrape
            # might not come back
            ("gan4j_recompiles_total", ()): 0.0,
            # elastic mesh (parallel/elastic.py): a reshard-on-restore
            # is rare by design, so the alert rule needs the series at
            # 0 long before the first one happens
            ("gan4j_reshard_total", ()): 0.0,
            # lockdep sanitizer (analysis/sanitizers.py): the inversion
            # counter must exist at 0 from the first scrape — an
            # inversion is exactly the event after which the next
            # scrape may never come — and the wait-time series is the
            # lock-contention trend an alert watches long before one
            ("gan4j_lock_inversions_total", ()): 0.0,
            ("gan4j_lock_wait_seconds_total", ()): 0.0,
            # serving plane (serve/engine.py): the request/shed
            # counters exist at 0 from the first scrape — the shed
            # alert rule must see the series before the first overload
            ("gan4j_serve_requests_total", ()): 0.0,
            ("gan4j_serve_shed_total", ()): 0.0,
            # network front door (serve/gateway.py): the wire-level
            # request/reject counters exist at 0 from the first scrape
            # — a reject alert rule must see the series before the
            # first abusive caller shows up
            ("gan4j_gateway_requests_total", ()): 0.0,
            ("gan4j_gateway_rejected_total", ()): 0.0,
            # serving mesh (serve/mesh.py): ejections exist at 0 from
            # the first scrape — an ejection alert rule must see the
            # series before the first replica dies
            ("gan4j_mesh_ejected_total", ()): 0.0,
            # control plane (serve/controlplane.py): scale/replace/
            # rollback counters exist at 0 from the first scrape — a
            # rollback alert is exactly the one that must not wait for
            # its first firing to learn the series name
            ("gan4j_controlplane_scale_events_total", ()): 0.0,
            ("gan4j_controlplane_replaced_total", ()): 0.0,
            ("gan4j_controlplane_rollbacks_total", ()): 0.0,
            # client keep-alive pool (serve/client.py): pool reuse /
            # stale-socket reconnects / retry counters exist at 0 from
            # the first scrape — a reconnect storm is a server-restart
            # signal an alert rule must already know the name of
            ("gan4j_client_reused_total", ()): 0.0,
            ("gan4j_client_reconnects_total", ()): 0.0,
            ("gan4j_client_retried_total", ()): 0.0,
            # checkpoint publication (serve/publisher.py): a rejected
            # checkpoint is exactly the event an alert rule exists for
            # — the series must be scrapeable before the first
            # poisoned checkpoint ever shows up
            ("gan4j_publish_rejected_total", ()): 0.0,
            ("gan4j_publish_promoted_total", ()): 0.0,
            # tenant lifecycle (train/lifecycle.py): quarantine is the
            # per-tenant fault-domain event an alert rule exists for —
            # all four lifecycle counters exist at 0 from the first
            # scrape, before the first onboard ever happens
            ("gan4j_fleet_tenant_quarantined_total", ()): 0.0,
            ("gan4j_fleet_tenant_onboarded_total", ()): 0.0,
            ("gan4j_fleet_tenant_offboarded_total", ()): 0.0,
            ("gan4j_fleet_tenant_throttled_total", ()): 0.0,
        }
        self._gauges: Dict[Tuple[str, tuple], float] = {
            # age since the last data-plane incident; 0 until one
            # happens (pre-created so alert rules see the series from
            # the first scrape, like the counters above)
            ("gan4j_data_last_error_age_seconds", ()): 0.0,
            # elastic-mesh surface: mesh size 0 = "no mesh formed yet";
            # the feed (observe_mesh) raises it to the live count
            ("gan4j_mesh_devices", ()): 0.0,
            ("gan4j_reshard_seconds", ()): 0.0,
            # multi-tenant fleet surface (train/fleet.py): 0 tenants =
            # "no fleet running"; the feed (observe_fleet) raises them —
            # pre-created like everything above so dashboards and alert
            # rules see the series from the first scrape
            ("gan4j_fleet_tenants", ()): 0.0,
            ("gan4j_fleet_steps_per_sec", ()): 0.0,
            ("gan4j_fleet_dispatch_ms", ()): 0.0,
            # tenant-lifecycle gauges (train/lifecycle.py): cohort
            # count, live quarantine count (per-tenant named series
            # appear labeled, e.g. ...{tenant="3"}), and the onboard
            # latency headline that lands next to tenants·steps/sec
            ("gan4j_fleet_cohorts", ()): 0.0,
            ("gan4j_fleet_tenant_quarantined", ()): 0.0,
            ("gan4j_fleet_onboard_latency_ms", ()): 0.0,
            # serving-plane gauges (serve/engine.py): 0 = "no engine
            # running"; the feed (observe_serve) raises them
            ("gan4j_serve_queue_depth", ()): 0.0,
            ("gan4j_serve_batch_fill", ()): 0.0,
            ("gan4j_serve_p99_ms", ()): 0.0,
            # gateway gauges (serve/gateway.py): 0 connections and 0
            # healthy replicas = "no gateway running"; the feed
            # (observe_gateway) raises them
            ("gan4j_gateway_active_connections", ()): 0.0,
            ("gan4j_gateway_replica_healthy", ()): 0.0,
            # serving-mesh gauges (serve/mesh.py — replica PROCESSES,
            # distinct from gan4j_mesh_devices, the elastic-training
            # device mesh): 0 replicas = "no mesh running"; the feed
            # (observe_serving_mesh) raises them
            ("gan4j_mesh_replicas", ()): 0.0,
            ("gan4j_mesh_replicas_healthy", ()): 0.0,
            # control-plane gauge: the fleet size the controller is
            # currently holding (observe_controlplane raises it)
            ("gan4j_controlplane_replicas", ()): 0.0,
            # resource telemetry (telemetry/resources.py): the soak
            # gauges exist at 0 from the first scrape — a leak trend
            # rule needs the series long before the monitor starts
            ("gan4j_resource_rss_bytes", ()): 0.0,
            ("gan4j_resource_device_bytes", ()): 0.0,
            ("gan4j_resource_open_fds", ()): 0.0,
            ("gan4j_resource_threads", ()): 0.0,
            # publication gauges (serve/publisher.py): last promoted
            # step and its age; 0 = "nothing published yet" — the feed
            # (observe_publication) raises them
            ("gan4j_publish_last_step", ()): 0.0,
            ("gan4j_publish_age_seconds", ()): 0.0,
        }
        self._callbacks: List[Callable[["MetricsRegistry"], None]] = []
        self.run_id: Optional[str] = None
        self._last_record_wall: Optional[float] = None
        # training-health feed (train/watchdog.py): a callable returning
        # the watchdog's report dict; drives the /healthz "stalled"
        # contract (503 once the heartbeat goes quiet past the deadline)
        # and the gan4j_watchdog_* series
        self._watchdog_fn: Optional[Callable[[], Optional[Dict]]] = None
        # data-plane feed (data/resilient.py DataHealth.report): drives
        # the gan4j_data_* series and the /healthz "data" block
        self._data_fn: Optional[Callable[[], Optional[Dict]]] = None
        # elastic-mesh feed (GANTrainer._mesh_report): drives the
        # gan4j_mesh_devices / gan4j_reshard_* series and the /healthz
        # "mesh" block (ok:false while mesh formation is quorum-blocked)
        self._mesh_fn: Optional[Callable[[], Optional[Dict]]] = None
        # fleet feed (train/fleet.FleetTrainer._fleet_report): drives
        # the gan4j_fleet_* series and the /healthz "fleet" block
        self._fleet_fn: Optional[Callable[[], Optional[Dict]]] = None
        # serving feed (serve/engine.ServeEngine.report): drives the
        # gan4j_serve_* series and the /healthz "serve" block
        self._serve_fn: Optional[Callable[[], Optional[Dict]]] = None
        # gateway feed (serve/gateway.Gateway.report): drives the
        # gan4j_gateway_* series and the /healthz "gateway" block
        self._gateway_fn: Optional[Callable[[], Optional[Dict]]] = None
        # serving-mesh feed (serve/mesh.MeshRouter.report): drives the
        # gan4j_mesh_replicas/ejected series and the /healthz
        # "serving_mesh" block (named to keep it distinct from the
        # elastic-training "mesh" block above)
        self._serving_mesh_fn: Optional[
            Callable[[], Optional[Dict]]] = None
        # control-plane feed (serve/controlplane.ControlPlane.report):
        # drives the gan4j_controlplane_* series and the /healthz
        # "controlplane" block (ok:false once a deploy goes fatal)
        self._controlplane_fn: Optional[
            Callable[[], Optional[Dict]]] = None
        # client feed (serve/client.GatewayClient.report): drives the
        # gan4j_client_* series
        self._client_fn: Optional[Callable[[], Optional[Dict]]] = None
        # resource feed (telemetry/resources.ResourceMonitor.report):
        # drives the gan4j_resource_* gauges and the /healthz
        # "resources" block
        self._resources_fn: Optional[
            Callable[[], Optional[Dict]]] = None
        # publication feed (serve/publisher.CheckpointPublisher.report):
        # drives the gan4j_publish_* series, the /healthz
        # "publication" block, and the top-level "serving_stale" flag
        # (true while the serving plane runs on old weights because no
        # fresh checkpoint has arrived / survived verification)
        self._publication_fn: Optional[
            Callable[[], Optional[Dict]]] = None

    @staticmethod
    def _key(name: str, labels: Optional[Dict]) -> Tuple[str, tuple]:
        return (name, tuple(sorted((labels or {}).items())))

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict] = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + float(value)

    def set(self, name: str, value: float,
            labels: Optional[Dict] = None) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def set_counter(self, name: str, value: float,
                    labels: Optional[Dict] = None) -> None:
        """Monotonic set: raise the counter to ``value`` if it is
        higher (for counters whose source of truth lives elsewhere —
        e.g. the rollback manager's lifetime count, mirrored at scrape
        time; a counter must never go backwards)."""
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = max(self._counters.get(k, 0.0),
                                    float(value))

    def add_callback(self, fn: Callable[["MetricsRegistry"], None]) -> None:
        with self._lock:
            self._callbacks.append(fn)

    # -- feeds ----------------------------------------------------------------

    def observe_record(self, rec: Dict) -> None:
        """``MetricsLogger.on_record`` hook: one materialized record
        (step or run-level) updates the scrape series.  Runs on the
        logger's worker thread — locking only, no I/O."""
        step = rec.get("step")
        with self._lock:
            self._last_record_wall = time.time()
            if step is None:
                return  # run-level record (goodput summary): no step axis
            self.inc("gan4j_steps_total")
            self.set("gan4j_step", step)
            for k in _LOSS_GAUGES:
                v = rec.get(k)
                if isinstance(v, (int, float)):
                    self.set(f"gan4j_{k}", v)
            nf = rec.get("nonfinite")
            if isinstance(nf, (int, float)) and math.isfinite(nf) and nf > 0:
                self.inc("gan4j_nonfinite_total", nf)

    def observe_goodput(self, report_fn: Callable[[], Optional[Dict]]) -> None:
        """Register the goodput feed: ``report_fn`` returns a
        ``GoodputTimer.report()`` dict (or None before the run starts);
        its phase totals become labeled gauges at scrape time."""

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            for k, v in rep.items():
                if k == "compute_fraction":
                    reg.set("gan4j_goodput_compute_fraction", v)
                elif isinstance(v, (int, float)) and k != "wall_s":
                    reg.set("gan4j_goodput_seconds", v,
                            labels={"phase": k})
            if "wall_s" in rep:
                reg.set("gan4j_goodput_wall_seconds", rep["wall_s"])

        self.add_callback(cb)

    def observe_watchdog(self, report_fn: Callable[[], Optional[Dict]]
                         ) -> None:
        """Register the hang-watchdog feed: ``report_fn`` returns a
        ``HeartbeatWatchdog.report()`` dict (last beat age, effective
        deadline, timeout count, stalled flag).  Scrapes mirror it into
        the ``gan4j_watchdog_*`` series, and ``/healthz`` answers 503 +
        ``"stalled": true`` while the heartbeat is quiet past the
        deadline — the liveness probe sees a hang the moment the
        watchdog does, without waiting for the process to die."""
        with self._lock:
            self._watchdog_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            age = rep.get("last_beat_age_s")
            if isinstance(age, (int, float)):
                reg.set("gan4j_watchdog_last_beat_age_seconds", age)
            deadline = rep.get("deadline_s")
            if isinstance(deadline, (int, float)):
                reg.set("gan4j_watchdog_deadline_seconds", deadline)
            reg.set("gan4j_watchdog_stalled",
                    1.0 if rep.get("stalled") else 0.0)
            reg.set_counter("gan4j_watchdog_timeouts_total",
                            float(rep.get("timeouts_total", 0)))

        self.add_callback(cb)

    def observe_data(self, report_fn: Callable[[], Optional[Dict]]) -> None:
        """Register the data-plane feed: ``report_fn`` returns a
        ``DataHealth.report()`` dict (data/resilient.py — retry and
        quarantine totals, last-incident age, budget verdict).  Scrapes
        mirror it into the ``gan4j_data_*`` series and ``/healthz``
        carries it as the ``"data"`` block, so a run chewing through
        its quarantine budget is visible BEFORE the budget-exhaustion
        fatality."""
        with self._lock:
            self._data_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set_counter("gan4j_data_retries_total",
                            float(rep.get("retries_total", 0)))
            reg.set_counter("gan4j_data_quarantined_total",
                            float(rep.get("quarantined_total", 0)))
            age = rep.get("last_error_age_s")
            if isinstance(age, (int, float)):
                reg.set("gan4j_data_last_error_age_seconds", age)

        self.add_callback(cb)

    def observe_mesh(self, report_fn: Callable[[], Optional[Dict]]) -> None:
        """Register the elastic-mesh feed: ``report_fn`` returns a
        ``GANTrainer._mesh_report`` dict (live mesh device count,
        reshard accounting, formation state).  Scrapes mirror it into
        ``gan4j_mesh_devices`` / ``gan4j_reshard_*`` and ``/healthz``
        carries it as the ``"mesh"`` block — ``ok: false`` while mesh
        formation is quorum-blocked (the agree_world barrier), so a
        probe can tell "waiting for survivors" from "training"."""
        with self._lock:
            self._mesh_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            devices = rep.get("devices")
            if isinstance(devices, (int, float)):
                reg.set("gan4j_mesh_devices", float(devices))
            reg.set_counter("gan4j_reshard_total",
                            float(rep.get("reshard_total", 0)))
            secs = rep.get("reshard_seconds")
            if isinstance(secs, (int, float)):
                reg.set("gan4j_reshard_seconds", float(secs))

        self.add_callback(cb)

    def observe_fleet(self, report_fn: Callable[[], Optional[Dict]]) -> None:
        """Register the fleet feed: ``report_fn`` returns a
        ``FleetTrainer._fleet_report`` dict (tenant count, fused
        throughput, dispatch latency).  Scrapes mirror it into the
        ``gan4j_fleet_*`` series and ``/healthz`` carries it as the
        ``"fleet"`` block — the bench-of-record headline
        (tenants·steps/sec) is ``tenants * steps_per_sec`` of exactly
        these two gauges.

        A lifecycle fleet (``FleetManager.report``) additionally
        carries a ``"tenants_detail"`` sub-dict; scrapes mirror it
        into the ``gan4j_fleet_tenant_*`` / ``gan4j_fleet_cohorts`` /
        ``gan4j_fleet_onboard_latency_ms`` series — each quarantined
        tenant is NAMED via a labeled gauge
        (``gan4j_fleet_tenant_quarantined{tenant="3"} 1``) — and
        ``/healthz`` carries it as ``fleet.tenants_detail``."""
        with self._lock:
            self._fleet_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            for key, series in (("tenants", "gan4j_fleet_tenants"),
                                ("steps_per_sec",
                                 "gan4j_fleet_steps_per_sec"),
                                ("dispatch_ms", "gan4j_fleet_dispatch_ms")):
                v = rep.get(key)
                if isinstance(v, (int, float)):
                    reg.set(series, float(v))
            det = rep.get("tenants_detail")
            if not isinstance(det, dict):
                return
            for key, series in (("cohorts", "gan4j_fleet_cohorts"),
                                ("onboard_latency_ms",
                                 "gan4j_fleet_onboard_latency_ms")):
                v = det.get(key)
                if isinstance(v, (int, float)):
                    reg.set(series, float(v))
            quarantined = det.get("quarantined") or []
            reg.set("gan4j_fleet_tenant_quarantined",
                    float(len(quarantined)))
            for t in quarantined:
                reg.set("gan4j_fleet_tenant_quarantined", 1.0,
                        labels={"tenant": str(t)})

        self.add_callback(cb)

    def observe_serve(self, report_fn: Callable[[], Optional[Dict]]) -> None:
        """Register the serving-plane feed: ``report_fn`` returns a
        ``ServeEngine.report()`` dict (request/shed totals, queue
        depth, batch fill, latency percentiles).  Scrapes mirror it
        into the ``gan4j_serve_*`` series and ``/healthz`` carries it
        as the ``"serve"`` block — the bench-of-record headline
        (saturation req/s at a p99 SLO, RESULTS.md) is measured from
        exactly these series."""
        with self._lock:
            self._serve_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set_counter("gan4j_serve_requests_total",
                            float(rep.get("requests_total", 0)))
            reg.set_counter("gan4j_serve_shed_total",
                            float(rep.get("shed_total", 0)))
            for key, series in (("queue_depth", "gan4j_serve_queue_depth"),
                                ("batch_fill", "gan4j_serve_batch_fill"),
                                ("p99_ms", "gan4j_serve_p99_ms")):
                v = rep.get(key)
                if isinstance(v, (int, float)):
                    reg.set(series, float(v))

        self.add_callback(cb)

    def observe_gateway(self, report_fn: Callable[[], Optional[Dict]]
                        ) -> None:
        """Register the network-front-door feed: ``report_fn`` returns
        a ``Gateway.report()`` dict (wire request/reject totals, live
        connection count, replica health).  Scrapes mirror it into the
        ``gan4j_gateway_*`` series and ``/healthz`` carries it as the
        ``"gateway"`` block — ``ok: false`` the moment the router has
        zero healthy replicas (the front door is up but nothing behind
        it can serve)."""
        with self._lock:
            self._gateway_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set_counter("gan4j_gateway_requests_total",
                            float(rep.get("requests_total", 0)))
            reg.set_counter("gan4j_gateway_rejected_total",
                            float(rep.get("rejected_total", 0)))
            reg.set("gan4j_gateway_active_connections",
                    float(rep.get("active_connections", 0)))
            reg.set("gan4j_gateway_replica_healthy",
                    float(rep.get("replicas_healthy", 0)))

        self.add_callback(cb)

    def observe_serving_mesh(self, report_fn:
                             Callable[[], Optional[Dict]]) -> None:
        """Register the serving-mesh feed: ``report_fn`` returns a
        ``MeshRouter.report()`` dict (replica count, healthy count,
        lifetime ejections).  Scrapes mirror it into the
        ``gan4j_mesh_replicas``/``gan4j_mesh_ejected_total`` series
        and ``/healthz`` carries it as the ``"serving_mesh"`` block —
        ``ok: false`` the moment zero replicas are healthy.  (The
        ``"mesh"`` block is the elastic-training DEVICE mesh; this one
        counts replica PROCESSES.)"""
        with self._lock:
            self._serving_mesh_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set("gan4j_mesh_replicas",
                    float(rep.get("replicas", 0)))
            reg.set("gan4j_mesh_replicas_healthy",
                    float(rep.get("replicas_healthy", 0)))
            reg.set_counter("gan4j_mesh_ejected_total",
                            float(rep.get("ejected_total", 0)))

        self.add_callback(cb)

    def observe_controlplane(self, report_fn:
                             Callable[[], Optional[Dict]]) -> None:
        """Register the control-plane feed: ``report_fn`` returns a
        ``ControlPlane.report()`` dict (fleet size, scale/replace/
        rollback totals, deploy state).  Scrapes mirror it into the
        ``gan4j_controlplane_*`` series and ``/healthz`` carries it
        as the ``"controlplane"`` block — ``ok: false`` once a
        deployment has gone FATAL (budget exhausted) and a human must
        look."""
        with self._lock:
            self._controlplane_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set("gan4j_controlplane_replicas",
                    float(rep.get("replicas", 0)))
            reg.set_counter(
                "gan4j_controlplane_scale_events_total",
                float(rep.get("scale_up_total", 0))
                + float(rep.get("scale_down_total", 0)))
            reg.set_counter("gan4j_controlplane_replaced_total",
                            float(rep.get("replaced_total", 0)))
            reg.set_counter("gan4j_controlplane_rollbacks_total",
                            float(rep.get("rollbacks_total", 0)))

        self.add_callback(cb)

    def observe_publication(self, report_fn:
                            Callable[[], Optional[Dict]]) -> None:
        """Register the checkpoint-publication feed: ``report_fn``
        returns a ``CheckpointPublisher.report()`` dict (last promoted
        step, age, promote/reject totals).  Scrapes mirror it into the
        ``gan4j_publish_*`` series and ``/healthz`` carries it as the
        ``"publication"`` block plus a top-level ``serving_stale``
        flag — the graceful-degradation signal: replicas still answer
        (status stays "ok") but on weights older than the staleness
        budget, which is a trainer-down page, not a serving page."""
        with self._lock:
            self._publication_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set("gan4j_publish_last_step",
                    float(rep.get("last_step", 0)))
            reg.set("gan4j_publish_age_seconds",
                    float(rep.get("age_seconds", 0.0)))
            reg.set_counter("gan4j_publish_promoted_total",
                            float(rep.get("promoted_total", 0)))
            reg.set_counter("gan4j_publish_rejected_total",
                            float(rep.get("rejected_total", 0)))

        self.add_callback(cb)

    def observe_client(self, report_fn: Callable[[], Optional[Dict]]
                       ) -> None:
        """Register a ``GatewayClient.report()`` feed: connection-pool
        reuse, reconnects, and retried requests become the
        ``gan4j_client_*`` series — the caller-side view of the wire
        that pairs with the gateway's server-side counters (a
        reconnect spike with a flat gateway error rate means the
        NETWORK between them is flapping, not the service)."""
        with self._lock:
            self._client_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            reg.set_counter("gan4j_client_reused_total",
                            float(rep.get("reused_total", 0)))
            reg.set_counter("gan4j_client_reconnects_total",
                            float(rep.get("reconnects_total", 0)))
            reg.set_counter("gan4j_client_retried_total",
                            float(rep.get("retried_total", 0)))

        self.add_callback(cb)

    def observe_resources(self, report_fn:
                          Callable[[], Optional[Dict]]) -> None:
        """Register the process-resource feed: ``report_fn`` returns a
        ``ResourceMonitor.report()`` dict (latest RSS/device-bytes/
        fd/thread sample).  Scrapes mirror it into the
        ``gan4j_resource_*`` gauges and ``/healthz`` carries it as the
        ``"resources"`` block — the live counterpart of the soak
        gate's offline ``leak_verdict`` (telemetry/resources.py)."""
        with self._lock:
            self._resources_fn = report_fn

        def cb(reg: "MetricsRegistry") -> None:
            rep = report_fn()
            if not rep:
                return
            for key, series in (
                    ("rss_bytes", "gan4j_resource_rss_bytes"),
                    ("device_bytes", "gan4j_resource_device_bytes"),
                    ("open_fds", "gan4j_resource_open_fds"),
                    ("threads", "gan4j_resource_threads")):
                v = rep.get(key)
                if isinstance(v, (int, float)):
                    reg.set(series, float(v))

        self.add_callback(cb)

    # -- render ---------------------------------------------------------------

    def render(self) -> str:
        with self._lock:
            for fn in self._callbacks:
                try:
                    fn(self)
                except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the scrape
                    pass
            lines: List[str] = []
            for kind, series in (("counter", self._counters),
                                 ("gauge", self._gauges)):
                seen = set()
                for (name, labels), value in sorted(series.items()):
                    if name not in seen:
                        lines.append(f"# TYPE {name} {kind}")
                        seen.add(name)
                    if labels:
                        lab = ",".join(f'{k}="{v}"' for k, v in labels)
                        lines.append(f"{name}{{{lab}}} {_fmt(value)}")
                    else:
                        lines.append(f"{name} {_fmt(value)}")
            return "\n".join(lines) + "\n"

    def health(self) -> Dict:
        """Liveness document.  ``stalled`` is the watchdog's verdict
        (False without a watchdog feed — no heartbeat means no hang
        CLAIM, not a hang); a stalled process answers
        ``status: "stalled"`` and the exporter serves it as 503, so a
        k8s liveness probe restarts a hung pod the same way
        ``train_with_recovery`` restarts a hung run."""
        stalled = False
        beat_age = None
        fn = self._watchdog_fn
        if fn is not None:
            try:
                rep = fn() or {}
                stalled = bool(rep.get("stalled"))
                beat_age = rep.get("last_beat_age_s")
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the data-plane block: from the live feed when one is
        # registered, else the registry's own (pre-created) counters —
        # the block is ALWAYS present, so probes can key on it
        data = None
        dfn = self._data_fn
        if dfn is not None:
            try:
                rep = dfn() or {}
                data = {"retries_total": int(rep.get("retries_total", 0)),
                        "quarantined_total": int(
                            rep.get("quarantined_total", 0)),
                        "last_error_age_s": rep.get("last_error_age_s"),
                        "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the elastic-mesh block: from the live feed when registered,
        # else the registry's own (pre-created) series — ALWAYS
        # present, like the data block, so probes can key on it.
        # ok:false only while mesh formation is quorum-blocked.
        mesh = None
        mfn = self._mesh_fn
        if mfn is not None:
            try:
                rep = mfn() or {}
                mesh = {"devices": int(rep.get("devices", 0)),
                        "reshard_total": int(rep.get("reshard_total", 0)),
                        "forming": bool(rep.get("forming", False)),
                        "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the fleet block: live feed when a fleet is running, else the
        # pre-created gauges — ALWAYS present, like data/mesh above
        fleet = None
        ffn = self._fleet_fn
        if ffn is not None:
            try:
                rep = ffn() or {}
                fleet = {"tenants": int(rep.get("tenants", 0)),
                         "steps_per_sec": float(
                             rep.get("steps_per_sec", 0.0)),
                         "dispatch_ms": float(rep.get("dispatch_ms", 0.0)),
                         "ok": bool(rep.get("ok", True))}
                det = rep.get("tenants_detail")
                if isinstance(det, dict):
                    # the tenant-lifecycle surface: quarantined tenants
                    # NAMED, onboard/offboard counts, cohort layout
                    fleet["tenants_detail"] = {
                        "active": int(det.get("active", 0)),
                        "cohorts": int(det.get("cohorts", 0)),
                        "quarantined": [int(t) for t in
                                        det.get("quarantined") or []],
                        "quarantine_reasons": {
                            str(k): str(v) for k, v in
                            (det.get("quarantine_reasons")
                             or {}).items()},
                        "onboarded_total": int(
                            det.get("onboarded_total", 0)),
                        "offboarded_total": int(
                            det.get("offboarded_total", 0)),
                        "throttled_total": int(
                            det.get("throttled_total", 0)),
                        "onboard_latency_ms": float(
                            det.get("onboard_latency_ms", 0.0)),
                    }
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the serving block: live feed when an engine is running, else
        # the pre-created series — ALWAYS present, like the rest.
        # ok:false when the dispatch loop is stalled past its watchdog
        # deadline (the serving-plane version of the 503 contract).
        serve = None
        sfn = self._serve_fn
        if sfn is not None:
            try:
                rep = sfn() or {}
                p99 = rep.get("p99_ms")
                serve = {"requests_total": int(
                             rep.get("requests_total", 0)),
                         "shed_total": int(rep.get("shed_total", 0)),
                         "queue_depth": int(rep.get("queue_depth", 0)),
                         "batch_fill": float(
                             rep.get("batch_fill", 0.0) or 0.0),
                         "p99_ms": (float(p99) if isinstance(
                             p99, (int, float)) else None),
                         "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the gateway block: live feed when a gateway is running, else
        # the pre-created series — ALWAYS present, like the rest.
        # ok:false when the router has zero healthy replicas.
        gateway = None
        gfn = self._gateway_fn
        if gfn is not None:
            try:
                rep = gfn() or {}
                gateway = {"requests_total": int(
                               rep.get("requests_total", 0)),
                           "rejected_total": int(
                               rep.get("rejected_total", 0)),
                           "active_connections": int(
                               rep.get("active_connections", 0)),
                           "replicas_healthy": int(
                               rep.get("replicas_healthy", 0)),
                           "replicas": int(rep.get("replicas", 0)),
                           "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the serving-mesh block (replica PROCESSES — the "mesh" block
        # above is the elastic-training device mesh): live feed when a
        # mesh is running, else the pre-created series — ALWAYS
        # present, like the rest.  ok:false with zero healthy replicas.
        serving_mesh = None
        smfn = self._serving_mesh_fn
        if smfn is not None:
            try:
                rep = smfn() or {}
                serving_mesh = {
                    "replicas": int(rep.get("replicas", 0)),
                    "replicas_healthy": int(
                        rep.get("replicas_healthy", 0)),
                    "ejected_total": int(rep.get("ejected_total", 0)),
                    "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the control-plane block: live feed when a controller is
        # running, else the pre-created series — ALWAYS present.
        # ok:false once a deployment has gone fatal (budget exhausted).
        controlplane = None
        cpfn = self._controlplane_fn
        if cpfn is not None:
            try:
                rep = cpfn() or {}
                controlplane = {
                    "replicas": int(rep.get("replicas", 0)),
                    "scale_up_total": int(rep.get("scale_up_total", 0)),
                    "scale_down_total": int(
                        rep.get("scale_down_total", 0)),
                    "replaced_total": int(rep.get("replaced_total", 0)),
                    "rollbacks_total": int(
                        rep.get("rollbacks_total", 0)),
                    "deploy_state": rep.get("deploy_state"),
                    "fatal": rep.get("fatal"),
                    "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the publication block: live feed when a CheckpointPublisher
        # is running, else the pre-created series — ALWAYS present.
        # stale:true means the serving plane is answering on old
        # weights (trainer down or checkpoints failing verification);
        # the top-level serving_stale flag mirrors it so probes need
        # not descend into the block.
        publication = None
        pubfn = self._publication_fn
        if pubfn is not None:
            try:
                rep = pubfn() or {}
                publication = {
                    "last_step": int(rep.get("last_step", 0)),
                    "age_seconds": round(
                        float(rep.get("age_seconds", 0.0)), 3),
                    "stale": bool(rep.get("stale", False)),
                    "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        # the resources block: live feed when a ResourceMonitor is
        # sampling, else the pre-created gauges — ALWAYS present.
        # Leak VERDICTS stay offline in the soak gate; the probe only
        # reports the latest sample.
        resources = None
        rfn = self._resources_fn
        if rfn is not None:
            try:
                rep = rfn() or {}
                resources = {
                    "rss_bytes": int(rep.get("rss_bytes", 0)),
                    "device_bytes": int(rep.get("device_bytes", 0)),
                    "open_fds": int(rep.get("open_fds", 0)),
                    "threads": int(rep.get("threads", 0)),
                    "ok": bool(rep.get("ok", True))}
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken feed must not take down the probe
                pass
        with self._lock:
            if data is None:
                data = {"retries_total": int(self._counters.get(
                            ("gan4j_data_retries_total", ()), 0.0)),
                        "quarantined_total": int(self._counters.get(
                            ("gan4j_data_quarantined_total", ()), 0.0)),
                        "last_error_age_s": None, "ok": True}
            if mesh is None:
                mesh = {"devices": int(self._gauges.get(
                            ("gan4j_mesh_devices", ()), 0.0)),
                        "reshard_total": int(self._counters.get(
                            ("gan4j_reshard_total", ()), 0.0)),
                        "forming": False, "ok": True}
            if fleet is None:
                fleet = {"tenants": int(self._gauges.get(
                             ("gan4j_fleet_tenants", ()), 0.0)),
                         "steps_per_sec": float(self._gauges.get(
                             ("gan4j_fleet_steps_per_sec", ()), 0.0)),
                         "dispatch_ms": float(self._gauges.get(
                             ("gan4j_fleet_dispatch_ms", ()), 0.0)),
                         "ok": True}
            if serve is None:
                serve = {"requests_total": int(self._counters.get(
                             ("gan4j_serve_requests_total", ()), 0.0)),
                         "shed_total": int(self._counters.get(
                             ("gan4j_serve_shed_total", ()), 0.0)),
                         "queue_depth": int(self._gauges.get(
                             ("gan4j_serve_queue_depth", ()), 0.0)),
                         "batch_fill": float(self._gauges.get(
                             ("gan4j_serve_batch_fill", ()), 0.0)),
                         "p99_ms": None, "ok": True}
            if gateway is None:
                gateway = {"requests_total": int(self._counters.get(
                               ("gan4j_gateway_requests_total", ()),
                               0.0)),
                           "rejected_total": int(self._counters.get(
                               ("gan4j_gateway_rejected_total", ()),
                               0.0)),
                           "active_connections": int(self._gauges.get(
                               ("gan4j_gateway_active_connections",
                                ()), 0.0)),
                           "replicas_healthy": int(self._gauges.get(
                               ("gan4j_gateway_replica_healthy", ()),
                               0.0)),
                           "replicas": 0, "ok": True}
            if serving_mesh is None:
                serving_mesh = {
                    "replicas": int(self._gauges.get(
                        ("gan4j_mesh_replicas", ()), 0.0)),
                    "replicas_healthy": int(self._gauges.get(
                        ("gan4j_mesh_replicas_healthy", ()), 0.0)),
                    "ejected_total": int(self._counters.get(
                        ("gan4j_mesh_ejected_total", ()), 0.0)),
                    "ok": True}
            if controlplane is None:
                controlplane = {
                    "replicas": int(self._gauges.get(
                        ("gan4j_controlplane_replicas", ()), 0.0)),
                    "scale_up_total": 0, "scale_down_total": 0,
                    "replaced_total": int(self._counters.get(
                        ("gan4j_controlplane_replaced_total", ()),
                        0.0)),
                    "rollbacks_total": int(self._counters.get(
                        ("gan4j_controlplane_rollbacks_total", ()),
                        0.0)),
                    "deploy_state": None, "fatal": None, "ok": True}
            if publication is None:
                publication = {
                    "last_step": int(self._gauges.get(
                        ("gan4j_publish_last_step", ()), 0.0)),
                    "age_seconds": round(self._gauges.get(
                        ("gan4j_publish_age_seconds", ()), 0.0), 3),
                    "stale": False, "ok": True}
            if resources is None:
                resources = {
                    "rss_bytes": int(self._gauges.get(
                        ("gan4j_resource_rss_bytes", ()), 0.0)),
                    "device_bytes": int(self._gauges.get(
                        ("gan4j_resource_device_bytes", ()), 0.0)),
                    "open_fds": int(self._gauges.get(
                        ("gan4j_resource_open_fds", ()), 0.0)),
                    "threads": int(self._gauges.get(
                        ("gan4j_resource_threads", ()), 0.0)),
                    "ok": True}
            age = (None if self._last_record_wall is None
                   else round(time.time() - self._last_record_wall, 3))
            doc = {"status": "stalled" if stalled else "ok",
                   "stalled": stalled, "run_id": self.run_id,
                   "last_record_age_s": age, "data": data,
                   "mesh": mesh, "fleet": fleet, "serve": serve,
                   "gateway": gateway,
                   "serving_mesh": serving_mesh,
                   "controlplane": controlplane,
                   "publication": publication,
                   "serving_stale": bool(publication.get("stale")),
                   "resources": resources}
            if beat_age is not None:
                doc["last_beat_age_s"] = round(float(beat_age), 3)
            return doc


def serve_exporter(registry: MetricsRegistry, port: int,
                   host: str = "127.0.0.1") -> Callable[[], None]:
    """Start the scrape endpoint (daemon thread); returns ``stop()``
    with the resolved port on ``stop.port`` (0 = ephemeral, same
    contract as utils/live_ui.serve_metrics)."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path.startswith("/metrics"):
                body = registry.render().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = 200
            elif self.path.startswith("/healthz"):
                doc = registry.health()
                body = json.dumps(doc).encode()
                ctype = "application/json"
                # the stalled contract (docs/OBSERVABILITY.md): a hung
                # run answers 503 so liveness probes restart the pod —
                # the process being alive enough to serve HTTP is
                # exactly what makes a hang invisible otherwise
                status = 503 if doc.get("stalled") else 200
            else:
                body = b'{"error": "not found"}'
                ctype = "application/json"
                status = 404
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet: no stderr per scrape
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="gan4j-metrics-exporter")
    thread.start()

    def stop() -> None:
        server.shutdown()
        server.server_close()

    stop.port = server.server_address[1]
    return stop
