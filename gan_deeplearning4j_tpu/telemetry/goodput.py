"""Goodput accounting — where every wall-clock second of a run went.

The r3 finding that "bookkeeping halves e2e throughput" was folklore
reconstructed from before/after benchmarks; this module makes it a
number that every run emits.  A ``GoodputTimer`` attributes the
training thread's wall time to named phases:

  data_wait   — blocking on the data pipeline (prefetch/chunk queues,
                CSV generation, iterator construction)
  dispatch    — dispatching device programs (includes the XLA compile,
                which happens inside the first dispatch)
  readback    — fencing on / reading back device results
  checkpoint  — checkpoint save/restore
  eval        — artifact dumps (latent grids, prediction CSVs)
  other       — everything unattributed (host bookkeeping, logging,
                the python loop itself)

``other`` is the complement of the attributed phases within total wall
time, so the breakdown always sums to the measured wall exactly; the
interesting signal is how small ``dispatch``'s share is (on a tunneled
PJRT link the device finishes long before the host returns from
dispatch, so host-side attribution is a LOWER bound on device idleness).

Alongside the per-phase SECONDS, ``report()`` carries ``phase_n`` — the
per-phase ENTRY COUNTS (how many times each phase was entered).  Totals
divided by counts turn the breakdown into per-event numbers:
``checkpoint / phase_n["checkpoint"]`` is the blocking seconds per save
(the async-checkpointing before/after metric), ``data_wait /
phase_n["data_wait"]`` the wait per chunk.  Phases never entered are
omitted from the map.

The companion ``write_run_manifest`` emits ``run_manifest.json`` — run
id, config, jax/libtpu versions, mesh/device topology — so metrics
JSONLs and bench JSONs can reference the exact software+topology a
number was measured under.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from contextlib import contextmanager
from typing import Dict, Optional

# the phase vocabulary; every phase also gets an entry COUNT in the
# report's ``phase_n`` map (entered-at-least-once phases only)
PHASES = ("data_wait", "dispatch", "readback", "checkpoint", "eval")


class GoodputTimer:
    """Accumulating phase timer for one run (one thread — the training
    thread; the async workers' time is by design NOT goodput-relevant,
    that is the point of moving work onto them).

    Pure host arithmetic: ``phase()`` costs two perf_counter reads, no
    device contact ever.  Phases may nest (e.g. a checkpoint that
    flushes artifacts inside an ``eval`` block): inner phases claim
    their own time and the outer phase gets the remainder, so no second
    is double-counted.  Every ``phase()`` entry also bumps that phase's
    ``phase_n`` count (reported alongside the totals), so seconds/count
    gives the per-event cost."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._acc: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._n: Dict[str, int] = {p: 0 for p in PHASES}
        self._stack = []  # (phase_name, start, inner_time) frames

    @contextmanager
    def phase(self, name: str):
        if name not in self._acc:
            raise ValueError(f"unknown goodput phase {name!r}; "
                             f"one of {PHASES}")
        start = time.perf_counter()
        self._stack.append([name, start, 0.0])
        try:
            yield
        finally:
            _, _, inner = self._stack.pop()
            elapsed = time.perf_counter() - start
            self._acc[name] += elapsed - inner
            self._n[name] += 1
            if self._stack:  # credit the whole span to the outer frame's
                self._stack[-1][2] += elapsed  # inner-time ledger

    def report(self) -> Dict[str, float]:
        """Breakdown so far: per-phase seconds, ``other`` (unattributed),
        ``wall_s`` (their exact sum), and ``compute_fraction`` —
        dispatch share of wall, the headline goodput number.  The nested
        ``phase_n`` entry-count map turns phase totals into per-event
        numbers — ``checkpoint / phase_n["checkpoint"]`` is the blocking
        seconds PER SAVE, the async-checkpointing before/after metric."""
        wall = time.perf_counter() - self._t0
        phases = {p: round(t, 6) for p, t in self._acc.items()}
        attributed = sum(phases.values())
        phases["other"] = round(max(0.0, wall - attributed), 6)
        return {
            **phases,
            "wall_s": round(wall, 6),
            "compute_fraction": round(
                phases["dispatch"] / wall if wall > 0 else 0.0, 4),
            "phase_n": {p: n for p, n in self._n.items() if n},
        }


def versions() -> Dict[str, str]:
    """jax / jaxlib / libtpu versions actually loaded (libtpu absent on
    CPU hosts; lookup failures degrade to "unknown", never raise)."""
    out = {}
    try:
        import jax

        out["jax"] = jax.__version__
    except Exception:
        out["jax"] = "unknown"
    try:
        import jaxlib

        out["jaxlib"] = getattr(jaxlib, "__version__", "unknown")
    except Exception:
        out["jaxlib"] = "unknown"
    try:
        from importlib import metadata

        for dist in ("libtpu", "libtpu-nightly"):
            try:
                out["libtpu"] = metadata.version(dist)
                break
            except metadata.PackageNotFoundError:  # gan4j-lint: disable=swallowed-exception — probing which libtpu dist is installed; absence is an answer
                continue
    except Exception:  # gan4j-lint: disable=swallowed-exception — version stamping is best-effort; the manifest is useful without it
        pass
    return out


def write_run_manifest(res_path: str, config=None, mesh=None,
                       extra: Optional[Dict] = None) -> Dict:
    """Write ``res_path/run_manifest.json`` and return its payload
    (callers key their metrics/bench records on ``run_id``).

    ``config``: a dataclass (asdict-ed) or plain dict; ``mesh``: a
    jax.sharding.Mesh or None.  Device topology is read from an ALREADY
    initialized jax backend only — this must never be the call that
    first touches a possibly-wedged device link."""
    manifest: Dict = {
        "run_id": uuid.uuid4().hex[:12],
        "unix_time": int(time.time()),
        "versions": versions(),
    }
    if config is not None:
        import dataclasses

        cfg = (dataclasses.asdict(config)
               if dataclasses.is_dataclass(config) else dict(config))
        manifest["config"] = {
            k: v for k, v in cfg.items()
            if isinstance(v, (int, float, str, bool, type(None)))}
    if mesh is not None:
        manifest["mesh"] = {str(k): int(v)
                            for k, v in dict(mesh.shape).items()}
    try:
        import jax

        manifest["process_index"] = jax.process_index()
        manifest["process_count"] = jax.process_count()
        dev = jax.devices()[0]
        manifest["devices"] = {
            "count": len(jax.devices()),
            "platform": dev.platform,
            "kind": getattr(dev, "device_kind", "unknown"),
        }
    except Exception:  # gan4j-lint: disable=swallowed-exception — manifest stays useful without topology (no devices in a unit test)
        pass
    if extra:
        manifest.update(extra)
    path = os.path.join(res_path, "run_manifest.json")
    try:
        os.makedirs(res_path, exist_ok=True)
        with open(path, "w") as f:
            json.dump(manifest, f, indent=1)
        manifest["path"] = path
    except OSError:  # gan4j-lint: disable=swallowed-exception — read-only res dir: the in-memory payload still flows
        pass
    return manifest
