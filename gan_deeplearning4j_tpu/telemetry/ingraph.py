"""In-graph numerics telemetry — the signals that explain a diverging GAN
run BEFORE the FID collapses.

Everything here is traced into the training program itself: global
gradient norm, parameter norm and update ratio per trained graph, plus
NaN/Inf counters over gradients and losses.  The step returns them as a
small fixed-shape block of device scalars alongside the losses — the
SAME dispatch, no host round trip; under ``lax.scan`` they stack to
(K,) arrays exactly like the chunked losses, and the async
MetricsLogger worker materializes them off the training thread.

Host side, ``NanAlarm`` watches the materialized records and trips on
the first non-finite step; the trainer decides what a trip means
(warn / snapshot / abort — train/gan_trainer.py).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

# update_ratio's divide-by-zero guard; in f32 a param norm at this scale
# is indistinguishable from an all-zero network anyway
_EPS = 1e-12


def tree_norm(tree) -> jax.Array:
    """Global L2 norm over every array leaf of ``tree`` (f32 scalar).

    Accumulates per-leaf sums of squares in f32 regardless of leaf dtype
    so a bf16 mixed-precision run reports the same norm (to rounding) as
    the f32 run."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def count_nonfinite(tree) -> jax.Array:
    """Total count of non-finite (NaN or +/-Inf) elements over every
    array leaf of ``tree`` (int32 scalar)."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")]
    if not leaves:
        return jnp.zeros((), jnp.int32)
    return sum(jnp.sum(~jnp.isfinite(l.astype(jnp.float32)))
               for l in leaves).astype(jnp.int32)


def graph_telemetry(params, new_params, grads, loss) -> Dict[str, jax.Array]:
    """One trained graph's numerics block, computed from values the step
    already holds (no extra forward/backward work):

    * ``grad_norm``    — global L2 of the (cross-replica reduced) grads
    * ``param_norm``   — global L2 of the UPDATED parameters
    * ``update_ratio`` — ||new - old|| / ||old||, the per-step relative
      weight movement (the classic LR-sanity signal: healthy training
      sits around 1e-3, ~1 means the optimizer is overwriting the net)
    * ``nonfinite``    — NaN/Inf count over grads and the loss
    """
    param_norm = tree_norm(new_params)
    old_norm = tree_norm(params)
    update = jax.tree_util.tree_map(
        lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
        new_params, params)
    return {
        "grad_norm": tree_norm(grads),
        "param_norm": param_norm,
        "update_ratio": tree_norm(update) / (old_norm + _EPS),
        "nonfinite": count_nonfinite(grads) + count_nonfinite(loss),
    }


class NanAlarmError(RuntimeError):
    """Raised by the trainer when a ``NanAlarm`` with action="abort"
    trips (first training step with a non-finite loss/grad)."""


class NanAlarm:
    """First-bad-step detector over materialized metrics records.

    Registered as the MetricsLogger's ``on_record`` hook, so it observes
    every record on the async worker thread — detection costs the
    training thread nothing.  A record is "bad" when its ``nonfinite``
    counter is positive or any telemetry/loss value is itself
    non-finite.  The first bad record arms ``tripped``/``step``/
    ``record`` (thread-safely, latched — later records don't overwrite
    the first occurrence) and fires the optional ``on_trip`` callback
    once.  The training loop polls ``tripped`` at its bookkeeping
    points and applies the configured action (warn/snapshot/abort)."""

    # keys whose own non-finiteness (not just nonfinite>0) means trouble
    _WATCH_SUFFIXES = ("_loss", "_norm", "_ratio")

    def __init__(self, on_trip: Optional[Callable[[Dict], None]] = None):
        self._lock = threading.Lock()
        self._on_trip = on_trip
        self.tripped = False
        self.step: Optional[int] = None
        self.record: Optional[Dict] = None

    @staticmethod
    def _is_bad(rec: Dict) -> bool:
        import math

        if rec.get("nonfinite", 0):
            return True
        for k, v in rec.items():
            if isinstance(v, float) and not math.isfinite(v) and (
                    k.endswith(NanAlarm._WATCH_SUFFIXES)):
                return True
        return False

    def observe(self, rec: Dict) -> None:
        """MetricsLogger ``on_record`` hook (worker thread)."""
        if self.tripped or not self._is_bad(rec):
            return
        with self._lock:
            if self.tripped:  # lost the race to an earlier bad record
                return
            self.step = rec.get("step")
            self.record = rec
            self.tripped = True
        if self._on_trip is not None:
            self._on_trip(rec)
