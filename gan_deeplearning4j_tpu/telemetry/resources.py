"""Continuous resource telemetry + the soak-mode leak gate.

A 30-second bench burst proves latency; it says nothing about
whether the plane survives HOURS.  The failure mode that kills
long-running serving processes is monotone resource growth — host
RSS from a hoarded reference, device bytes from a leaked buffer,
file descriptors from an unclosed socket, threads from an unjoined
worker.  This module supplies both halves of the answer:

* **ResourceMonitor** — a named daemon sampler thread
  (``gan4j-resource-sampler``) reading host RSS (``/proc``), device
  bytes (jax ``memory_stats``, only if jax is already imported),
  open fds, and thread count on a fixed interval into a bounded
  ring.  ``report()`` is a scrape feed for
  ``MetricsRegistry.observe_resources`` (the ``gan4j_resource_*``
  gauges); ``samples()`` is the raw ring for the gate.
* **leak_verdict** — a robust linear-trend test over the ring.  The
  slope estimator is Theil–Sen (median of pairwise slopes), which a
  single GC spike or allocator step cannot drag the way least
  squares can; a resource is declared leaking only when BOTH the
  slope and the absolute growth (median of the last samples minus
  median of the first, post-warmup) clear their thresholds, so a
  one-time arena expansion does not fail the gate.  The verdict is
  TYPED: a dict with per-resource slope/growth/threshold blocks and
  the list of leaking resources — ``bench --soak`` prints it in its
  JSON line and ``bench_gate.check_soak`` gates on it.

Thresholds are deliberately loose (a real leak under load clears
them within seconds; CPython noise does not): RSS must grow faster
than 512 KiB/s AND by more than 32 MiB over the window.
"""

from __future__ import annotations

import os
import statistics
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

# -- gate thresholds (module constants so tests/docs can cite them) -----------

MIN_SAMPLES = 8          # below this, no trend claim is honest
WARMUP_FRAC = 0.25       # drop the head: imports/compiles/arena growth
RSS_SLOPE_BYTES_PER_S = 512 << 10
RSS_GROWTH_BYTES = 32 << 20
DEVICE_SLOPE_BYTES_PER_S = 1 << 20
DEVICE_GROWTH_BYTES = 64 << 20
FD_GROWTH = 64
THREAD_GROWTH = 16

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> int:
    """Current resident set from /proc/self/statm (field 1, pages).
    0 where /proc is absent — the gate treats a flat 0 as clean."""
    try:
        with open("/proc/self/statm", "r") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):  # gan4j-lint: disable=swallowed-exception — non-Linux hosts have no /proc; sampling must degrade to 0, not crash the sampler thread
        return 0


def _open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # gan4j-lint: disable=swallowed-exception — same /proc degradation as _rss_bytes
        return 0


def _device_bytes() -> int:
    """Sum of ``bytes_in_use`` across jax devices.  Never IMPORTS
    jax — a sampler thread must not trigger backend initialization;
    it only reads stats when the process already uses jax.  CPU
    devices expose no memory_stats and count 0."""
    if "jax" not in sys.modules:
        return 0
    try:
        jax = sys.modules["jax"]
        total = 0
        for d in jax.devices():
            stats_fn = getattr(d, "memory_stats", None)
            if stats_fn is None:
                continue
            stats = stats_fn() or {}
            total += int(stats.get("bytes_in_use") or 0)
        return total
    except Exception:  # gan4j-lint: disable=swallowed-exception — device stats are best-effort telemetry; a backend mid-teardown must not kill the sampler
        return 0


def sample_resources(t: float = 0.0,
                     device_fn: Optional[Callable[[], int]] = None) -> Dict:
    """One sample of all four tracked resources."""
    return {"t": float(t),
            "rss_bytes": _rss_bytes(),
            "device_bytes": (device_fn or _device_bytes)(),
            "open_fds": _open_fds(),
            "threads": threading.active_count()}


class ResourceMonitor:
    """Named daemon sampler thread feeding a bounded in-memory ring.

    ``interval_s`` trades resolution for overhead (each sample is a
    couple of /proc reads — microseconds); ``ring_size`` bounds
    memory so a days-long soak cannot itself become the leak."""

    def __init__(self, interval_s: float = 0.5, *,
                 ring_size: int = 4096,
                 device_fn: Optional[Callable[[], int]] = None):
        self.interval_s = float(interval_s)
        self._device_fn = device_fn
        self._ring: deque = deque(maxlen=int(ring_size))
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()
        self._samples_total = 0

    def sample_once(self) -> Dict:
        s = sample_resources(time.monotonic() - self._t0,
                             device_fn=self._device_fn)
        with self._lock:
            self._ring.append(s)
            self._samples_total += 1
        return s

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            self.sample_once()

    def start(self) -> "ResourceMonitor":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_evt.clear()
            thread = threading.Thread(target=self._run, daemon=True,
                                      name="gan4j-resource-sampler")
            self._thread = thread
        self.sample_once()  # a sample exists the moment start returns
        thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)  # join OUTSIDE the lock

    def __enter__(self) -> "ResourceMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def samples(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_resources``:
        the LATEST sample plus ring bookkeeping."""
        with self._lock:
            latest = self._ring[-1] if self._ring else None
            total = self._samples_total
        if latest is None:
            latest = {"t": 0.0, "rss_bytes": 0, "device_bytes": 0,
                      "open_fds": 0, "threads": 0}
        return {"rss_bytes": latest["rss_bytes"],
                "device_bytes": latest["device_bytes"],
                "open_fds": latest["open_fds"],
                "threads": latest["threads"],
                "samples_total": total,
                "window_s": latest["t"],
                "ok": True}


# -- the leak gate -------------------------------------------------------------

def theil_sen_slope(ts: Sequence[float], vs: Sequence[float],
                    max_points: int = 200) -> float:
    """Median of pairwise slopes — robust to outlier spikes that
    would drag a least-squares fit.  Decimates evenly to
    ``max_points`` so a 4096-sample ring costs ~20k pairs, not 8M."""
    n = len(ts)
    if n < 2:
        return 0.0
    if n > max_points:
        step = n / max_points
        idx = [int(i * step) for i in range(max_points)]
        ts = [ts[i] for i in idx]
        vs = [vs[i] for i in idx]
        n = len(ts)
    slopes = []
    for i in range(n):
        for j in range(i + 1, n):
            dt = ts[j] - ts[i]
            if dt > 0:
                slopes.append((vs[j] - vs[i]) / dt)
    return statistics.median(slopes) if slopes else 0.0


def _growth(vs: Sequence[float]) -> float:
    """Median of the last k samples minus median of the first k —
    endpoint medians, so a single spike at either edge cannot fake
    (or hide) growth."""
    k = max(1, min(5, len(vs) // 4))
    return statistics.median(vs[-k:]) - statistics.median(vs[:k])


def leak_verdict(samples: Sequence[Dict], *,
                 warmup_frac: float = WARMUP_FRAC,
                 min_samples: int = MIN_SAMPLES,
                 rss_slope_bytes_per_s: float = RSS_SLOPE_BYTES_PER_S,
                 rss_growth_bytes: float = RSS_GROWTH_BYTES,
                 device_slope_bytes_per_s: float = DEVICE_SLOPE_BYTES_PER_S,
                 device_growth_bytes: float = DEVICE_GROWTH_BYTES,
                 fd_growth: int = FD_GROWTH,
                 thread_growth: int = THREAD_GROWTH) -> Dict:
    """Typed verdict over a sample ring (docstring at module top:
    Theil–Sen slope AND endpoint growth must both clear thresholds).

    fds and threads are integer-valued and step-shaped, so they gate
    on growth alone — a slope over a staircase means little."""
    n = len(samples)
    if n < min_samples:
        return {"ok": True, "type": "resource_leak",
                "reason": f"{n} samples < {min_samples}: "
                          "no trend claim", "samples": n,
                "window_s": 0.0, "leaking": [], "resources": {}}
    body = list(samples[int(n * warmup_frac):])
    ts = [float(s["t"]) for s in body]
    window_s = (ts[-1] - ts[0]) if len(ts) >= 2 else 0.0
    resources: Dict[str, Dict] = {}
    leaking: List[str] = []

    for key, slope_th, growth_th in (
            ("rss_bytes", rss_slope_bytes_per_s, rss_growth_bytes),
            ("device_bytes", device_slope_bytes_per_s,
             device_growth_bytes)):
        vs = [float(s.get(key) or 0) for s in body]
        slope = theil_sen_slope(ts, vs)
        growth = _growth(vs)
        leak = slope > slope_th and growth > growth_th
        resources[key] = {"slope_per_s": round(slope, 1),
                          "growth": round(growth, 1),
                          "slope_threshold": slope_th,
                          "growth_threshold": growth_th,
                          "leak": leak}
        if leak:
            leaking.append(key)

    for key, growth_th in (("open_fds", fd_growth),
                           ("threads", thread_growth)):
        vs = [float(s.get(key) or 0) for s in body]
        growth = _growth(vs)
        leak = growth > growth_th
        resources[key] = {"slope_per_s": round(theil_sen_slope(ts, vs), 3),
                          "growth": round(growth, 1),
                          "growth_threshold": growth_th,
                          "leak": leak}
        if leak:
            leaking.append(key)

    return {"ok": not leaking, "type": "resource_leak",
            "samples": n, "window_s": round(window_s, 3),
            "warmup_dropped": n - len(body),
            "leaking": leaking, "resources": resources}
