"""Request-scoped distributed tracing for the serving plane.

The serving mesh is multi-PROCESS (client → gateway → mesh hops →
replica → engine) but the flight recorder (telemetry/events.py) is
per-process: each replica writes its own ``events.jsonl`` and no
signal crosses the wire.  This module is the Dapper-style answer:

* **Trace context** — a ``TraceContext(trace, span)`` pair.  The
  FIRST hop mints it (``GatewayClient`` for programmatic callers,
  ``Gateway`` for untraced ones, ``MeshRouter`` for direct mesh
  callers) and every later hop derives a child.  Span ids are
  pid-prefixed so two processes can never collide.
* **Wire format** — one header, ``X-Gan4j-Trace``, carrying
  ``trace=<id>;parent=<span>``.  The receiver parses it with
  ``from_header`` and children itself under the sender's span.
  Responses echo the header back (including typed error responses —
  shed/timeout requests must not vanish from merged timelines).
* **Spans on the one substrate** — stages are recorded as ordinary
  ``trace.*`` events on the installed ``EventRecorder`` carrying
  ``trace``/``span``/``parent`` attributes; no second sink, no new
  file format.  The vocabulary (client-side ``trace.client``/
  ``trace.wire_send``/``trace.wire_recv``, gateway-side
  ``trace.request``/``trace.rate_limit``/``trace.decode``/
  ``trace.dispatch_wait``/``trace.response_encode``/``trace.reject``,
  mesh-side ``trace.route``/``trace.hop``, engine-side
  ``trace.queue_wait``/``trace.coalesce``/``trace.bucket_pad``/
  ``trace.dispatch``/``trace.readback``) is documented in
  docs/OBSERVABILITY.md.
* **trace_merge** — ``merge_trace_files`` joins per-process
  ``events.jsonl`` files into ONE timeline keyed by trace id.  A
  ``recorder.start`` header anchors each process-local monotonic
  clock (``t``) to wall time (``wall``); the merge normalizes every
  span to ``wall0 + t`` so spans from different hosts order correctly
  without assuming a shared monotonic epoch.  Files are SEGMENTED at
  every header: an appended multi-incarnation trainer file (resume
  after preemption) gets one anchor per incarnation, not one per
  file.  ``include_events=`` prefixes additionally ingest non-trace
  events (trainer lifecycle, publication decisions, chaos firings)
  into a flat wall-ordered ``timeline`` — the combined-chaos
  scenario's one contiguous cross-process story.  ``python -m
  gan_deeplearning4j_tpu.telemetry.tracing FILE... [--events
  PREFIX]`` is the CLI.

A trace tree is COMPLETE when it has exactly one root (a span with
no parent) and every other span's parent id resolves to a span in
the same trace — the property ``bench --dryrun`` gates at ≥95%.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import statistics
import sys
from contextlib import contextmanager
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence

from gan_deeplearning4j_tpu.telemetry import events

# the one wire header (documented in docs/SERVING.md)
TRACE_HEADER = "X-Gan4j-Trace"

# response breakdown header (Server-Timing, RFC 8941 shaped)
TIMING_HEADER = "Server-Timing"

_SEQ = itertools.count(1)

_MAX_ID_LEN = 64  # reject absurd header payloads, not just garbage


class TraceContext(NamedTuple):
    """An immutable (trace id, current span id) pair.  Passing one
    across a hop means "parent yourself under my span"."""

    trace: str
    span: str


def new_trace_id() -> str:
    """128 bits would be Dapper-faithful; 64 is plenty for one mesh."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """Pid-prefixed counter: unique within a process by the counter,
    across processes by the pid."""
    return f"{os.getpid():x}-{next(_SEQ):x}"


def mint() -> TraceContext:
    """Start a new trace at this hop (this span is the root)."""
    return TraceContext(new_trace_id(), new_span_id())


def child(ctx: TraceContext) -> TraceContext:
    """Same trace, fresh span id — the receiver side of a hop."""
    return TraceContext(ctx.trace, new_span_id())


def to_header(ctx: TraceContext) -> str:
    return f"trace={ctx.trace};parent={ctx.span}"


def from_header(value: Optional[str]) -> Optional[TraceContext]:
    """Tolerant parse of ``trace=<id>;parent=<span>``.  Anything
    malformed returns None — an untraceable request is served, not
    rejected, and the gateway mints a fresh root for it."""
    if not value:
        return None
    fields = {}
    for part in value.split(";"):
        key, _, val = part.strip().partition("=")
        fields[key.strip()] = val.strip()
    trace, parent = fields.get("trace"), fields.get("parent")
    if not trace or not parent:
        return None
    if len(trace) > _MAX_ID_LEN or len(parent) > _MAX_ID_LEN:
        return None
    return TraceContext(trace, parent)


@contextmanager
def stage(ctx: TraceContext, name: str, **attrs) -> Iterator[TraceContext]:
    """Record ``name`` as a child span of ``ctx`` around the body;
    yields the child context for deeper nesting.  Thin sugar over
    ``events.span`` for call sites that do not need manual timing."""
    sub = child(ctx)
    with events.span(name, trace=sub.trace, span=sub.span,
                     parent=ctx.span, **attrs):
        yield sub


# -- trace_merge: the cross-process join ---------------------------------------

# event keys that are structure, not user attributes
_STRUCTURAL = ("name", "ph", "t", "wall", "thread", "dur",
               "trace", "span", "parent", "error", "status")


def _file_anchor(evs: List[Dict]) -> tuple:
    """(wall0, host) from the segment's ``recorder.start`` header line
    — the anchor that turns process-local monotonic ``t`` into a
    cross-process wall timestamp."""
    for ev in evs:
        if ev.get("name") == "recorder.start":
            return ev.get("wall"), ev.get("host")
    return None, None


def _segments(evs: List[Dict]) -> List[List[Dict]]:
    """Split one events file at EVERY ``recorder.start`` header.

    A trainer that resumes after preemption/crash APPENDS to its own
    ``events.jsonl`` (train/shell.py): one file then holds several
    incarnations, each a distinct process (the header's host is
    ``node:pid``) with its OWN monotonic epoch.  Anchoring the whole
    file on the first header would misplace every later incarnation's
    spans by the restart gap; per-segment anchors keep each
    incarnation wall-correct, so the merged timeline genuinely spans
    trainer incarnations and replica processes alike."""
    segs: List[List[Dict]] = []
    cur: List[Dict] = []
    for ev in evs:
        if ev.get("name") == "recorder.start" and cur:
            segs.append(cur)
            cur = []
        cur.append(ev)
    if cur:
        segs.append(cur)
    return segs


def merge_trace_files(paths: Sequence[str],
                      include_events: Sequence[str] = ()) -> Dict:
    """Join per-process events files into one timeline keyed by trace
    id.  Returns ``{"traces": {tid: {...}}, "timeline": [...],
    "stats": {...}}`` where each trace carries its wall-ordered spans,
    the process set it touched, and a completeness verdict (exactly
    one root + every parent resolves).

    ``include_events``: name PREFIXES (e.g. ``("fleet.", "publish.",
    "chaos.")``) of non-trace events to ingest into the flat
    wall-ordered ``"timeline"`` list — how the combined-chaos
    scenario joins trainer-side lifecycle events (``fleet.start``,
    ``preempt.exit``), publication decisions and chaos firings with
    the serving spans into ONE contiguous cross-process story.  Files
    are segmented at every ``recorder.start`` header so appended
    multi-incarnation trainer files normalize correctly (see
    :func:`_segments`)."""
    prefixes = tuple(str(p) for p in include_events)
    spans: List[Dict] = []
    timeline: List[Dict] = []
    files_read = 0
    n_segments = 0
    for path in paths:
        try:
            evs = events.read_events(path)
        except OSError:  # gan4j-lint: disable=swallowed-exception — a replica that died pre-flush (SIGKILL chaos) has no file; the merge must still join the survivors
            continue
        files_read += 1
        for seg in _segments(evs):
            n_segments += 1
            wall0, host = _file_anchor(seg)
            for ev in seg:
                name = ev.get("name", "")
                t = ev.get("t")
                if wall0 is not None and isinstance(t, (int, float)):
                    wall = wall0 + t
                else:
                    wall = ev.get("wall")  # torn header: per-event clock
                if not name.startswith("trace."):
                    if prefixes and name.startswith(prefixes):
                        item = {"name": name,
                                "host": host or ev.get("host") or path,
                                "wall": wall}
                        if ev.get("error") is not None:
                            item["error"] = ev["error"]
                        extra = {k: v for k, v in ev.items()
                                 if k not in _STRUCTURAL}
                        if extra:
                            item["attrs"] = extra
                        timeline.append(item)
                    continue
                if "trace" not in ev or "span" not in ev:
                    continue
                span = {"name": name,
                        "trace": ev["trace"],
                        "span": ev["span"],
                        "parent": ev.get("parent"),
                        "host": host or ev.get("host") or path,
                        "wall": wall,
                        "dur": float(ev.get("dur") or 0.0)}
                if ev.get("error") is not None:
                    span["error"] = ev["error"]
                if ev.get("status") is not None:
                    span["status"] = ev["status"]
                extra = {k: v for k, v in ev.items()
                         if k not in _STRUCTURAL}
                if extra:
                    span["attrs"] = extra
                spans.append(span)
    timeline.sort(key=lambda e: (e["wall"] is None, e["wall"]))

    by_trace: Dict[str, List[Dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)

    traces: Dict[str, Dict] = {}
    stage_ms: Dict[str, List[float]] = {}
    n_complete = 0
    for tid, ss in by_trace.items():
        ss.sort(key=lambda s: (s["wall"] is None, s["wall"]))
        ids = {s["span"] for s in ss}
        roots = [s for s in ss if not s.get("parent")]
        resolved = all(
            (not s.get("parent")) or s["parent"] in ids for s in ss)
        complete = len(roots) == 1 and resolved
        if complete:
            n_complete += 1
        traces[tid] = {
            "spans": ss,
            "complete": complete,
            "root": roots[0]["name"] if len(roots) == 1 else None,
            "processes": sorted({s["host"] for s in ss}),
            "errors": [s["name"] for s in ss if s.get("error")],
        }
        for s in ss:
            stage_ms.setdefault(s["name"], []).append(s["dur"] * 1e3)

    total = len(traces)
    stats = {
        "files": files_read,
        "segments": n_segments,
        "spans": len(spans),
        "timeline_events": len(timeline),
        "timeline_processes": sorted({e["host"] for e in timeline}),
        "traces": total,
        "complete": n_complete,
        "complete_frac": (n_complete / total) if total else 0.0,
        "cross_process": sum(1 for t in traces.values()
                             if len(t["processes"]) >= 2),
        "errors": sum(len(t["errors"]) for t in traces.values()),
        "stage_p50_ms": {k: round(statistics.median(v), 3)
                         for k, v in sorted(stage_ms.items())},
    }
    return {"traces": traces, "timeline": timeline, "stats": stats}


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="trace_merge",
        description="Join per-process events.jsonl files into one "
                    "cross-process timeline keyed by trace id.")
    p.add_argument("files", nargs="+",
                   help="events.jsonl files (one per process)")
    p.add_argument("--out", default=None,
                   help="write the full merged document (traces + "
                        "stats) as JSON to PATH")
    p.add_argument("--trace", default=None,
                   help="print one trace id's merged spans instead "
                        "of the stats line")
    p.add_argument("--events", action="append", default=[],
                   metavar="PREFIX",
                   help="also ingest non-trace events whose name "
                        "starts with PREFIX into the flat timeline "
                        "(repeatable; e.g. --events fleet. --events "
                        "chaos.)")
    args = p.parse_args(argv)
    merged = merge_trace_files(args.files,
                               include_events=tuple(args.events))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
    if args.trace is not None:
        doc = merged["traces"].get(args.trace)
        if doc is None:
            print(f"no such trace: {args.trace}", file=sys.stderr)
            return 2
        print(json.dumps(doc, indent=1))
        return 0
    print(json.dumps(merged["stats"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
