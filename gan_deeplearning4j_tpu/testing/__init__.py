"""Fault-injection / chaos tooling (tests and drills only — nothing in
the training path imports this package)."""

from gan_deeplearning4j_tpu.testing.chaos import (
    ChaosInjector,
    CorruptRecordSource,
    DeviceLostError,
    FlakyReader,
    FlakySource,
    HangingSource,
    InjectedCrash,
    NanSource,
    StallingSource,
)

__all__ = ["ChaosInjector", "CorruptRecordSource", "DeviceLostError",
           "FlakyReader", "FlakySource", "HangingSource",
           "InjectedCrash", "NanSource", "StallingSource"]
