"""Fault injection — prove the fault-tolerance contract, don't assert it.

The robustness claims of checkpoint/ and train/ (``restart equals never
failed``, ``no kill point leaves the directory unrestorable``) are only
claims until something actually kills the process mid-write, corrupts a
file, stalls the data source, or poisons the gradients.  This module is
the seeded, deterministic injector that does all four, driven by
``tests/test_chaos.py``:

* **kill-during-save** — ``ChaosInjector.kill_at_save_event`` hooks the
  checkpointer's enumerated write/rename points (``checkpointer.
  _chaos_hook``) and raises ``InjectedCrash`` at the chosen one; the
  exception carries ``simulates_kill = True`` so the checkpointer skips
  its graceful temp cleanup and the directory is left exactly as SIGKILL
  would leave it.  ``count_save_events`` enumerates the points so a test
  can walk every one.  The subprocess variant (actual ``SIGKILL`` at a
  seeded moment — no python frames unwound at all) lives in the test.
* **corrupt-one-file** — flip one seeded byte of one seeded file of a
  committed checkpoint (silent media corruption); **truncate-file** cuts
  a seeded tail off (a torn write that survived a crash).  Both must be
  caught by manifest verification, never loaded.
* **stall-the-data-source** — ``StallingSource`` wraps any DataSet
  iterator and blocks inside ``next()`` at a seeded call until released
  (a hung storage layer); pins that ``PrefetchIterator.close`` neither
  deadlocks nor loses worker errors.  ``HangingSource`` is the terminal
  variant: it NEVER releases (a dead storage layer) — the hang the
  watchdog (train/watchdog.py) converts into a retryable restart.
* **hang-the-readback** — ``ChaosInjector.hang_at_readback`` hooks
  ``utils/device.device_fence`` so a chosen fence call blocks
  indefinitely (a wedged device/tunnel), the OTHER silent hang class.
* **NaN-into-grads** — ``NanSource`` poisons the features of a seeded
  batch (the classic bad-record path to non-finite grads), driving the
  telemetry NaN alarm — and the rollback-with-perturbation heal path —
  end to end.

Everything is parameterized by an explicit seed: a chaos failure must
replay exactly.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

import numpy as np

from gan_deeplearning4j_tpu.checkpoint import checkpointer as _ckpt_mod


class InjectedCrash(RuntimeError):
    """A simulated hard kill.  ``simulates_kill`` tells the checkpointer
    to leave the directory un-cleaned (debris and all), exactly as a
    real SIGKILL would; the recovery wrapper still classifies it as a
    retryable failure (it is a RuntimeError, not a config error)."""

    simulates_kill = True


class ChaosInjector:
    """Seeded injector; one instance per test scenario."""

    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)

    # -- kill-during-save ------------------------------------------------------

    def count_save_events(self, save_fn) -> List[str]:
        """Run ``save_fn()`` with a recording hook; return the ordered
        list of chaos events it passed (the enumerable kill points)."""
        events: List[str] = []
        prev = _ckpt_mod._chaos_hook
        _ckpt_mod._chaos_hook = events.append
        try:
            save_fn()
        finally:
            _ckpt_mod._chaos_hook = prev
        return events

    def kill_at_save_event(self, index: int,
                           after_times: int = 0) -> "_KillPoint":
        """Context manager: the ``index``-th chaos event of the
        (``after_times``+1)-th save inside the block raises
        ``InjectedCrash``.  ``after_times`` lets a test crash the Nth
        save of a run while earlier ones succeed."""
        return _KillPoint(index, after_times)

    # -- corruption ------------------------------------------------------------

    def corrupt_one_file(self, ckpt_dir: str,
                         exclude_manifest: bool = False) -> tuple:
        """Flip one seeded byte of one seeded file under ``ckpt_dir``
        (committed checkpoint).  Returns (path, offset).  With
        ``exclude_manifest`` the manifest itself stays intact — the
        harder case: the corruption is only discoverable by hashing."""
        import os

        files = sorted(
            f for f in os.listdir(ckpt_dir)
            if os.path.isfile(os.path.join(ckpt_dir, f))
            and not (exclude_manifest and f == _ckpt_mod.MANIFEST_NAME))
        name = self.rng.choice(files)
        path = os.path.join(ckpt_dir, name)
        data = bytearray(open(path, "rb").read())
        off = self.rng.randrange(len(data))
        data[off] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(data))
        return path, off

    def truncate_file(self, ckpt_dir: str) -> tuple:
        """Cut a seeded non-empty tail off one seeded data file (torn
        write).  Returns (path, new_size)."""
        import os

        files = sorted(
            f for f in os.listdir(ckpt_dir)
            if os.path.isfile(os.path.join(ckpt_dir, f))
            and f != _ckpt_mod.MANIFEST_NAME)
        name = self.rng.choice(files)
        path = os.path.join(ckpt_dir, name)
        size = os.path.getsize(path)
        new_size = self.rng.randrange(max(1, size))  # strictly shorter
        with open(path, "rb+") as f:
            f.truncate(new_size)
        return path, new_size

    def delete_file(self, ckpt_dir: str, name: str) -> str:
        """Remove one named file of a committed checkpoint (e.g.
        ``state.npz`` lost to a filesystem fault)."""
        import os

        path = os.path.join(ckpt_dir, name)
        os.remove(path)
        return path

    # -- hangs -----------------------------------------------------------------

    def hang_at_readback(self, at: int = 0) -> "_ReadbackHang":
        """Context manager: the ``at``-th ``device_fence`` call inside
        the block hangs indefinitely (a wedged device readback /
        tunnel).  One-shot — a restarted run's fences proceed normally,
        so a watchdog-driven restart can finish.  The hang sleeps in
        small increments, which keeps the hung thread interruptible at
        bytecode boundaries — exactly the property a real C-level hang
        lacks until its call returns, and the reason the watchdog also
        dumps diagnostics and checkpoints from its OWN thread."""
        return _ReadbackHang(at)


class _ReadbackHang:
    def __init__(self, at: int):
        self.at = at
        self.calls = 0
        self.fired = False                  # one-shot, like _KillPoint
        self.hung = threading.Event()       # observable: fence is stuck
        self._release = threading.Event()   # set on __exit__ (cleanup)
        self._prev = None

    def _hook(self) -> None:
        if self.fired:
            return
        if self.calls == self.at:
            self.fired = True
            self.hung.set()
            while not self._release.is_set():
                time.sleep(0.05)
        self.calls += 1

    def __enter__(self) -> "_ReadbackHang":
        from gan_deeplearning4j_tpu.utils import device as _device_mod

        self._device_mod = _device_mod
        self._prev = _device_mod._chaos_readback_hook
        _device_mod._chaos_readback_hook = self._hook
        return self

    def __exit__(self, *exc) -> None:
        self._device_mod._chaos_readback_hook = self._prev
        self._release.set()  # free any thread still parked in the hook


class _KillPoint:
    def __init__(self, index: int, after_times: int):
        self.index = index
        self.after_times = after_times
        self.fired = False  # one-shot: a killed process stays dead once
        self._events = 0
        self._saves_seen = 0
        self._prev = None

    def _hook(self, event: str) -> None:
        if self.fired:
            return  # the "process" already died; later saves (the
            # restarted run's) proceed normally
        if self._saves_seen < self.after_times:
            if event == "post_swap":  # one per completed save
                self._saves_seen += 1
            return
        if self._events == self.index:
            self.fired = True
            raise InjectedCrash(
                f"injected kill at save event #{self.index} ({event!r})")
        self._events += 1

    def __enter__(self) -> "_KillPoint":
        self._prev = _ckpt_mod._chaos_hook
        _ckpt_mod._chaos_hook = self._hook
        return self

    def __exit__(self, *exc) -> None:
        _ckpt_mod._chaos_hook = self._prev


class StallingSource:
    """DataSet-iterator wrapper whose ``next()`` blocks at the
    ``stall_at``-th call until ``release()`` (or forever) — a wedged
    storage layer under the prefetch worker."""

    def __init__(self, source, stall_at: int):
        self.source = source
        self.stall_at = stall_at
        self.calls = 0
        self.stalled = threading.Event()   # observable: worker is stuck
        self._release = threading.Event()

    def release(self) -> None:
        self._release.set()

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        self.calls += 1
        if self.calls - 1 == self.stall_at:
            self.stalled.set()
            self._release.wait()  # block until the test releases us
        return self.source.next()

    def __getattr__(self, name):
        return getattr(self.source, name)


class HangingSource:
    """DataSet-iterator wrapper whose ``next()`` blocks FOREVER at the
    ``hang_at``-th call — a dead storage layer.  Unlike
    ``StallingSource`` there is no release: the only way out is the
    hang watchdog (train/watchdog.py) unwinding the consumer and the
    recovery wrapper rebuilding the pipeline (the abandoned daemon
    worker thread dies with the process).  One-shot: a source
    constructed fresh for a restarted incarnation hangs again, so tests
    wrap only the first incarnation's iterator.

    The wait sleeps in small increments so a TRAINING thread that calls
    ``next()`` directly (the unfused/streaming paths go through the
    prefetch queue instead) stays interruptible at bytecode
    boundaries."""

    def __init__(self, source, hang_at: int = 0):
        self.source = source
        self.hang_at = hang_at
        self.calls = 0
        self.hung = threading.Event()   # observable: a consumer is stuck

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        self.calls += 1
        if self.calls - 1 == self.hang_at:
            self.hung.set()
            while True:  # never released — the watchdog's problem now
                time.sleep(0.05)
        return self.source.next()

    def __getattr__(self, name):
        return getattr(self.source, name)


class NanSource:
    """DataSet-iterator wrapper that poisons the features of the
    ``nan_at``-th emitted batch with NaNs (a bad record reaching the
    gradient path)."""

    def __init__(self, source, nan_at: int,
                 rng: Optional[random.Random] = None):
        self.source = source
        self.nan_at = nan_at
        self.emitted = 0
        self.rng = rng or random.Random(0)

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        ds = self.source.next()
        if self.emitted == self.nan_at:
            feats = np.array(ds.features, copy=True)
            flat = feats.reshape(-1)
            flat[self.rng.randrange(flat.size)] = np.nan
            ds.features = feats
        self.emitted += 1
        return ds

    def __getattr__(self, name):
        return getattr(self.source, name)
