"""Fault injection — prove the fault-tolerance contract, don't assert it.

The robustness claims of checkpoint/ and train/ (``restart equals never
failed``, ``no kill point leaves the directory unrestorable``) are only
claims until something actually kills the process mid-write, corrupts a
file, stalls the data source, or poisons the gradients.  This module is
the seeded, deterministic injector that does all four, driven by
``tests/test_chaos.py``:

* **kill-during-save** — ``ChaosInjector.kill_at_save_event`` hooks the
  checkpointer's enumerated write/rename points (``checkpointer.
  _chaos_hook``) and raises ``InjectedCrash`` at the chosen one; the
  exception carries ``simulates_kill = True`` so the checkpointer skips
  its graceful temp cleanup and the directory is left exactly as SIGKILL
  would leave it.  ``count_save_events`` enumerates the points so a test
  can walk every one.  The subprocess variant (actual ``SIGKILL`` at a
  seeded moment — no python frames unwound at all) lives in the test.
* **corrupt-one-file** — flip one seeded byte of one seeded file of a
  committed checkpoint (silent media corruption); **truncate-file** cuts
  a seeded tail off (a torn write that survived a crash).  Both must be
  caught by manifest verification, never loaded.
* **stall-the-data-source** — ``StallingSource`` wraps any DataSet
  iterator and blocks inside ``next()`` at a seeded call until released
  (a hung storage layer); pins that ``PrefetchIterator.close`` neither
  deadlocks nor loses worker errors.  ``HangingSource`` is the terminal
  variant: it NEVER releases (a dead storage layer) — the hang the
  watchdog (train/watchdog.py) converts into a retryable restart.
* **hang-the-readback** — ``ChaosInjector.hang_at_readback`` hooks
  ``utils/device.device_fence`` so a chosen fence call blocks
  indefinitely (a wedged device/tunnel), the OTHER silent hang class.
* **hang-the-serving-dispatch** — ``ChaosInjector.hang_at_dispatch``
  hooks the serving engine's dispatch seam (``serve/engine.py``) so a
  chosen batch dispatch blocks indefinitely; pins that the
  watchdog-supervised dispatch loop fails in-flight requests with a
  typed error and keeps serving (never hangs).  ``SlowRequestSource``
  is the traffic-shaped counterpart: it injects oversized request
  sizes into a load harness's size stream at seeded indices, forcing
  the chunked dispatch path under live traffic.
* **NaN-into-grads** — ``NanSource`` poisons the features of a seeded
  batch (the classic bad-record path to non-finite grads), driving the
  telemetry NaN alarm — and the rollback-with-perturbation heal path —
  end to end.
* **lose-part-of-the-fleet** — ``ChaosInjector.shrink_world`` /
  ``lost_device`` hook the trainer's step-boundary seam
  (``train/gan_trainer._chaos_step_hook``) and raise
  ``DeviceLostError`` at a seeded kill step; afterwards
  ``world_size()`` reports the survivor count, so the next incarnation
  rebuilds its mesh over a device SUBSET (the in-process variant of an
  ``XLA_FLAGS`` re-exec with a smaller
  ``--xla_force_host_platform_device_count``).  Drives the elastic-
  resume layer (parallel/elastic.py, reshard-on-restore) end to end.
* **flaky-reads** — ``FlakySource`` (a source whose ``next()`` raises a
  transient ``OSError`` N times starting at a chosen call, then
  recovers — an NFS blip) and ``FlakyReader`` (the same for a CSV
  reader's ``read()``) drive the bounded-retry layer
  (data/resilient.py ``RetryingSource``/``RetryingReader``) end to end.
* **corrupt-records** — ``CorruptRecordSource`` yields malformed
  batches at chosen emitted indices (seeded NaN rows, or a wrong-width
  table) and ``ChaosInjector.corrupt_csv_rows`` rewrites seeded lines
  of an on-disk CSV as garbage — both feed the quarantine layer
  (``ValidatingSource`` / the row-tolerant ``CSVRecordReader.read``).
* **abuse-the-network-path** — ``SlowLorisClient`` opens a raw socket
  to the HTTP gateway and drips the request body one tiny chunk at a
  time (the classic connection-starvation attack); pins that the
  gateway's TOTAL body-read deadline answers 408 in bounded time no
  matter how slowly bytes arrive.  ``mid_body_disconnect`` sends the
  headers plus a fraction of the declared body and hangs up — the
  vanished-caller case the gateway must count and shrug off without
  losing the connection thread.  ``kill_replica`` stops one engine of
  a live ``Router`` replica set under traffic — the router must eject
  it and drain requests to the survivors with only TYPED failures.
* **kill-the-replica-process** — ``kill_replica_process`` SIGKILLs a
  spawned replica subprocess (serve/replica.py) mid-traffic; the
  control plane must replace it and the mesh must drain to the
  survivors.  ``wedge_replica`` makes a replica report unhealthy
  while still listening (stalled-but-listening — a DIFFERENT ejection
  path than a dead socket).  ``poison_checkpoint_dir`` forges a
  newest checkpoint that VERIFIES but serves NaN — only the canary's
  SLO probe can catch it, and auto-rollback must land on the previous
  step with the rollback budget charged.
  ``poison_fleet_checkpoint_dir`` is the fleet variant: ONE tenant's
  ``gen_params`` slice NaN'd through a genuine ``FleetCheckpointer``
  save, catchable only by the publisher's finite-params probe or the
  canary (docs/SCENARIO.md).
* **cross-plane coordination** — ``ChaosSchedule`` fires a SEEDED
  timeline of the injections above against the training and serving
  planes in the same run (trainer preemption + world shrink AND
  replica kill + slow-loris + corrupt tenant rows), with the resolved
  deterministic timeline written into events up front — the
  combined-chaos scenario's conductor (``bench --scenario``).

Everything is parameterized by an explicit seed: a chaos failure must
replay exactly.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import random
import select
import signal as _signal
import socket
import subprocess
import tempfile
import threading
import time
import zipfile
from http.client import HTTPConnection
from typing import Dict, List, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.checkpoint import checkpointer as _ckpt_mod


class InjectedCrash(RuntimeError):
    """A simulated hard kill.  ``simulates_kill`` tells the checkpointer
    to leave the directory un-cleaned (debris and all), exactly as a
    real SIGKILL would; the recovery wrapper still classifies it as a
    retryable failure (it is a RuntimeError, not a config error)."""

    simulates_kill = True


class DeviceLostError(RuntimeError):
    """A simulated loss of part of the device fleet mid-run (a spot
    eviction, a failed chip).  A plain RuntimeError on purpose: the
    recovery wrapper classifies it RETRYABLE — the restart is exactly
    where the elastic layer re-forms the mesh over the survivors and
    reshards the checkpoint onto it."""


class ChaosInjector:
    """Seeded injector; one instance per test scenario."""

    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)

    # -- kill-during-save ------------------------------------------------------

    def count_save_events(self, save_fn) -> List[str]:
        """Run ``save_fn()`` with a recording hook; return the ordered
        list of chaos events it passed (the enumerable kill points)."""
        events: List[str] = []
        prev = _ckpt_mod._chaos_hook
        _ckpt_mod._chaos_hook = events.append
        try:
            save_fn()
        finally:
            _ckpt_mod._chaos_hook = prev
        return events

    def kill_at_save_event(self, index: int,
                           after_times: int = 0) -> "_KillPoint":
        """Context manager: the ``index``-th chaos event of the
        (``after_times``+1)-th save inside the block raises
        ``InjectedCrash``.  ``after_times`` lets a test crash the Nth
        save of a run while earlier ones succeed."""
        return _KillPoint(index, after_times)

    # -- corruption ------------------------------------------------------------

    def corrupt_one_file(self, ckpt_dir: str,
                         exclude_manifest: bool = False) -> tuple:
        """Flip one seeded byte of one seeded file under ``ckpt_dir``
        (committed checkpoint).  Returns (path, offset).  With
        ``exclude_manifest`` the manifest itself stays intact — the
        harder case: the corruption is only discoverable by hashing."""
        import os

        files = sorted(
            f for f in os.listdir(ckpt_dir)
            if os.path.isfile(os.path.join(ckpt_dir, f))
            and not (exclude_manifest and f == _ckpt_mod.MANIFEST_NAME))
        name = self.rng.choice(files)
        path = os.path.join(ckpt_dir, name)
        data = bytearray(open(path, "rb").read())
        off = self.rng.randrange(len(data))
        data[off] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(data))
        return path, off

    def truncate_file(self, ckpt_dir: str) -> tuple:
        """Cut a seeded non-empty tail off one seeded data file (torn
        write).  Returns (path, new_size)."""
        import os

        files = sorted(
            f for f in os.listdir(ckpt_dir)
            if os.path.isfile(os.path.join(ckpt_dir, f))
            and f != _ckpt_mod.MANIFEST_NAME)
        name = self.rng.choice(files)
        path = os.path.join(ckpt_dir, name)
        size = os.path.getsize(path)
        new_size = self.rng.randrange(max(1, size))  # strictly shorter
        with open(path, "rb+") as f:
            f.truncate(new_size)
        return path, new_size

    def delete_file(self, ckpt_dir: str, name: str) -> str:
        """Remove one named file of a committed checkpoint (e.g.
        ``state.npz`` lost to a filesystem fault)."""
        import os

        path = os.path.join(ckpt_dir, name)
        os.remove(path)
        return path

    def corrupt_csv_rows(self, path: str, n_rows: int = 1,
                         skip_lines: int = 0) -> List[int]:
        """Rewrite ``n_rows`` seeded data lines of an on-disk CSV as
        unparseable garbage (silent upstream-producer corruption /
        bit-rot that still splits into lines).  Returns the 1-based
        line numbers hit — exactly what ``quarantine.jsonl`` must name
        back."""
        with open(path) as f:
            lines = f.read().splitlines()
        eligible = list(range(skip_lines, len(lines)))
        hit = sorted(self.rng.sample(eligible, min(n_rows, len(eligible))))
        for i in hit:
            lines[i] = f"#CORRUPT#,{self.rng.random()},###"
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return [i + 1 for i in hit]

    # -- device loss / world shrink --------------------------------------------

    def shrink_world(self, kill_step: int, before: int,
                     after: int) -> "_ShrinkWorld":
        """Context manager: the run loses ``before - after`` devices at
        the first step boundary >= ``kill_step`` — the trainer's step
        seam raises ``DeviceLostError`` (one-shot; the restarted
        incarnation trains normally) and ``world_size()`` flips from
        ``before`` to ``after``.  The test's ``make_trainer`` reads
        ``world_size()`` so the next incarnation rebuilds its mesh over
        the surviving subset — the in-process equivalent of re-execing
        with a smaller ``--xla_force_host_platform_device_count``."""
        if not 0 < after < before:
            raise ValueError(
                f"shrink_world needs 0 < after < before, got "
                f"{before} -> {after}")
        return _ShrinkWorld(kill_step, before, after)

    def lost_device(self, kill_step: int, before: int,
                    lose: int = 1) -> "_ShrinkWorld":
        """``shrink_world`` phrased as "K devices died": drop ``lose``
        of the ``before`` devices at the seeded kill step."""
        return self.shrink_world(kill_step, before, before - lose)

    # -- hangs -----------------------------------------------------------------

    def hang_at_readback(self, at: int = 0) -> "_ReadbackHang":
        """Context manager: the ``at``-th ``device_fence`` call inside
        the block hangs indefinitely (a wedged device readback /
        tunnel).  One-shot — a restarted run's fences proceed normally,
        so a watchdog-driven restart can finish.  The hang sleeps in
        small increments, which keeps the hung thread interruptible at
        bytecode boundaries — exactly the property a real C-level hang
        lacks until its call returns, and the reason the watchdog also
        dumps diagnostics and checkpoints from its OWN thread."""
        return _ReadbackHang(at)

    def hang_at_dispatch(self, at: int = 0) -> "_DispatchHang":
        """Context manager: the ``at``-th serving batch dispatch inside
        the block hangs indefinitely (``serve/engine.py``'s chaos seam
        — a wedged device under the serving plane).  One-shot: after
        the watchdog fails the in-flight requests and re-arms, later
        dispatches proceed normally, so the "degrade, recover, keep
        serving" contract is what the test observes.  Sleeps in small
        increments for the same bytecode-boundary interruptibility as
        ``hang_at_readback``."""
        return _DispatchHang(at)


class _ReadbackHang:
    def __init__(self, at: int):
        self.at = at
        self.calls = 0
        self.fired = False                  # one-shot, like _KillPoint
        self.hung = threading.Event()       # observable: fence is stuck
        self._release = threading.Event()   # set on __exit__ (cleanup)
        self._prev = None

    def _hook(self) -> None:
        if self.fired:
            return
        if self.calls == self.at:
            self.fired = True
            self.hung.set()
            while not self._release.is_set():
                time.sleep(0.05)
        self.calls += 1

    def __enter__(self) -> "_ReadbackHang":
        from gan_deeplearning4j_tpu.utils import device as _device_mod

        self._device_mod = _device_mod
        self._prev = _device_mod._chaos_readback_hook
        _device_mod._chaos_readback_hook = self._hook
        return self

    def __exit__(self, *exc) -> None:
        self._device_mod._chaos_readback_hook = self._prev
        self._release.set()  # free any thread still parked in the hook


class _DispatchHang:
    """Seeded serving-dispatch hang (``ChaosInjector.hang_at_dispatch``):
    parks the ``at``-th batch dispatch of ``serve/engine.py`` until the
    watchdog unwinds it (or ``__exit__`` releases the parked thread on
    cleanup).  Structured exactly like ``_ReadbackHang`` — observable
    ``hung`` event, one-shot ``fired`` flag, released on exit."""

    def __init__(self, at: int):
        self.at = at
        self.calls = 0
        self.fired = False                  # one-shot, like _ReadbackHang
        self.hung = threading.Event()       # observable: dispatch stuck
        self._release = threading.Event()   # set on __exit__ (cleanup)
        self._prev = None

    def _hook(self) -> None:
        if self.fired:
            return
        if self.calls == self.at:
            self.fired = True
            self.hung.set()
            while not self._release.is_set():
                time.sleep(0.05)
        self.calls += 1

    def __enter__(self) -> "_DispatchHang":
        from gan_deeplearning4j_tpu.serve import engine as _serve_mod

        self._serve_mod = _serve_mod
        self._prev = _serve_mod._chaos_dispatch_hook
        _serve_mod._chaos_dispatch_hook = self._hook
        return self

    def __exit__(self, *exc) -> None:
        self._serve_mod._chaos_dispatch_hook = self._prev
        self._release.set()  # free any thread still parked in the hook


class SlowRequestSource:
    """Request-size iterator wrapper that injects OVERSIZED sizes at
    seeded emitted indices — the serving-plane burst/abuse pattern: a
    caller whose batches exceed the largest declared bucket forces the
    chunked dispatch path under live traffic.  Wraps any iterable of
    row counts (e.g. the load harness's size stream); ``factor`` scales
    the hit sizes past ``largest_bucket``."""

    def __init__(self, sizes, largest_bucket: int, slow_at=(0,),
                 factor: int = 2):
        if factor < 1:
            raise ValueError("factor must be >= 1")
        self._sizes = iter(sizes)
        self.largest_bucket = int(largest_bucket)
        self.slow_at = frozenset(slow_at)
        self.factor = int(factor)
        self.emitted = 0
        self.injected = 0

    def __iter__(self) -> "SlowRequestSource":
        return self

    def __next__(self) -> int:
        size = next(self._sizes)
        if self.emitted in self.slow_at:
            self.injected += 1
            size = self.largest_bucket * self.factor + size
        self.emitted += 1
        return size


class _ShrinkWorld:
    """Seeded device-loss injector (``ChaosInjector.shrink_world``).
    Installs the trainer step-boundary hook for the with-block; fires
    ONCE at the first boundary at or past ``kill_step`` (chunked paths
    only visit multiples of steps_per_call, so "at or past" is the
    honest contract), then reports the shrunken world."""

    def __init__(self, kill_step: int, before: int, after: int):
        self.kill_step = kill_step
        self.before = before
        self.after = after
        self.fired = False          # one-shot, like _KillPoint
        self.killed_at: Optional[int] = None
        self._prev = None

    def world_size(self) -> int:
        """Devices alive right now: ``before`` until the kill fires,
        ``after`` from then on — what an elastic ``make_trainer`` hands
        to ``n_devices``."""
        return self.after if self.fired else self.before

    def _hook(self, step: int) -> None:
        if self.fired or step < self.kill_step:
            return
        self.fired = True
        self.killed_at = step
        raise DeviceLostError(
            f"injected device loss at step {step}: fleet shrank "
            f"{self.before} -> {self.after} devices")

    def __enter__(self) -> "_ShrinkWorld":
        from gan_deeplearning4j_tpu.train import gan_trainer as _gt_mod

        self._gt_mod = _gt_mod
        self._prev = _gt_mod._chaos_step_hook
        _gt_mod._chaos_step_hook = self._hook
        return self

    def __exit__(self, *exc) -> None:
        self._gt_mod._chaos_step_hook = self._prev


class _KillPoint:
    def __init__(self, index: int, after_times: int):
        self.index = index
        self.after_times = after_times
        self.fired = False  # one-shot: a killed process stays dead once
        self._events = 0
        self._saves_seen = 0
        self._prev = None

    def _hook(self, event: str) -> None:
        if self.fired:
            return  # the "process" already died; later saves (the
            # restarted run's) proceed normally
        if self._saves_seen < self.after_times:
            if event == "post_swap":  # one per completed save
                self._saves_seen += 1
            return
        if self._events == self.index:
            self.fired = True
            raise InjectedCrash(
                f"injected kill at save event #{self.index} ({event!r})")
        self._events += 1

    def __enter__(self) -> "_KillPoint":
        self._prev = _ckpt_mod._chaos_hook
        _ckpt_mod._chaos_hook = self._hook
        return self

    def __exit__(self, *exc) -> None:
        _ckpt_mod._chaos_hook = self._prev


class StallingSource:
    """DataSet-iterator wrapper whose ``next()`` blocks at the
    ``stall_at``-th call until ``release()`` (or forever) — a wedged
    storage layer under the prefetch worker."""

    def __init__(self, source, stall_at: int):
        self.source = source
        self.stall_at = stall_at
        self.calls = 0
        self.stalled = threading.Event()   # observable: worker is stuck
        self._release = threading.Event()

    def release(self) -> None:
        self._release.set()

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        self.calls += 1
        if self.calls - 1 == self.stall_at:
            self.stalled.set()
            self._release.wait()  # block until the test releases us
        return self.source.next()

    def __getattr__(self, name):
        return getattr(self.source, name)


class HangingSource:
    """DataSet-iterator wrapper whose ``next()`` blocks FOREVER at the
    ``hang_at``-th call — a dead storage layer.  Unlike
    ``StallingSource`` there is no release: the only way out is the
    hang watchdog (train/watchdog.py) unwinding the consumer and the
    recovery wrapper rebuilding the pipeline (the abandoned daemon
    worker thread dies with the process).  One-shot: a source
    constructed fresh for a restarted incarnation hangs again, so tests
    wrap only the first incarnation's iterator.

    The wait sleeps in small increments so a TRAINING thread that calls
    ``next()`` directly (the unfused/streaming paths go through the
    prefetch queue instead) stays interruptible at bytecode
    boundaries."""

    def __init__(self, source, hang_at: int = 0):
        self.source = source
        self.hang_at = hang_at
        self.calls = 0
        self.hung = threading.Event()   # observable: a consumer is stuck

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        self.calls += 1
        if self.calls - 1 == self.hang_at:
            self.hung.set()
            while True:  # never released — the watchdog's problem now
                time.sleep(0.05)
        return self.source.next()

    def __getattr__(self, name):
        return getattr(self.source, name)


class FlakySource:
    """DataSet-iterator wrapper whose ``next()`` raises a TRANSIENT
    ``OSError`` on ``failures`` consecutive calls starting at call
    index ``at``, then succeeds forever — an NFS blip / flaky disk
    under the reader.  The failure happens BEFORE the delegate is
    touched, so a retried call replays the exact same batch sequence
    (the property the bit-identical-resume tests lean on).  Seeded:
    the error payload carries the seed so a chaos failure replays
    exactly."""

    def __init__(self, source, failures: int = 1, at: int = 0,
                 seed: int = 0):
        self.source = source
        self.failures = failures
        self.at = at
        self.seed = seed
        self.calls = 0
        self.raised = 0

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        call = self.calls
        self.calls += 1
        if self.at <= call < self.at + self.failures:
            self.raised += 1
            raise OSError(
                f"injected transient read failure "
                f"{self.raised}/{self.failures} (seed {self.seed})")
        return self.source.next()

    def __getattr__(self, name):
        return getattr(self.source, name)


class FlakyReader:
    """CSV-reader wrapper whose ``read()`` raises a transient
    ``OSError`` the first ``failures`` calls, then delegates — the
    ingest-time counterpart of ``FlakySource`` (drives
    ``RetryingReader``)."""

    def __init__(self, reader, failures: int = 1, seed: int = 0):
        self.reader = reader
        self.failures = failures
        self.seed = seed
        self.calls = 0

    def read(self, *a, **kw):
        call = self.calls
        self.calls += 1
        if call < self.failures:
            raise OSError(
                f"injected transient decode failure "
                f"{call + 1}/{self.failures} (seed {self.seed})")
        return self.reader.read(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.reader, name)


class CorruptRecordSource:
    """DataSet-iterator wrapper that yields MALFORMED batches at the
    chosen emitted indices — the runtime-corruption counterpart of
    ``corrupt_csv_rows``.  ``mode="nan"`` poisons one seeded row per
    hit batch with NaNs (a bad record that parsed); ``mode="shape"``
    emits the batch with an extra feature column (a producer schema
    break).  Drives the quarantine layer (data/resilient.py
    ``ValidatingSource``): NaN rows must be skipped-and-charged
    row-by-row, shape breaks quarantined as a batch."""

    def __init__(self, source, corrupt_at=(0,), mode: str = "nan",
                 rng: Optional[random.Random] = None):
        if mode not in ("nan", "shape"):
            raise ValueError(f"unknown corruption mode {mode!r}")
        self.source = source
        self.corrupt_at = frozenset(corrupt_at)
        self.mode = mode
        self.rng = rng or random.Random(0)
        self.emitted = 0
        self.corrupted = 0

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        ds = self.source.next()
        if self.emitted in self.corrupt_at:
            self.corrupted += 1
            feats = np.array(ds.features, copy=True)
            if self.mode == "nan":
                feats[self.rng.randrange(max(1, feats.shape[0]))] = np.nan
            else:  # "shape": one extra column — the record width broke
                feats = np.concatenate(
                    [feats, np.zeros((feats.shape[0], 1), feats.dtype)],
                    axis=1)
            ds.features = feats
        self.emitted += 1
        return ds

    def __getattr__(self, name):
        return getattr(self.source, name)


class NanSource:
    """DataSet-iterator wrapper that poisons the features of the
    ``nan_at``-th emitted batch with NaNs (a bad record reaching the
    gradient path)."""

    def __init__(self, source, nan_at: int,
                 rng: Optional[random.Random] = None):
        self.source = source
        self.nan_at = nan_at
        self.emitted = 0
        self.rng = rng or random.Random(0)

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        return self.source.reset()

    def next(self):
        ds = self.source.next()
        if self.emitted == self.nan_at:
            feats = np.array(ds.features, copy=True)
            flat = feats.reshape(-1)
            flat[self.rng.randrange(flat.size)] = np.nan
            ds.features = feats
        self.emitted += 1
        return ds

    def __getattr__(self, name):
        return getattr(self.source, name)


# -- network-path injectors (serve/gateway.py) --------------------------------

_DEFAULT_LORIS_BODY = b'{"inputs": [[[0.0, 0.0]]]}'


def _request_head(path: str, body_len: int, content_type: str) -> bytes:
    return (f"POST {path} HTTP/1.1\r\n"
            f"Host: chaos\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {body_len}\r\n"
            f"Connection: close\r\n\r\n").encode("ascii")


def _read_status(sock: socket.socket) -> Optional[int]:
    """Best-effort read of the response status line from a raw socket
    (the peer may have closed already — that's a legitimate outcome
    for an abusive client)."""
    try:
        sock.settimeout(2.0)
        data = b""
        while b"\r\n" not in data and len(data) < 4096:
            chunk = sock.recv(1024)
            if not chunk:
                break
            data += chunk
        parts = data.split(b" ", 2)
        return int(parts[1]) if len(parts) >= 2 else None
    except (OSError, ValueError, IndexError):
        return None


class SlowLorisClient:
    """Raw-socket client that sends complete headers declaring the full
    ``Content-Length``, then drips the body ``drip_bytes`` at a time
    every ``drip_interval_s`` — the connection-starvation abuse
    pattern.  A per-recv socket timeout on the server is USELESS here
    (every drip resets it); only a TOTAL body-read deadline bounds the
    connection hold time, which is exactly what the test asserts:
    ``run()`` returns as soon as the server answers (or resets), and
    the elapsed time must be far below the full drip duration.

    ``run(max_s)`` returns ``(status, elapsed_s, sent_bytes)`` —
    ``status`` is the HTTP status the server managed to send (408 from
    a well-behaved gateway) or None if the connection just died."""

    def __init__(self, host: str, port: int, path: str = "/v1/generate",
                 body: bytes = _DEFAULT_LORIS_BODY,
                 content_type: str = "application/json",
                 drip_bytes: int = 1, drip_interval_s: float = 0.1):
        if drip_bytes <= 0 or drip_interval_s < 0:
            raise ValueError("drip_bytes must be > 0 and "
                             "drip_interval_s >= 0")
        self.host = host
        self.port = int(port)
        self.path = path
        self.body = bytes(body)
        self.content_type = content_type
        self.drip_bytes = int(drip_bytes)
        self.drip_interval_s = float(drip_interval_s)

    def run(self, max_s: float = 30.0
            ) -> Tuple[Optional[int], float, int]:
        t0 = time.monotonic()
        sent = 0
        status: Optional[int] = None
        with socket.create_connection((self.host, self.port),
                                      timeout=5.0) as sock:
            sock.sendall(_request_head(self.path, len(self.body),
                                       self.content_type))
            while sent < len(self.body) \
                    and time.monotonic() - t0 < max_s:
                # an early answer (the 408) ends the abuse: a loris
                # that keeps dripping into a closed window just eats
                # a reset
                readable, _, _ = select.select([sock], [], [], 0)
                if readable:
                    break
                try:
                    sock.sendall(
                        self.body[sent:sent + self.drip_bytes])
                    sent += self.drip_bytes
                except OSError:  # gan4j-lint: disable=swallowed-exception — a server reset mid-drip IS a result for this injector: stop dripping and read whatever status the server managed to send
                    break
                time.sleep(self.drip_interval_s)
            status = _read_status(sock)
        return status, time.monotonic() - t0, min(sent, len(self.body))


def mid_body_disconnect(host: str, port: int,
                        path: str = "/v1/generate",
                        body: bytes = _DEFAULT_LORIS_BODY,
                        content_type: str = "application/json",
                        frac: float = 0.5) -> int:
    """Send complete headers declaring ``len(body)`` bytes, then only
    ``frac`` of the body, then hang up — the vanished-caller case.
    The gateway must count it and release the connection thread; there
    is nobody left to answer.  Returns the body bytes actually sent."""
    if not 0 <= frac < 1:
        raise ValueError("frac must be in [0, 1)")
    cut = int(len(body) * frac)
    with socket.create_connection((host, port), timeout=5.0) as sock:
        sock.sendall(_request_head(path, len(body), content_type))
        if cut:
            sock.sendall(body[:cut])
    return cut


def kill_replica(router, index: int):
    """Stop one engine of a live ``Router`` replica set — the
    mid-load replica death the router must absorb: the dead replica is
    ejected on its next probe/submit and requests drain to the
    survivors with only TYPED failures.  Returns the stopped engine
    (restartable with ``engine.start()`` to exercise recovery)."""
    eng = router.replicas[index]
    eng.stop()
    return eng


# -- process-level injectors (the mesh/control-plane chaos set) ---------------


def kill_replica_process(proc) -> int:
    """SIGKILL a spawned replica subprocess — the process-level
    variant of ``kill_replica``: no drain, no goodbye, no python
    frames unwound.  The control plane must notice the corpse, eject
    it from the mesh, spawn a replacement, and keep every in-flight
    failure TYPED.  Accepts a ``controlplane.ReplicaProcess`` or a
    raw ``Popen``; reaps (bounded) and returns the pid."""
    popen = getattr(proc, "proc", proc)
    pid = popen.pid
    if popen.poll() is None:
        os.kill(pid, _signal.SIGKILL)
    try:
        popen.wait(timeout=10.0)
    except subprocess.TimeoutExpired:  # gan4j-lint: disable=swallowed-exception — a SIGKILLed child the kernel will not reap within 10s is not this injector's bug; the caller's alive() polling still sees the truth
        pass
    return pid


def wedge_replica(host: str, port: int,
                  seconds: float = 5.0) -> Dict:
    """Make a replica report UNHEALTHY for ``seconds`` while its
    socket keeps accepting — the stalled-but-listening failure mode
    (a dead socket is ejected by a refused connect; a wedged replica
    must be ejected by its 503 /healthz, which is a different code
    path).  Drives the replica's ``POST /admin/chaos/wedge`` verb;
    returns the replica's acknowledgment."""
    conn = HTTPConnection(host, port, timeout=10.0)
    try:
        body = json.dumps({"seconds": float(seconds)}).encode("utf-8")
        conn.request("POST", "/admin/chaos/wedge", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
    finally:
        conn.close()
    if resp.status != 200:
        raise RuntimeError(
            f"wedge_replica: HTTP {resp.status} from {host}:{port}: "
            f"{data[:200]!r}")
    return json.loads(data.decode("utf-8"))["result"]


def poison_checkpoint_dir(directory: str, name: str = "gen") -> int:
    """Forge a VERIFYING-but-poisoned newest checkpoint: copy the
    newest verified ``ckpt_N`` to ``ckpt_{N+1}`` with every float
    param of graph ``name`` NaN'd and the manifest REBUILT over the
    new bytes.  Manifest verification passes — this is not a torn
    write but a semantically bad save (the artifact of a diverged run
    or a bad export), so only the control plane's canary SLO probe
    (finite outputs) can catch it, and rollback must land on step N.
    Returns the poisoned step."""
    ckpt = _ckpt_mod.TrainCheckpointer(directory, sweep_debris=False)
    steps = ckpt.steps()
    base = None
    for s in reversed(steps):
        if ckpt.verify(s):
            base = s
            break
    if base is None:
        raise FileNotFoundError(
            f"no verified checkpoint in {directory} to poison")
    new_step = max(steps) + 1
    src = os.path.join(directory, f"ckpt_{base}")
    with open(os.path.join(src, _ckpt_mod.MANIFEST_NAME)) as f:
        src_manifest = json.load(f)
    model_file = f"{name}_model.zip"
    if model_file not in src_manifest["files"]:
        raise FileNotFoundError(
            f"ckpt_{base} has no graph {name!r} "
            f"(files: {sorted(src_manifest['files'])})")
    from gan_deeplearning4j_tpu.graph import serialization

    with zipfile.ZipFile(os.path.join(src, model_file)) as z:
        cfg = json.loads(z.read("config.json").decode("utf-8"))
        with np.load(io.BytesIO(z.read("params.npz")),
                     allow_pickle=False) as f:
            params = {k: np.asarray(f[k]) for k in f.files}
        with np.load(io.BytesIO(z.read("updater.npz")),
                     allow_pickle=False) as f:
            updater = {k: np.asarray(f[k]) for k in f.files}
    poisoned = {k: (np.full_like(v, np.nan)
                    if np.issubdtype(v.dtype, np.floating) else v)
                for k, v in params.items()}
    tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=directory)
    entries: Dict[str, Dict] = {}

    def put(fname: str, data: bytes) -> None:
        path = os.path.join(tmp, fname)
        with open(path, "wb") as fh:
            fh.write(data)
        _ckpt_mod._fsync_file(path)
        entries[fname] = {"bytes": len(data),
                          "sha256": hashlib.sha256(data).hexdigest()}

    put(model_file,
        serialization.model_zip_bytes(cfg, poisoned, updater))
    for fname in src_manifest["files"]:
        if fname == model_file:
            continue
        with open(os.path.join(src, fname), "rb") as fh:
            data = fh.read()
        if fname == "state.json":
            scalars = json.loads(data.decode("utf-8"))
            scalars["step"] = new_step
            data = json.dumps(scalars, indent=1).encode("utf-8")
        put(fname, data)
    manifest: Dict = {"step": new_step, "files": entries}
    if "mesh_spec" in src_manifest:
        manifest["mesh_spec"] = src_manifest["mesh_spec"]
    mpath = os.path.join(tmp, _ckpt_mod.MANIFEST_NAME)
    with open(mpath, "w") as fh:
        json.dump(manifest, fh, indent=1)
    _ckpt_mod._fsync_file(mpath)
    _ckpt_mod._fsync_dir(tmp)
    os.rename(tmp, os.path.join(directory, f"ckpt_{new_step}"))
    _ckpt_mod._fsync_dir(directory)
    return new_step


def poison_fleet_checkpoint_dir(directory: str, tenant: int = 0) -> int:
    """Fleet variant of :func:`poison_checkpoint_dir`: forge a
    VERIFYING newest fleet checkpoint whose ``gen_params`` are NaN for
    ONE tenant's slice.  The forgery goes through
    ``FleetCheckpointer.save`` itself (restore newest verified → NaN
    the slice → save as step N+1), so manifest hashing is genuine —
    only a semantic probe can catch it: the publisher's finite-params
    probe over ``state.npz`` (rejection at publication), or — had it
    been deployed — the canary's finite-output probe against the
    tenant's serving engine (``FleetTenantBank`` path, tenant 0 being
    the fleet replica's plain-probe engine).  Returns the poisoned
    step."""
    from gan_deeplearning4j_tpu.train.fleet import FleetCheckpointer

    # keep ALL existing checkpoints (the forge must not prune the live
    # trainer's history) and never sweep the owner's in-flight tmps
    ck = FleetCheckpointer(directory, keep=10 ** 9, sweep_debris=False)
    steps = ck._inner.steps()
    if not steps:
        raise FileNotFoundError(
            f"no checkpoints in {directory} to poison")
    # target_mesh=None: the forge runs host-side (maybe fewer devices
    # than the trainer that wrote the checkpoint); extras-only fleet
    # restores carry no sharded graphs, so nothing needs resharding
    _, state, _ = ck.restore(target_mesh=None)
    n = int(state.it.shape[0])
    if not 0 <= int(tenant) < n:
        raise ValueError(f"tenant {tenant} outside fleet of {n}")

    def nan_slice(x):
        arr = np.array(np.asarray(x), copy=True)
        if np.issubdtype(arr.dtype, np.floating):
            arr[int(tenant)] = np.nan
        return arr

    import jax

    poisoned = state._replace(
        gen_params=jax.tree.map(nan_slice, state.gen_params))
    new_step = max(steps) + 1
    ck.save(new_step, poisoned)
    return new_step


def poison_tenant_params(manager, tenant: int) -> None:
    """Queue a NaN param-poison of ONE tenant's lane for the next
    window boundary (``FleetManager.request`` → ``poison_params``):
    the lifecycle-chaos injection the per-tenant health sentinel must
    catch by quarantining exactly that tenant — its cohort-mates' loss
    timelines stay bit-equal to an undisturbed control (the lane-
    independence pin).  Boundary-queued because fleet membership and
    state surgery only happen between windows — a mid-dispatch poison
    would race the donated step's buffers."""
    manager.request(lambda: manager.poison_params(int(tenant)))


class TenantFeedPoisoner:
    """Flag-guarded per-tenant feed corruption for lifecycle fleets.

    Wraps a fleet feed callback ``feed(window) -> (features, labels)``;
    once :meth:`arm`\\ ed (typically from a :class:`ChaosSchedule`
    thread), every row of ``tenant``'s segment (``row % num_segments
    == tenant`` — the ``TenantRouter`` ownership rule) comes back NaN.
    The router's per-tenant quarantine budget then trips THAT tenant
    (``raise_on_budget=False`` → a ``tripped`` marker, never an
    exception through the fleet loop) while every other segment's rows
    pass through untouched — byte-identical to the unwrapped feed, so
    survivors keep their bit-equal-to-control timelines."""

    def __init__(self, feed, tenant: int, num_segments: int):
        self._feed = feed
        self.tenant = int(tenant)
        self.num_segments = int(num_segments)
        self._armed = threading.Event()
        self.windows_poisoned = 0

    def arm(self) -> None:
        self._armed.set()

    def disarm(self) -> None:
        self._armed.clear()

    @property
    def armed(self) -> bool:
        return self._armed.is_set()

    def __call__(self, window: int):
        feats, labs = self._feed(window)
        if not self._armed.is_set():
            return feats, labs
        feats = np.array(np.asarray(feats), np.float32, copy=True)
        rows = np.arange(feats.shape[0])
        feats[rows % self.num_segments == self.tenant] = np.nan
        self.windows_poisoned += 1
        return feats, labs


class ChaosSchedule:
    """A seeded CROSS-PLANE chaos timeline: one coordinator firing
    injections against the training plane (preemption signal, world
    shrink, corrupt tenant rows) and the serving plane (replica kill,
    slow-loris, wedge) in the same run — the combined-chaos scenario's
    conductor (scenario/runner.py, docs/SCENARIO.md).

    Determinism contract: actions are registered with ``add(at_s,
    name, fn)`` in a fixed caller order; per-entry jitter (when
    ``jitter_s`` > 0) is drawn from ``random.Random(seed)`` in that
    order, so the same seed + same registration sequence yields the
    same resolved timeline, every run.  The resolved timeline is
    written into the events stream UP FRONT (``chaos.schedule``) and
    every firing lands a ``chaos.fire`` event with the action's
    outcome — an action's exception is captured and counted, never
    allowed to kill the coordinator thread (chaos that crashes the
    chaos harness proves nothing)."""

    def __init__(self, seed: int, *, jitter_s: float = 0.0):
        self.seed = int(seed)
        self.jitter_s = float(jitter_s)
        self._rng = random.Random(self.seed)
        self._entries: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.fired: list = []

    def add(self, at_s: float, name: str, fn, **attrs) -> float:
        """Register ``fn`` to fire ``at_s`` seconds (plus seeded
        jitter) after ``start()``.  Returns the resolved offset."""
        if self._thread is not None:
            raise RuntimeError("schedule already started")
        at = float(at_s)
        if self.jitter_s > 0:
            at += self._rng.uniform(0.0, self.jitter_s)
        self._entries.append({"at_s": round(at, 3), "name": str(name),
                              "fn": fn, "attrs": dict(attrs)})
        return at

    def timeline(self) -> list:
        """The resolved deterministic timeline (no callables — the
        JSON-safe form written to events and verdicts)."""
        return [{"at_s": e["at_s"], "name": e["name"], **e["attrs"]}
                for e in sorted(self._entries,
                                key=lambda e: e["at_s"])]

    def start(self) -> "ChaosSchedule":
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("schedule already started")
            t = threading.Thread(
                target=self._run, name="gan4j-chaos-schedule",
                daemon=True)
            self._thread = t
        from gan_deeplearning4j_tpu.telemetry import events

        events.instant("chaos.schedule", seed=self.seed,
                       jitter_s=self.jitter_s,
                       timeline=self.timeline())
        t.start()
        return self

    def _run(self) -> None:
        from gan_deeplearning4j_tpu.telemetry import events

        t0 = time.monotonic()
        for entry in sorted(self._entries, key=lambda e: e["at_s"]):
            delay = t0 + entry["at_s"] - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            rec = {"name": entry["name"], "at_s": entry["at_s"],
                   "error": None}
            try:
                entry["fn"]()
            except Exception as e:  # gan4j-lint: disable=swallowed-exception — an injection that raises (its target already dead, a race with the plane it attacks) is an OUTCOME to record, not a coordinator crash
                rec["error"] = repr(e)
            with self._lock:
                self.fired.append(rec)
            events.instant("chaos.fire", action=rec["name"],
                           at_s=rec["at_s"], error=rec["error"])

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)

    def __enter__(self) -> "ChaosSchedule":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def report(self) -> Dict:
        with self._lock:
            fired = list(self.fired)
        return {"seed": self.seed,
                "planned": len(self._entries),
                "fired": len(fired),
                "errors": sum(1 for f in fired if f["error"]),
                "timeline": self.timeline(),
                "outcomes": fired}


class LeakyDispatchSource:
    """Reference-hoarding leak injector for the soak gate
    (``bench --soak --soak-leak``): hooks the serving engine's
    per-batch dispatch (``serve/engine._chaos_dispatch_hook``, the
    same seam ``hang_at_dispatch`` uses) and APPENDS
    ``bytes_per_dispatch`` of live memory to an internal hoard on
    every batch — the classic "a callback captured a buffer and the
    list never drains" production leak.  RSS then grows linearly with
    served load, which is exactly the signature
    ``telemetry/resources.leak_verdict`` must flag; the CI soak lane
    uses this to prove the leak gate CAN fail."""

    def __init__(self, bytes_per_dispatch: int = 256 << 10):
        if bytes_per_dispatch <= 0:
            raise ValueError("bytes_per_dispatch must be > 0")
        self.bytes_per_dispatch = int(bytes_per_dispatch)
        self.hoard: list = []   # the leak: grows forever, never read
        self.dispatches = 0
        self._prev = None
        self._serve_mod = None

    def _hook(self) -> None:
        # bytearray, not bytes: guarantees fresh, non-interned pages
        self.hoard.append(bytearray(self.bytes_per_dispatch))
        self.dispatches += 1

    def install(self) -> "LeakyDispatchSource":
        from gan_deeplearning4j_tpu.serve import engine as _serve_mod

        self._serve_mod = _serve_mod
        self._prev = _serve_mod._chaos_dispatch_hook
        _serve_mod._chaos_dispatch_hook = self._hook
        return self

    def uninstall(self) -> None:
        if self._serve_mod is not None:
            self._serve_mod._chaos_dispatch_hook = self._prev
            self._serve_mod = None
        self.hoard.clear()

    def __enter__(self) -> "LeakyDispatchSource":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
