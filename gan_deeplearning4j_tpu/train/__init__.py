"""Trainers — the reference's two application mains (SURVEY.md §1 L7)
re-built on the framework: the three-graph GAN protocol engine plus the
CV DCGAN and insurance MLP-GAN entry points."""

from gan_deeplearning4j_tpu.train.early_stopping import (
    EarlyStoppingConfig,
    EarlyStoppingGraphTrainer,
    EarlyStoppingResult,
)
from gan_deeplearning4j_tpu.train.gan_trainer import (
    GANTrainer,
    GANTrainerConfig,
    Workload,
)
from gan_deeplearning4j_tpu.train.preemption import (
    PreemptionError,
    PreemptionGuard,
)

__all__ = ["EarlyStoppingConfig", "EarlyStoppingGraphTrainer",
           "EarlyStoppingResult", "GANTrainer", "GANTrainerConfig",
           "PreemptionError", "PreemptionGuard", "Workload"]
