"""Divergence sentinel — trip on the blowup BEFORE the NaNs.

The NaN alarm (telemetry/ingraph.py) fires on the first non-finite
value, which for a diverging GAN is the LAST act: losses and gradient
norms explode for tens of steps first (the classic D-overpowers-G
spiral, the reference papers over it with hand-tuned fixed LRs).  By
the time a NaN materializes, every checkpoint of the blowup window
holds half-cooked weights.  ``DivergenceSentinel`` watches the SAME
materialized metrics stream the NaN alarm rides
(``MetricsLogger.on_record``, worker thread — the training thread pays
nothing) and trips while the numbers are still finite, so the
rollback/snapshot happens with more healthy checkpoints to fall back
to.

Detection is windowed and robust, per watched series (losses and the
in-graph grad norms):

* keep a rolling window of the last ``window`` finite values;
* once ``min_history`` values exist, a value whose magnitude exceeds
  ``factor`` x the window MEDIAN magnitude (floored at ``floor`` so an
  early near-zero loss cannot make any value look explosive) counts as
  an outlier;
* ``patience`` CONSECUTIVE outliers on one series trip the sentinel —
  a single lucky batch does not.

The sentinel is latched like the NaN alarm (first trip wins, thread
safe) and the trainer decides what a trip means — warn / snapshot /
abort / rollback, the same action vocabulary (train/gan_trainer.py).
``DivergenceError`` (the abort action) is FATAL in
``train_with_recovery``: a deterministic replay from the last
checkpoint marches into the same divergence, exactly the NaN-abort
rationale; the ``rollback`` action is the one that heals
(train/rollback.py — restore an earlier checkpoint, cut the LR,
perturb the noise stream).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Callable, Dict, Optional

# series suffixes the sentinel watches: the three protocol losses and
# the in-graph global grad norms (d_/g_/clf_ prefixed, telemetry/ingraph)
_WATCH_SUFFIXES = ("_loss", "_grad_norm")


class DivergenceError(RuntimeError):
    """Raised by the trainer when the divergence sentinel trips with
    action="abort".  Fatal in the recovery wrapper (deterministic
    replay re-diverges identically); use the rollback action to heal
    instead."""


class DivergenceSentinel:
    """Windowed loss-explosion / grad-norm-blowup detector over
    materialized metrics records.  See module docstring for the
    detection rule; ``observe`` runs on the MetricsLogger worker
    thread, everything it does is O(window) python-float work."""

    def __init__(self, window: int = 64, factor: float = 20.0,
                 patience: int = 3, min_history: int = 8,
                 floor: float = 1e-3,
                 on_trip: Optional[Callable[[Dict], None]] = None):
        if window < min_history:
            raise ValueError(
                f"divergence window ({window}) must be >= min_history "
                f"({min_history})")
        if factor <= 1.0:
            raise ValueError("divergence factor must be > 1")
        if patience < 1:
            raise ValueError("divergence patience must be >= 1")
        self.window = int(window)
        self.factor = float(factor)
        self.patience = int(patience)
        self.min_history = int(min_history)
        self.floor = float(floor)
        self._lock = threading.Lock()
        self._on_trip = on_trip
        self._hist: Dict[str, deque] = {}
        self._streak: Dict[str, int] = {}
        self.tripped = False
        self.step: Optional[int] = None
        self.key: Optional[str] = None
        self.value: Optional[float] = None
        self.baseline: Optional[float] = None
        self.record: Optional[Dict] = None

    @staticmethod
    def _median_abs(values) -> float:
        s = sorted(abs(v) for v in values)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def observe(self, rec: Dict) -> None:
        """MetricsLogger ``on_record`` hook (worker thread).  Non-finite
        values are the NaN alarm's jurisdiction and are skipped here
        (they would also poison the medians).  The history/streak tables
        mutate under the lock — the final flush can drive this from the
        closing thread while the worker drains, and a torn streak would
        miss (or double-fire) a trip."""
        if self.tripped:
            return
        trip = None
        with self._lock:
            for k, v in rec.items():
                if not isinstance(v, (int, float)) or not k.endswith(
                        _WATCH_SUFFIXES):
                    continue
                v = float(v)
                if not math.isfinite(v):
                    continue
                hist = self._hist.get(k)
                if hist is None:
                    hist = self._hist[k] = deque(maxlen=self.window)
                    self._streak[k] = 0
                if len(hist) >= self.min_history:
                    baseline = max(self._median_abs(hist), self.floor)
                    if abs(v) > self.factor * baseline:
                        self._streak[k] += 1
                        if self._streak[k] >= self.patience:
                            trip = (rec, k, v, baseline)
                            break
                    else:
                        self._streak[k] = 0
                hist.append(v)
        if trip is not None:
            self._trip(*trip)

    def _trip(self, rec: Dict, key: str, value: float,
              baseline: float) -> None:
        with self._lock:
            if self.tripped:  # lost the race to another worker record
                return
            self.step = rec.get("step")
            self.key = key
            self.value = value
            self.baseline = baseline
            self.record = rec
            self.tripped = True
        if self._on_trip is not None:
            self._on_trip(rec)

    def describe(self) -> str:
        return (f"divergence: {self.key}={self.value:.6g} exceeded "
                f"{self.factor:g}x the rolling median magnitude "
                f"({self.baseline:.6g}) for {self.patience} consecutive "
                f"records, first at step {self.step}")
