"""Early stopping — DL4J's ``EarlyStoppingGraphTrainer`` equivalent.

The DL4J stack the reference builds on ships
``org.deeplearning4j.earlystopping`` (epoch/iteration termination
conditions, a score calculator over a validation set, best-model saving);
the reference's mains don't use it, but a DL4J user switching stacks
expects it.  This is the TPU-native counterpart over the framework's
``ComputationGraph``: train epoch by epoch from a
``RecordReaderDataSetIterator``, score each epoch on a held-out iterator
via the graph's inference-mode loss (``score_on`` —
``ComputationGraph.score(DataSet)``), track the best epoch, stop on
no-improvement patience / score explosion / max epochs, and restore (and
optionally persist) the best model.

    result = EarlyStoppingGraphTrainer(
        graph, train_iter, val_iter,
        EarlyStoppingConfig(max_epochs=50, patience=5)).fit()
    result.best_epoch, result.best_score, result.reason
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Callable, NamedTuple, Optional

import jax


@dataclasses.dataclass(frozen=True)
class EarlyStoppingConfig:
    """Termination conditions — DL4J's MaxEpochsTermination,
    ScoreImprovementEpochTermination(patience, minImprovement) and
    MaxScoreIterationTermination as one flat config."""

    max_epochs: int = 100
    patience: Optional[int] = None     # epochs without improvement; None = off
    min_improvement: float = 0.0       # improvement smaller than this is none
    max_score: Optional[float] = None  # abort when val score explodes past this
    save_path: Optional[str] = None    # persist the best model zip


class EarlyStoppingResult(NamedTuple):
    reason: str          # "max_epochs" | "patience" | "max_score" | "nan_score"
    details: str
    best_epoch: int
    best_score: float
    total_epochs: int


class EarlyStoppingGraphTrainer:
    """``score_fn``: optional override for the per-epoch validation score
    (graph -> float, lower is better); default = mean inference-mode loss
    over the validation iterator's batches."""

    def __init__(self, graph, train_iter, val_iter=None,
                 config: EarlyStoppingConfig = EarlyStoppingConfig(),
                 score_fn: Optional[Callable] = None):
        if val_iter is None and score_fn is None:
            raise ValueError("need a validation iterator or a score_fn")
        self.graph = graph
        self.train_iter = train_iter
        self.val_iter = val_iter
        self.config = config
        self.score_fn = score_fn

    def _epoch_score(self) -> float:
        if self.score_fn is not None:
            return float(self.score_fn(self.graph))
        total, n = 0.0, 0
        self.val_iter.reset()
        while self.val_iter.has_next():
            ds = self.val_iter.next()
            total += self.graph.score_on(ds.features, ds.labels)
            n += 1
        return total / max(n, 1)

    def fit(self) -> EarlyStoppingResult:
        c = self.config
        best_score = math.inf
        best_epoch = -1
        best_params = None
        stale = 0
        reason, details = "max_epochs", f"completed {c.max_epochs} epochs"
        epoch = 0
        for epoch in range(1, c.max_epochs + 1):
            self.train_iter.reset()
            while self.train_iter.has_next():
                ds = self.train_iter.next()
                self.graph.fit(ds.features, ds.labels)
            score = self._epoch_score()
            if math.isnan(score):
                # NaN compares False against every bound — without this
                # a diverged run would silently train to max_epochs
                reason = "nan_score"
                details = f"validation score NaN at epoch {epoch}"
                break
            if c.max_score is not None and score > c.max_score:
                reason = "max_score"
                details = f"score {score:.6f} > max_score {c.max_score}"
                break
            if score < best_score - c.min_improvement:
                best_score, best_epoch, stale = score, epoch, 0
                # snapshot device arrays by reference (immutable pytrees)
                best_params = jax.tree_util.tree_map(
                    lambda x: x, self.graph.params)
            else:
                stale += 1
                if c.patience is not None and stale > c.patience:
                    reason = "patience"
                    details = (f"no improvement > {c.min_improvement} for "
                               f"{stale} epochs (best {best_score:.6f} at "
                               f"epoch {best_epoch})")
                    break
        if best_params is not None:
            self.graph.params = best_params
            if c.save_path:
                from gan_deeplearning4j_tpu.graph import serialization

                os.makedirs(os.path.dirname(c.save_path) or ".",
                            exist_ok=True)
                serialization.write_model(self.graph, c.save_path)
        return EarlyStoppingResult(
            reason=reason, details=details, best_epoch=best_epoch,
            best_score=best_score, total_epochs=epoch)
