"""Fleet execution layer — thousands of per-segment GANs in ONE dispatch.

The reference application is per-customer-segment feature engineering
(SURVEY §0): at production scale that is one small MLP-GAN *per
insurance segment*, i.e. a fleet of thousands of independent models.
Run one at a time, each 4x3-lattice program leaves the MXU almost idle
and the dominant cost is per-model dispatch overhead.  This module
stacks N tenant parameter trees along a leading tenant axis and vmaps
the existing fused three-graph step (train/fused_step.py) over it, so
the whole fleet advances in one donated XLA dispatch — dense batched
compute instead of N tiny dispatches.

Semantics (docs/FLEET.md):

  - **Stacking**: every ``ProtocolState`` leaf gains a leading tenant
    dim via ``jax.tree.map``; ``state.it`` becomes an ``(N,)`` vector of
    per-tenant device step counters.
  - **PRNG independence**: tenant ``i`` draws from
    ``fold_in(base_key, i)`` — the SAME folding a single-tenant control
    run uses, so a fleet tenant's d/g-loss timeline is bitwise-equal
    (f32) to an independently-run single-tenant control with the same
    folded seed: the vmap changes the schedule, not the math
    (tests/test_fleet.py::test_fleet_matches_single_tenant_controls).
  - **Per-tenant semantics preserved**: the vmapped program contains the
    unmodified fused step — carry-dedup, the RmsProp updater, the three
    cross-graph syncs — applied per tenant with no cross-tenant
    communication of any kind (the ``fleet_step`` program contract pins
    the collective budget at zero).

The multi-chip tenant-axis shard_map lives in ``parallel/fleet.py``;
the supervised training payload (``FleetTrainer``) composes the shared
supervision shell from ``train/shell.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.data import resilient
from gan_deeplearning4j_tpu.runtime import prng
from gan_deeplearning4j_tpu.utils import device_fence
from gan_deeplearning4j_tpu.telemetry import events as telemetry_events
from gan_deeplearning4j_tpu.train import fused_step as fused_lib
from gan_deeplearning4j_tpu.train.fused_step import ProtocolState

# ProtocolState fields in checkpoint-tree order (``it`` and the optional
# ``ema_gen`` are keyed explicitly; see state_to_tree)
_STATE_FIELDS = ("dis_params", "dis_opt", "gan_params", "gan_opt",
                 "clf_params", "clf_opt", "gen_params")


# ---------------------------------------------------------------------------
# per-tenant PRNG streams

def tenant_keys(base_key: jax.Array, num_tenants: int) -> jax.Array:
    """``(N,)`` key vector: tenant ``i`` gets ``fold_in(base_key, i)``.

    This folding IS the fleet/control equivalence: a single-tenant run
    seeded with ``fold_in(base, i)`` and fleet row ``i`` draw the same
    z/dropout streams, so their timelines match bitwise."""
    return jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
        jnp.arange(num_tenants))


# ---------------------------------------------------------------------------
# stacking / slicing

def replicate_state(state: ProtocolState, num_tenants: int) -> ProtocolState:
    """Broadcast ONE template init to an N-tenant fleet state.

    All tenants start from the same weights (the builders are
    deterministic in their seed); trajectories decorrelate through the
    per-tenant PRNG streams.  For per-tenant *inits* stack distinct
    states with :func:`stack_states` instead."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_tenants,) + x.shape),
        state)


def stack_states(states: Sequence[ProtocolState]) -> ProtocolState:
    """Stack N per-tenant states along a new leading tenant axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def fleet_size(state: ProtocolState) -> int:
    return int(state.it.shape[0])


def slice_tenant(state: ProtocolState, tenant: int) -> ProtocolState:
    """Tenant ``tenant``'s state as a plain single-model ProtocolState."""
    return jax.tree.map(lambda x: x[tenant], state)


def subset_state(state: ProtocolState,
                 tenants: Sequence[int]) -> ProtocolState:
    """A smaller fleet holding only ``tenants`` (order preserved)."""
    ids = jnp.asarray(list(tenants), jnp.int32)
    return jax.tree.map(lambda x: jnp.take(x, ids, axis=0), state)


# ---------------------------------------------------------------------------
# checkpoint tree form (graph/serialization flattens nested DICTS only)

# the flat '/'-key serialization cannot represent an EMPTY dict (no
# leaves, no keys) — but param trees legitimately hold them (a Dropout
# layer owns no params), and a restored state missing those layer keys
# is unsteppable.  The tree form carries an explicit zero-scalar marker
# per empty dict; state_from_tree strips it, so state values stay
# bit-identical through the round trip.
_EMPTY_MARKER = "__fleet_empty__"


def _mark_empty(tree):
    if isinstance(tree, dict):
        if not tree:
            return {_EMPTY_MARKER: jnp.zeros((), jnp.int32)}
        return {k: _mark_empty(v) for k, v in tree.items()}
    return tree


def _unmark_empty(tree):
    if isinstance(tree, dict):
        return {k: _unmark_empty(v) for k, v in tree.items()
                if k != _EMPTY_MARKER}
    return tree


def state_to_tree(state: ProtocolState) -> Dict:
    """ProtocolState -> nested dict, the checkpoint-extras pytree form."""
    tree = {f: _mark_empty(getattr(state, f)) for f in _STATE_FIELDS}
    tree["it"] = state.it
    if state.ema_gen is not None:
        tree["ema_gen"] = _mark_empty(state.ema_gen)
    return tree


def state_from_tree(tree: Dict) -> ProtocolState:
    ema = tree.get("ema_gen")
    return ProtocolState(
        *(_unmark_empty(tree[f]) for f in _STATE_FIELDS),
        jnp.asarray(tree["it"], jnp.int32),
        None if ema is None else _unmark_empty(ema))


# ---------------------------------------------------------------------------
# the fleet step

def make_fleet_step(
    dis, gen, gan, classifier,
    dis_to_gan, gan_to_gen, dis_to_classifier,
    z_size: int,
    num_features: int,
    per_tenant_data: bool = False,
    donate: bool = True,
    data_on_device: bool = False,
    steps_per_call: int = 1,
    ema_decay: float = 0.0,
    carry_dedup: bool = True,
    masked: bool = False,
    jit: bool = True,
):
    """Build the fleet step:
    ``(state, real, labels, z_keys, rng_keys, y_real, y_fake, ones) ->
    (state', (d_loss, g_loss, clf_loss))`` with every state leaf, both
    key vectors and (vmapped) every loss carrying a leading tenant dim.

    ``per_tenant_data``: ``real``/``labels`` are ``(N, ...)`` per-tenant
    tables (the TenantRouter's output) mapped over axis 0; off = one
    shared batch/table broadcast to every tenant (the bench's resident
    mode — segment routing is a data concern, not a program one).

    ``masked``: the lifecycle form — the signature gains an ``(N,)``
    bool ``mask`` after ``rng_keys``; a masked-off lane's state leaves
    come back bit-identical (``it`` included, so a frozen tenant's PRNG
    schedule does not advance) while active lanes step exactly as the
    unmasked program.  Mask flips are runtime array values, never shape
    or program changes — the mechanism behind ghost slots, quarantine
    freezes and zero-recompile onboarding (train/lifecycle.py).  Losses
    are still reported for every lane; callers mask them host-side.

    The inner program is the UNMODIFIED fused step built by
    ``make_protocol_step(mesh=None)`` — vmap supplies the tenant axis,
    so carry-dedup/scan/updater semantics hold per tenant by
    construction.  Donation: the single-step fleet program donates the
    stacked state (verified from the lowering by the ``fleet_step``
    gan4j-prove contract); the scan path inherits the repo-wide
    scan-donation exemption and announces the flip like fused_step does.

    ``jit=False`` returns the raw vmapped callable — the form
    ``parallel/fleet.py`` wraps in a tenant-axis shard_map."""
    single = fused_lib.make_protocol_step(
        dis, gen, gan, classifier,
        dis_to_gan, gan_to_gen, dis_to_classifier,
        z_size=z_size, num_features=num_features,
        mesh=None, donate=False, data_on_device=data_on_device,
        steps_per_call=steps_per_call, ema_decay=ema_decay,
        carry_dedup=carry_dedup)
    data_ax = 0 if per_tenant_data else None
    if masked:
        def lane(st, real, labels, zk, rk, m, y_real, y_fake, ones):
            new_st, losses = single(st, real, labels, zk, rk,
                                    y_real, y_fake, ones)
            kept = jax.tree.map(
                lambda new, old: jnp.where(m, new, old), new_st, st)
            return kept, losses

        vstep = jax.vmap(
            lane,
            in_axes=(0, data_ax, data_ax, 0, 0, 0, None, None, None),
            out_axes=(0, 0))
    else:
        vstep = jax.vmap(
            single,
            in_axes=(0, data_ax, data_ax, 0, 0, None, None, None),
            out_axes=(0, 0))
    if not jit:
        return vstep
    if steps_per_call > 1 and donate:
        # same exemption as the single-model scan program — owned by the
        # fleet_step/fused_multi contracts, never flipped silently
        telemetry_events.instant(
            "donation.disabled", reason="scan-donation",
            steps_per_call=steps_per_call)
        donate = False
    return jax.jit(vstep, donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# per-tenant data routing

@dataclasses.dataclass
class RouteInfo:
    """What :meth:`TenantRouter.route_tables` did beyond the tables:
    per-tenant fault-domain outcomes the lifecycle layer acts on
    (starved/tripped tenants are frozen for the window, never allowed
    to stall or truncate cohort-mates)."""

    starved: list    # live tenants with < rows_per_tenant clean rows
    tripped: list    # tenants whose quarantine budget blew this call
    throttled: Dict[int, int]  # tenant -> rows dropped by its quota
    unrouted: int    # rows whose segment had no live tenant THIS call
    # (the router's ``unrouted`` attribute keeps the lifetime total)


class TenantRouter:
    """Route a row stream to tenants with PER-TENANT quarantine budgets,
    stable segment identity, and optional token-bucket ingest quotas.

    Row ``r`` belongs to segment ``r % num_segments`` (the production
    analog keys on a segment column; the modulo is the deterministic
    stand-in the bench and tests share).  ``num_segments`` is the FIXED
    segment universe — it never changes when tenants onboard or
    offboard, so a surviving tenant's routed rows are identical before
    and after any lifecycle event; rows for segments with no live
    tenant are counted in ``unrouted`` and dropped.  (The legacy
    constructor form ``TenantRouter(path, N, budget)`` keeps the old
    behavior exactly: universe == live set == ``range(N)``.)

    Each tenant owns its own ``data/resilient.RecordQuarantine``
    (``quarantine_tenant{i}.jsonl``, budget ``budget`` EACH): one
    segment's poisoned feed burns only that segment's budget and
    raises only that tenant's ``DataQuarantineError`` — a fleet must
    not lose 4095 healthy tenants to one bad one.  All charges also
    feed the shared :class:`~gan_deeplearning4j_tpu.data.resilient.DataHealth`
    (the ``gan4j_data_*`` scrape series aggregate fleet-wide).  With
    ``raise_on_budget=False`` (the lifecycle layer's mode) a blown
    budget marks the tenant *tripped* in the returned
    :class:`RouteInfo` instead of raising — the caller quarantines
    that one tenant and the rest of the fleet keeps training.

    ``quota_rows``/``quota_refill_per_s`` arm a per-tenant
    :class:`~gan_deeplearning4j_tpu.serve.gateway.TokenBucket` over
    ingested ROWS: a hot tenant whose feed exceeds its allowance has
    the excess rows dropped (counted per tenant in
    ``RouteInfo.throttled``) instead of inflating its share of routing
    work — one tenant's traffic cannot starve cohort-mates.

    :meth:`route` validates rows (finite features/labels), quarantines
    offenders, and returns rectangular per-tenant tables truncated to
    the minimum surviving per-tenant row count (the PR-12 contract);
    :meth:`route_tables` is the lifecycle form — fixed
    ``rows_per_tenant`` tables where a short tenant is reported
    starved (and masked for the window) rather than truncating
    everyone else."""

    def __init__(self, res_path: str, num_tenants: Optional[int] = None,
                 budget: int = 100,
                 health: Optional[resilient.DataHealth] = None, *,
                 tenants: Optional[Sequence[int]] = None,
                 num_segments: Optional[int] = None,
                 quota_rows: Optional[float] = None,
                 quota_refill_per_s: Optional[float] = None,
                 raise_on_budget: bool = True):
        if tenants is None:
            if num_tenants is None or num_tenants < 1:
                raise ValueError(
                    f"num_tenants must be >= 1, got {num_tenants}")
            tenants = list(range(num_tenants))
        else:
            tenants = [int(t) for t in tenants]
            if len(set(tenants)) != len(tenants):
                raise ValueError(f"duplicate tenant ids: {tenants}")
        if num_segments is None:
            num_segments = (max(tenants) + 1) if tenants else 1
        self.num_segments = int(num_segments)
        for t in tenants:
            self._check_segment(t)
        self.res_path = res_path
        self.tenants = tenants  # live tenant ids, stacking order
        self.budget = budget
        self.health = health
        self.raise_on_budget = raise_on_budget
        self.quota_rows = quota_rows
        self.quota_refill_per_s = quota_refill_per_s
        self.unrouted = 0
        # lazily created — a 4096-tenant fleet with clean data should
        # not stat 4096 quarantine files up front
        self._quarantines: Dict[int, resilient.RecordQuarantine] = {}
        self._buckets: Dict[int, object] = {}

    @property
    def num_tenants(self) -> int:
        return len(self.tenants)

    def _check_segment(self, tenant: int) -> None:
        if not 0 <= tenant < self.num_segments:
            raise ValueError(
                f"tenant id {tenant} outside the segment universe "
                f"[0, {self.num_segments})")

    def add_tenant(self, tenant: int) -> None:
        """Onboard: ``tenant``'s segment starts routing to it.  Every
        other tenant's row stream is untouched (stable ids)."""
        tenant = int(tenant)
        self._check_segment(tenant)
        if tenant in self.tenants:
            raise ValueError(f"tenant {tenant} is already live")
        self.tenants.append(tenant)

    def remove_tenant(self, tenant: int) -> None:
        """Offboard: the segment's rows become unrouted from now on."""
        self.tenants.remove(int(tenant))

    def quarantine_for(self, tenant: int) -> resilient.RecordQuarantine:
        q = self._quarantines.get(tenant)
        if q is None:
            q = resilient.RecordQuarantine(
                os.path.join(self.res_path,
                             f"quarantine_tenant{tenant}.jsonl"),
                self.budget, health=self.health)
            self._quarantines[tenant] = q
        return q

    def quarantined_total(self) -> int:
        return sum(q.count for q in self._quarantines.values())

    def _bucket_for(self, tenant: int):
        b = self._buckets.get(tenant)
        if b is None:
            from gan_deeplearning4j_tpu.serve.gateway import TokenBucket

            b = TokenBucket(self.quota_rows,
                            self.quota_refill_per_s or self.quota_rows)
            self._buckets[tenant] = b
        return b

    def _gather(self, features, labels, source: str):
        """Validate + segment-route the row stream; returns per-tenant
        row lists plus the call's fault-domain bookkeeping."""
        feats = np.asarray(features, np.float32)
        labs = np.asarray(labels, np.float32)
        if labs.ndim == 1:
            labs = labs[:, None]
        if feats.shape[0] != labs.shape[0]:
            raise ValueError(
                f"features/labels row counts differ: {feats.shape[0]} "
                f"vs {labs.shape[0]}")
        per_feat: Dict[int, list] = {t: [] for t in self.tenants}
        per_lab: Dict[int, list] = {t: [] for t in self.tenants}
        tripped: set = set()
        throttled: Dict[int, int] = {}
        unrouted = 0
        live = set(self.tenants)
        bad = ~(np.isfinite(feats).all(axis=1)
                & np.isfinite(labs).all(axis=1))
        for r in range(feats.shape[0]):
            t = r % self.num_segments
            if t not in live:
                unrouted += 1
                continue
            if bad[r]:
                if t in tripped:
                    continue
                try:
                    # raises this tenant's DataQuarantineError past
                    # budget; lifecycle mode converts that to a trip
                    self.quarantine_for(t).charge(
                        source, row=r, reason="non-finite row",
                        raw=f"tenant={t}")
                except resilient.DataQuarantineError:
                    if self.raise_on_budget:
                        raise
                    tripped.add(t)
                continue
            if self.quota_rows is not None:
                ok, _ = self._bucket_for(t).take()
                if not ok:
                    throttled[t] = throttled.get(t, 0) + 1
                    continue
            per_feat[t].append(feats[r])
            per_lab[t].append(labs[r])
        self.unrouted += unrouted
        return (feats, labs, per_feat, per_lab, tripped, throttled,
                unrouted)

    def route(self, features, labels, source: str = "<memory>"):
        """``(rows, F), (rows, L)`` -> ``(N, m, F), (N, m, L)`` stacked
        per-tenant tables (f32), bad rows quarantined per tenant."""
        _, _, per_feat, per_lab, _, _, _ = self._gather(
            features, labels, source)
        m = min(len(v) for v in per_feat.values())
        if m == 0:
            raise ValueError(
                "tenant routing left at least one tenant with zero "
                f"rows ({np.asarray(features).shape[0]} rows over "
                f"{self.num_tenants} tenants)")
        out_f = np.stack([np.stack(per_feat[t][:m])
                          for t in self.tenants])
        out_l = np.stack([np.stack(per_lab[t][:m])
                          for t in self.tenants])
        return jnp.asarray(out_f), jnp.asarray(out_l)

    def route_tables(self, features, labels, rows_per_tenant: int,
                     source: str = "<memory>"):
        """The lifecycle form: HOST ``(N, rows_per_tenant, ...)`` f32
        tables in ``self.tenants`` order plus a :class:`RouteInfo`.

        A tenant short of ``rows_per_tenant`` clean rows is reported
        ``starved`` (its table rows are zeros — the caller masks the
        lane for the window) and a tenant whose quarantine budget blew
        is ``tripped``; neither truncates or stalls cohort-mates, which
        is what keeps survivors' loss timelines bit-equal to an
        undisturbed control under feed poison."""
        feats, labs, per_feat, per_lab, tripped, throttled, unrouted = \
            self._gather(features, labels, source)
        nt = len(self.tenants)
        out_f = np.zeros((nt, rows_per_tenant, feats.shape[1]),
                         np.float32)
        out_l = np.zeros((nt, rows_per_tenant, labs.shape[1]),
                         np.float32)
        starved = []
        for i, t in enumerate(self.tenants):
            if t in tripped:
                continue
            got = per_feat[t]
            if len(got) < rows_per_tenant:
                starved.append(t)
                continue
            out_f[i] = np.stack(got[:rows_per_tenant])
            out_l[i] = np.stack(per_lab[t][:rows_per_tenant])
        info = RouteInfo(starved=starved, tripped=sorted(tripped),
                         throttled=throttled, unrouted=unrouted)
        return out_f, out_l, info


# ---------------------------------------------------------------------------
# fleet checkpoints: save once, restore any tenant subset

class TenantMappingError(ValueError):
    """A ``restore(tenants=...)`` was asked to resolve tenant IDS
    against a checkpoint whose recorded tenant-id -> slot/cohort
    mapping disagrees (or lacks the id) — refused with both mappings
    named rather than silently returning wrong-tenant params."""


class FleetCheckpointer:
    """Stacked-fleet checkpoints over ``checkpoint/TrainCheckpointer``.

    The stacked state rides the checkpointer's EXTRAS pytree channel
    (nested-dict form, ``state_to_tree``) with an empty graph set — so
    manifest hashing, torn-write fallback, keep-rotation and the
    elastic mesh_spec/reshard accounting all come from the one
    checkpointer the repo already trusts.  On disk each leaf is the
    full ``(N, ...)`` array: **save once, restore any tenant subset**
    — slicing happens at restore (``tenants=``), not at save, so one
    fleet checkpoint serves single-tenant forensics, subset fleets and
    full-fleet resume alike, bit-equal to the stacked slices."""

    EXTRA_KEY = "fleet"
    MAP_KEY = "fleet_tenant_map"

    def __init__(self, directory: str, keep: int = 3,
                 sweep_debris: bool = True):
        from gan_deeplearning4j_tpu.checkpoint.checkpointer import (
            TrainCheckpointer,
        )

        self._inner = TrainCheckpointer(directory, keep=keep,
                                        sweep_debris=sweep_debris)
        self.directory = directory

    def save(self, step: int, state: ProtocolState, mesh=None,
             tenant_map: Optional[Dict] = None) -> str:
        """``tenant_map`` (lifecycle fleets): the slot semantics of the
        stacked arrays, persisted in the MANIFEST extras —
        ``{"slots": [tenant_id_or_None per slot], "cohorts":
        {tenant_id: cohort_key}}``.  With a map on record,
        ``restore(tenants=...)`` resolves tenant IDS through it (and
        refuses a disagreeing expectation); without one, ``tenants``
        keep their PR-12 raw-slot-index meaning."""
        from gan_deeplearning4j_tpu.parallel.fleet import fleet_mesh_spec

        extra = {self.EXTRA_KEY: state_to_tree(state),
                 "fleet_tenants": fleet_size(state)}
        if tenant_map is not None:
            extra[self.MAP_KEY] = json.dumps(tenant_map, sort_keys=True)
        return self._inner.save(
            step, {}, extra=extra,
            mesh_spec=fleet_mesh_spec(mesh).to_dict())

    def restore(self, step: Optional[int] = None, tenants=None,
                expect_map: Optional[Dict] = None, **kw):
        """Returns ``(step, state, extra)``.

        ``tenants``: ``None`` = the full fleet; an ``int`` = ONE
        tenant's state as a plain single-model ``ProtocolState``; a
        sequence = a subset-fleet in the given order.  When the
        checkpoint carries a tenant map (lifecycle saves) the values
        are tenant IDS resolved through the STORED mapping — an id the
        map does not hold raises :class:`TenantMappingError`; without
        a map they are raw slot indices (PR-12 checkpoints).

        ``expect_map``: the caller's belief about the tenant-id ->
        slot/cohort mapping; if the checkpoint's stored map disagrees
        the restore is refused with a :class:`TenantMappingError`
        naming both mappings — never wrong-tenant params.

        ``kw`` passes through to ``TrainCheckpointer.restore``
        (``max_step``, ``target_mesh`` — the elastic path: restoring a
        fleet written on 8 devices onto a 4-device tenant mesh
        reshards with the usual accounting, values bit-equal
        post-gather)."""
        step_out, extra = self._inner.restore({}, step=step, **kw)
        tree = extra.get(self.EXTRA_KEY)
        if tree is None:
            raise ValueError(
                f"checkpoint at step {step_out} in {self.directory} "
                "carries no fleet state (not a fleet checkpoint)")
        stored = extra.get(self.MAP_KEY)
        if isinstance(stored, str):
            stored = json.loads(stored)
            extra[self.MAP_KEY] = stored  # decoded for callers
        if expect_map is not None:
            want = json.loads(json.dumps(expect_map, sort_keys=True))
            if stored != want:
                raise TenantMappingError(
                    f"checkpoint at step {step_out} in {self.directory} "
                    f"records tenant map {stored!r} but the caller "
                    f"expects {want!r} — refusing to restore "
                    "wrong-tenant params")
        state = state_from_tree(tree)
        if tenants is None:
            return step_out, state, extra

        def _slot(t) -> int:
            t = int(t)
            if stored is None:
                return t  # legacy checkpoint: raw slot index
            slots = stored.get("slots", [])
            try:
                return slots.index(t)
            except ValueError:
                raise TenantMappingError(
                    f"tenant id {t} is not in the tenant map recorded "
                    f"by the checkpoint at step {step_out} "
                    f"(slots={slots!r})") from None

        if isinstance(tenants, (int, np.integer)):
            return step_out, slice_tenant(state, _slot(tenants)), extra
        return (step_out,
                subset_state(state, [_slot(t) for t in tenants]),
                extra)

    # thin delegates to the inner checkpointer's discovery surface —
    # the publication pipeline (serve/publisher.py) and the fleet
    # serving bank walk fleet directories with the same verbs as
    # single-model ones
    def steps(self) -> list:
        return self._inner.steps()

    def verify(self, step: int) -> bool:
        return self._inner.verify(step)

    def latest_verified_step(self) -> Optional[int]:
        return self._inner.latest_verified_step()


# ---------------------------------------------------------------------------
# the fleet payload behind the shared supervision shell

@dataclasses.dataclass
class FleetConfig:
    """Knobs for a supervised fleet run (insurance-protocol tenants)."""

    num_tenants: int = 64
    num_iterations: int = 100
    batch_size: int = 16
    seed: int = prng.NUMBER_OF_THE_BEAST
    res_path: str = "outputs/fleet"
    # True: TenantRouter tables, one segment per tenant; False: one
    # shared resident table every tenant slices identically
    per_tenant_data: bool = True
    steps_per_call: int = 1
    print_every: int = 100
    checkpoint_every: int = 0
    keep_checkpoints: int = 3
    quarantine_budget: int = 100  # PER TENANT (TenantRouter)
    n_devices: Optional[int] = None  # tenant-mesh size; None = one device
    metrics_port: Optional[int] = None
    events: bool = True
    resume: bool = False
    watchdog: bool = False
    sanitize: bool = False
    # e.g. "SIGTERM": arm the shell's PreemptionGuard; the loop then
    # drains at the next step boundary — emergency fleet checkpoint,
    # PREEMPTED.json marker, PreemptionError (exit 75 protocol)
    preempt_signals: Optional[str] = None


# fault-injection seam (testing/chaos.py, scenario/trainer_child.py):
# called as _chaos_step_hook(step) at every fleet step boundary; a
# raised DeviceLostError simulates losing part of the tenant mesh
_chaos_step_hook: Optional[Callable[[int], None]] = None


class FleetTrainer:
    """The fleet as a SECOND PAYLOAD behind the one supervision shell
    (train/shell.py) — not a second trainer.  GANTrainer and this class
    share the shell's install/teardown bracket verbatim; what differs
    is only the stepped payload: here, one donated vmapped dispatch
    advances every tenant (train/fleet.make_fleet_step; the tenant-axis
    shard_map when ``n_devices`` forms a mesh).

    Ops integration: ``gan4j_fleet_*`` scrape series + the ``/healthz``
    fleet block (telemetry/exporter.observe_fleet), per-tenant data
    routing with per-tenant quarantine budgets (TenantRouter),
    checkpoint cadence through FleetCheckpointer (save once, restore
    any subset), and the shared watchdog/sentinel/event machinery."""

    def __init__(self, config: FleetConfig):
        from gan_deeplearning4j_tpu.models import mlpgan_insurance as M
        from gan_deeplearning4j_tpu.telemetry.exporter import (
            MetricsRegistry,
        )

        self.c = config
        os.makedirs(config.res_path, exist_ok=True)
        cfg = M.InsuranceConfig(seed=config.seed)
        self.model_cfg = cfg
        dis = M.build_discriminator(cfg)
        self.graphs = (dis, M.build_generator(cfg), M.build_gan(cfg),
                       M.build_classifier(dis, cfg))
        self.maps = (M.DIS_TO_GAN, M.GAN_TO_GEN, M.DIS_TO_CLASSIFIER)
        self.registry = MetricsRegistry()
        self.health = resilient.DataHealth()
        self.registry.observe_data(self.health.report)
        self.registry.observe_fleet(self._fleet_report)
        self.router = TenantRouter(config.res_path, config.num_tenants,
                                   budget=config.quarantine_budget,
                                   health=self.health)
        self.checkpointer = (
            FleetCheckpointer(os.path.join(config.res_path, "checkpoints"),
                              keep=config.keep_checkpoints)
            if config.checkpoint_every else None)
        self.batch_counter = 0
        self.state: Optional[ProtocolState] = None
        self.last_losses = None
        self.metrics_port: Optional[int] = None
        self._steps_per_sec = 0.0
        self._dispatch_ms = 0.0

    def _fleet_report(self) -> Dict:
        return {"tenants": self.c.num_tenants,
                "steps_per_sec": self._steps_per_sec,
                "dispatch_ms": self._dispatch_ms,
                "ok": self.health.report().get("ok", True)}

    def train(self, features, labels,
              log: Callable[[str], None] = print) -> Dict:
        """Train the fleet on ``(rows, F)`` features / ``(rows,)`` or
        ``(rows, 1)`` labels, supervised by the shared shell."""
        from gan_deeplearning4j_tpu.train.shell import SupervisionShell

        c = self.c
        preempt_nums = ()
        if c.preempt_signals:
            from gan_deeplearning4j_tpu.train.preemption import (
                parse_signals,
            )

            preempt_nums = parse_signals(c.preempt_signals)
        shell = SupervisionShell(
            self.registry, c.res_path,
            events_enabled=c.events, events_append=c.resume,
            watchdog=c.watchdog, sanitize=c.sanitize,
            step_fn=lambda: self.batch_counter,
            metrics_port=c.metrics_port,
            preempt_signal_nums=preempt_nums, log=log)

        def _payload():
            self.metrics_port = shell.metrics_port
            return self._train_impl(features, labels, shell, log)

        return shell.run(_payload)

    # -- payload ------------------------------------------------------------

    def _log_window(self, log, losses) -> None:
        """Print-cadence progress line (called every ``print_every``
        steps, after the window's fence — the readback here is the
        cadence's, not a per-iteration sync)."""
        d = np.asarray(jax.tree.leaves(losses)[0])
        log(f"[fleet] step {self.batch_counter}: "
            f"{self.c.num_tenants} tenants, "
            f"{self._steps_per_sec:.1f} steps/s "
            f"(d_loss mean {float(d.mean()):.4f})")

    def _preempt_drain(self, state, mesh, shell) -> None:
        """The latched preemption signal observed at a step boundary:
        fence, commit an emergency fleet checkpoint, and exit through
        the one protocol every trainer shares (``preempt_exit``:
        PREEMPTED.json marker + ``PreemptionError`` — the scenario
        orchestrator maps it to exit code 75 and respawns with
        ``--resume``)."""
        from gan_deeplearning4j_tpu.train.preemption import preempt_exit

        device_fence(state)
        path = None
        if self.checkpointer is not None:
            path = self.checkpointer.save(self.batch_counter, state,
                                          mesh=mesh)
        preempt_exit(self.c.res_path, shell.guard,
                     local_step=self.batch_counter,
                     fleet_min_step=self.batch_counter,
                     checkpoint=path)

    def _train_impl(self, features, labels, shell, log) -> Dict:
        c = self.c
        mesh = None
        if c.n_devices is not None:
            from gan_deeplearning4j_tpu.parallel import fleet as pfleet

            mesh = pfleet.tenant_mesh(c.n_devices)
        if c.per_tenant_data:
            feats, labs = self.router.route(features, labels)
        else:
            feats = jnp.asarray(np.asarray(features, np.float32))
            labs = np.asarray(labels, np.float32)
            labs = jnp.asarray(labs[:, None] if labs.ndim == 1 else labs)
        rows = int(feats.shape[1] if c.per_tenant_data else feats.shape[0])
        if rows // c.batch_size == 0:
            raise ValueError(
                f"{rows} rows per tenant cannot fill one batch of "
                f"{c.batch_size}")
        k = max(1, int(c.steps_per_call))
        step_kw = dict(z_size=self.model_cfg.z_size,
                       num_features=self.model_cfg.num_features,
                       per_tenant_data=c.per_tenant_data,
                       data_on_device=True, steps_per_call=k)
        if mesh is None:
            step = make_fleet_step(*self.graphs, *self.maps, **step_kw)
        else:
            from gan_deeplearning4j_tpu.parallel import fleet as pfleet

            step = pfleet.make_sharded_fleet_step(
                *self.graphs, *self.maps, mesh=mesh, **step_kw)

        root = prng.root_key(c.seed)
        zks = tenant_keys(prng.stream(root, "fleet-z"), c.num_tenants)
        rks = tenant_keys(prng.stream(root, "fleet-rng"), c.num_tenants)
        B = c.batch_size
        ones = jnp.ones((B, 1), jnp.float32)
        # the reference's label softening, sampled once (gan_trainer)
        y_real = ones + 0.05 * jax.random.normal(
            prng.stream(root, "soften-real"), (B, 1), dtype=jnp.float32)
        y_fake = 0.05 * jax.random.normal(
            prng.stream(root, "soften-fake"), (B, 1), dtype=jnp.float32)

        start_step = 0
        state = None
        if self.checkpointer is not None and c.resume:
            from gan_deeplearning4j_tpu.checkpoint.checkpointer import (
                NoVerifiedCheckpointError,
            )

            try:
                # no mesh = the plain single-device load; a live tenant
                # mesh engages the elastic reshard-on-restore path
                restore_kw = {} if mesh is None else {"target_mesh": mesh}
                start_step, state, _ = self.checkpointer.restore(
                    **restore_kw)
                log(f"[fleet] resumed {fleet_size(state)} tenants at "
                    f"step {start_step}")
            except (NoVerifiedCheckpointError, FileNotFoundError):
                state = None
        if state is None:
            state = replicate_state(
                fused_lib.state_from_graphs(*self.graphs), c.num_tenants)
        if mesh is not None:
            from gan_deeplearning4j_tpu.parallel import fleet as pfleet

            state = pfleet.shard_fleet_state(state, mesh)
            sh = pfleet.fleet_sharding(mesh)
            zks, rks = jax.device_put(zks, sh), jax.device_put(rks, sh)
        self.batch_counter = start_step

        telemetry_events.instant(
            "fleet.start", tenants=c.num_tenants, steps_per_call=k,
            devices=(1 if mesh is None else int(mesh.devices.size)))
        losses = None
        window_t0 = time.perf_counter()
        window_steps = 0
        t_start = window_t0
        while self.batch_counter < c.num_iterations:
            state, losses = step(state, feats, labs, zks, rks,
                                 y_real, y_fake, ones)
            self.batch_counter += k
            window_steps += k
            if shell.watchdog is not None:
                shell.watchdog.beat(self.batch_counter)
            if shell.guard is not None and shell.guard.triggered:
                self._preempt_drain(state, mesh, shell)
            if _chaos_step_hook is not None:
                _chaos_step_hook(self.batch_counter)
            at_print = (c.print_every
                        and self.batch_counter % c.print_every < k)
            at_ckpt = (self.checkpointer is not None
                       and self.batch_counter % c.checkpoint_every < k)
            if at_print or at_ckpt:
                # print/checkpoint cadence, NOT per iteration: the fence
                # is the one deliberate readback of the window
                device_fence(losses)
                dt = time.perf_counter() - window_t0
                if dt > 0 and window_steps:
                    self._steps_per_sec = window_steps / dt
                    self._dispatch_ms = (dt / window_steps) * k * 1e3
                self.registry.inc("gan4j_steps_total", window_steps)
                self.registry.set("gan4j_step", self.batch_counter)
                if at_print:
                    self._log_window(log, losses)
                if at_ckpt:
                    self.checkpointer.save(self.batch_counter, state,
                                           mesh=mesh)
                window_t0 = time.perf_counter()
                window_steps = 0
        device_fence(state)
        wall = time.perf_counter() - t_start
        steps_done = self.batch_counter - start_step
        if wall > 0 and steps_done:
            self._steps_per_sec = steps_done / wall
            self._dispatch_ms = (wall / steps_done) * k * 1e3
        self.state = state
        self.last_losses = (None if losses is None
                            else jax.tree.map(np.asarray, losses))
        if self.checkpointer is not None:
            self.checkpointer.save(self.batch_counter, state, mesh=mesh)
        telemetry_events.instant(
            "fleet.done", tenants=c.num_tenants, steps=self.batch_counter)
        return {"tenants": c.num_tenants, "steps": self.batch_counter,
                "steps_per_sec": self._steps_per_sec,
                "dispatch_ms": self._dispatch_ms,
                "tenants_steps_per_sec": (c.num_tenants
                                          * self._steps_per_sec),
                "quarantined": self.router.quarantined_total()}
