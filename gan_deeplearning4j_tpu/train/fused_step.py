"""Fused protocol step — the whole GAN iteration as ONE XLA program.

The reference's iteration (SURVEY.md §3.2) is a host-driven dance: three
separate Spark fit jobs with an RDD serialization round trip each, plus
30+ ``setParam`` copies between them.  The unfused GANTrainer already
removes the serialization; this module removes the remaining per-fit
dispatch entirely: D-step, dis->gan sync, G-step, gan->gen sync,
dis->classifier sync, and classifier-step compile into a single jitted
(optionally shard_map-ed SPMD) program.  Inside XLA the "weight copies"
are pure aliasing — zero ops, zero HBM traffic — and the compiler can
overlap the three backward passes' HBM streams.  State buffers are
donated, so parameters update in place in HBM.

Under a mesh, every gradient/BN reduce is the same pmean-over-ICI as
parallel/data_parallel.py (sync-BN included); per-replica z draws fold in
``lax.axis_index``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from gan_deeplearning4j_tpu.optim import ema as ema_lib
from gan_deeplearning4j_tpu.runtime import prng
from gan_deeplearning4j_tpu.telemetry import events as telemetry_events


# Cap on lax.scan steps per dispatch (trainer auto mode and the
# benchmark's multistep measurement both use it, so the published number
# describes the program the trainer actually runs).  100 aligns with the
# reference's printEvery/saveEvery cadence (dl4jGANComputerVision.java:69)
# so the auto chunk IS the artifact interval; scan cost is
# trip-count-independent and the carried state does not grow with K.
MAX_STEPS_PER_CALL = 100


class ProtocolState(NamedTuple):
    """All four graphs' learnable state, one donated pytree.

    ``it`` is the step counter AS A DEVICE SCALAR: the fused step derives
    its per-step PRNG streams from it and increments it in-place, so the
    host never ships a scalar argument per step (a host->device scalar
    transfer costs milliseconds over a tunneled PJRT link — it would
    dominate the step)."""

    dis_params: Dict
    dis_opt: Dict
    gan_params: Dict
    gan_opt: Dict
    clf_params: Dict
    clf_opt: Dict
    gen_params: Dict
    it: jax.Array
    # exponential moving average of gen_params (None when disabled):
    # sampling/FID from the EMA weights averages over the adversarial
    # trajectory, damping the equilibrium's rounding sensitivity
    # (RESULTS.md FID variance note).  A capability over the reference.
    ema_gen: Optional[Dict] = None


def _apply_sync(dst_params: Dict, src_params: Dict, mapping) -> Dict:
    """The reference's setParam block as a pure pytree merge (free in XLA)."""
    out = dict(dst_params)
    for dst_layer, src_layer, names in mapping:
        out[dst_layer] = {
            **out[dst_layer],
            **{n: src_params[src_layer][n] for n in names},
        }
    return out


# Carry-dedup (the 51MB-copy fix, RESULTS.md "Overlap experiment series"):
# under ``lax.scan`` the carried ProtocolState holds every synced weight
# TWICE — gen_params duplicates the gan graph's generator side
# (``gan_to_gen``), the gan graph's frozen discriminator tail duplicates
# dis_params (``dis_to_gan``), and the classifier's frozen feature
# extractor duplicates dis_params again (``dis_to_classifier``).  Two scan-carry outputs can never alias one
# buffer, so XLA materializes a full HBM copy of each duplicate EVERY
# step (the two 51.4MB ``copy`` ops in hlo_cost_r5.json — the #1/#2 byte
# sinks of the b200 program).  The fix: carry each duplicated weight ONCE
# and rebuild the mirror by the same ``_apply_sync`` merge (free aliasing
# inside one iteration), restoring the full state after the scan.
#
# Only ``W``/``b`` are deduped.  BatchNorm running statistics (mean/var)
# of the frozen tail are NOT rematerializable — the G-step's forward pass
# updates them by momentum regardless of the lr-0 freeze — and
# gamma/beta are kilobytes; all BN params therefore stay in the carry.
# W/b of the tail (and of the classifier's synced feature extractor) ARE
# exact: the per-step sync overwrites them before any read, and the
# frozen RmsProp update is ``p - 0.0 * clip(...)`` = ``p`` bitwise for
# finite grads (a diverged NaN grad would differ — the divergence
# sentinel owns that regime).
_DEDUP_NAMES = frozenset({"W", "b"})


def _dedup_strip(params: Dict, mapping) -> Dict:
    """Drop the deduped (synced W/b) entries of every mapped dst layer —
    the scan-carry form.  Layer keys stay (possibly empty) so the pytree
    keeps one dict per layer."""
    out = dict(params)
    for dst_layer, _src_layer, names in mapping:
        drop = _DEDUP_NAMES.intersection(names)
        out[dst_layer] = {
            k: v for k, v in out[dst_layer].items() if k not in drop
        }
    return out


def _dedup_rebuild(params: Dict, src_params: Dict, mapping) -> Dict:
    """Inverse of ``_dedup_strip``: re-add the stripped entries from the
    sync source (pure aliasing in XLA — no copies)."""
    out = dict(params)
    for dst_layer, src_layer, names in mapping:
        add = _DEDUP_NAMES.intersection(names)
        out[dst_layer] = {
            **out[dst_layer],
            **{n: src_params[src_layer][n] for n in names if n in add},
        }
    return out


def make_protocol_step(
    dis, gen, gan, classifier,
    dis_to_gan, gan_to_gen, dis_to_classifier,
    z_size: int,
    num_features: int,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    donate: bool = True,
    data_on_device: bool = False,
    steps_per_call: int = 1,
    ema_decay: float = 0.0,
    data_codec: Optional[str] = None,
    codec_chunk_decode: bool = False,
    chunk_indexed: bool = False,
    telemetry: bool = False,
    carry_dedup: bool = True,
):
    """Build the fused step:
    (state, real, labels, z_key, rng_key, y_real, y_fake, ones) ->
    (state', (d_loss, g_loss, clf_loss)) — or, with ``telemetry``,
    (state', ((d_loss, g_loss, clf_loss), telemetry_block)).

    ``telemetry``: compute the in-graph numerics block per step — global
    grad-norm / param-norm / update-ratio for each trained graph
    (``d_``/``g_``/``clf_`` prefixes) plus one total NaN/Inf counter
    over grads and losses (telemetry/ingraph.py).  A dozen extra scalar
    outputs riding the SAME dispatch: zero additional dispatches, and
    nothing reads them back on the training thread (the async
    MetricsLogger worker materializes them with the losses).  Under
    ``lax.scan`` they stack to (K,) arrays like the chunked losses.

    ``steps_per_call`` > 1 wraps the iteration in ``lax.scan`` so ONE
    dispatch advances K steps and returns K-stacked losses — on a
    high-latency (tunneled) link the per-step dispatch cost otherwise
    bounds throughput at ~1/dispatch-latency regardless of device speed.
    Requires ``data_on_device`` (each inner step must slice its own batch;
    a streamed batch argument would be reused K times).

    The per-iteration host work is ONE dispatch: the step index lives in
    ``state.it`` (device scalar, incremented by the program itself), and
    the latent draws and all per-step key folding happen inside the XLA
    program, derived from it (z1 = U[-1,1] under fold_in(z_key, 2*it),
    z2 under fold_in(z_key, 2*it+1) — the same counter-based stream the
    unfused trainer path uses, so fused == unfused numerically).
    ``y_real``/``y_fake``/``ones`` are the loop-invariant (pre-softened)
    GLOBAL-batch target vectors and ``z_key``/``rng_key`` (dropout) the
    base keys — all loop-invariant, but passed as ARGUMENTS, not closed
    over: on a tunneled PJRT backend, closure-captured device constants
    cost milliseconds per execution, argument buffers microseconds.
    Python scalars must never be per-step arguments for the same reason.

    ``data_on_device``: ``real``/``labels`` are the ENTIRE device-resident
    training set; the program slices batch ``it % (rows // batch)`` itself
    (HBM is the right home for a dataset that fits — no per-step
    host->device traffic at all).  The floor-division drops the partial
    epoch tail, which is exactly the streaming loop's skip-and-wrap
    semantics (dl4jGANComputerVision.java:524-526).

    Under a mesh, every replica draws the full global z and slices its
    own shard — bitwise identical to the single-device draw, so
    single-device == multi-device parity holds exactly.

    ``data_codec``: ``"u8x100"`` expects ``real`` as uint8 fixed-point
    codes (data/codec.py) and dequantizes through a 256-entry f32 table
    baked into the program — bitwise the host-parsed values (the decode
    is a one-hot matmul: each row of the one-hot has a single 1.0, so
    every dot product is exactly one table entry — no accumulation
    rounding, exact BY CONSTRUCTION; measured 6.5x faster than the
    elementwise gather lowering on TPU).  1/4 the host->device bytes
    (the streaming path's bandwidth lever) and 1/4 the HBM footprint of
    a resident table.  ``codec_chunk_decode``: decode the WHOLE data
    argument once before the scan instead of per sliced batch — the
    streaming-chunk mode, where the f32 working copy is chunk-sized and
    the decode cost amortizes over steps_per_call; per-step decode (the
    default) keeps a u8-RESIDENT table at 1/4 HBM for its whole life.

    ``carry_dedup`` (scan path only): carry every cross-graph-synced W/b
    ONCE instead of twice, rebuilding the mirrors by aliasing — removes
    the per-step 51.4MB scan-carry copies XLA otherwise emits for the
    duplicated weights (see the module-level dedup note).  Bitwise
    identical to the undeduped program for ANY input state: the first
    step runs unrolled against the caller's literal gen/gan weights (a
    fresh graph's gen init is NOT the projection of its gan init), and
    every later step's mirror is exactly the sync the body would have
    applied anyway.  Off = the pre-dedup lowering, kept as the A/B
    baseline for the overlap experiment series.

    ``chunk_indexed``: the step takes an extra ``row_idx`` argument
    (after ``labels``) and ``real``/``labels`` are DISTINCT-row tables,
    not pre-assembled batches — step ``it`` gathers rows
    ``row_idx[(it % n_batches) * B : ...]``.  The adaptive streaming
    tier's program shape (data/prefetch.py dedup mode): when a chunk
    spans whole epochs of a deterministic iterator, each distinct row
    crosses the link once per chunk instead of once per occurrence —
    the epoch-in-chunk regime's bandwidth lever.
    """
    axis_name = axis if mesh is not None else None
    n_shards = mesh.shape[axis] if mesh is not None else 1
    if data_codec not in (None, "u8x100"):
        raise ValueError(f"unknown data_codec: {data_codec!r}")
    if codec_chunk_decode and data_codec is None:
        raise ValueError("codec_chunk_decode requires a data_codec")
    if codec_chunk_decode and steps_per_call <= 1:
        raise ValueError("codec_chunk_decode requires steps_per_call > 1 "
                         "(it amortizes the decode over a scan)")
    if chunk_indexed and (not data_on_device or steps_per_call <= 1):
        raise ValueError("chunk_indexed is the streaming-chunk gather "
                         "mode: it requires data_on_device=True and "
                         "steps_per_call > 1")
    if data_codec == "u8x100":
        from gan_deeplearning4j_tpu.data.codec import U8X100_TABLE

        dequant_table = jnp.asarray(U8X100_TABLE)  # compile-time constant

        def dequant(codes):
            oh = jax.nn.one_hot(codes.astype(jnp.int32), 256,
                                dtype=jnp.float32)
            return oh @ dequant_table
    step_codec = None if codec_chunk_decode else data_codec

    def reduce(loss, updates, grads):
        if axis_name is None:
            return loss, updates, grads
        return (lax.pmean(loss, axis_name), lax.pmean(updates, axis_name),
                lax.pmean(grads, axis_name))

    def step(state: ProtocolState, real, labels, z_key, rng_key,
             y_real, y_fake, ones, row_idx=None):
        global_batch = ones.shape[0]  # ones is replicated, so global
        step_idx = state.it
        if data_on_device:
            # slice this step's (local) batch out of the resident dataset
            # (chunk_indexed: gather it through the row-index schedule —
            # the tables hold each distinct row once)
            src = row_idx if chunk_indexed else real
            n_batches = src.shape[0] // global_batch
            local_b = global_batch // n_shards
            off = (step_idx % n_batches) * global_batch
            if axis_name is not None:
                off = off + lax.axis_index(axis_name) * local_b
            if chunk_indexed:
                ids = lax.dynamic_slice_in_dim(row_idx, off, local_b)
                real = jnp.take(real, ids, axis=0)
                labels = jnp.take(labels, ids, axis=0)
            else:
                real = lax.dynamic_slice_in_dim(real, off, local_b)
                labels = lax.dynamic_slice_in_dim(labels, off, local_b)
        if step_codec == "u8x100":
            # slice first (above), then dequantize just this batch
            real = dequant(real)
        B = real.shape[0]  # local shard under a mesh, global otherwise
        rng = jax.random.fold_in(rng_key, step_idx + 1)
        z1 = jax.random.uniform(
            jax.random.fold_in(z_key, 2 * step_idx),
            (global_batch, z_size), minval=-1.0, maxval=1.0)
        z2 = jax.random.uniform(
            jax.random.fold_in(z_key, 2 * step_idx + 1),
            (global_batch, z_size), minval=-1.0, maxval=1.0)
        yr, yf, on = y_real, y_fake, ones
        if axis_name is not None:
            idx = lax.axis_index(axis_name)
            rng = prng.fold_in_index(rng, idx)
            off = idx * B
            z1, z2, yr, yf, on = (
                lax.dynamic_slice_in_dim(a, off, B)
                for a in (z1, z2, yr, yf, on))
        # (1) D-step on [real; G(z)] — generator runs inference mode.
        # y_real/y_fake are sliced per shard and concatenated LOCALLY so
        # each shard's label halves align with its own [real; fake] halves
        # (a globally pre-concatenated label vector would misalign).
        fake_vals, _ = gen._forward(
            state.gen_params, {gen.input_names[0]: z1}, False, None)
        fake = fake_vals[gen.output_names[0]].reshape(B, num_features)
        x = jnp.concatenate([real, fake])
        y_dis = jnp.concatenate([yr, yf])

        def train(graph, params, opt, stream, inputs, targets):
            # telemetry is traced out entirely when disabled; when on it
            # rides as a 4th return (graph.py _train_step)
            out = graph._train_step(params, opt, stream, inputs, targets,
                                    reduce, axis_name, telemetry=telemetry)
            return out if telemetry else (*out, None)

        dis_params, dis_opt, d_loss, d_tel = train(
            dis, state.dis_params, state.dis_opt, prng.stream(rng, "d"),
            {dis.input_names[0]: x}, {dis.output_names[0]: y_dis})
        # (2) dis -> gan frozen tail: pure aliasing
        gan_params = _apply_sync(state.gan_params, dis_params, dis_to_gan)
        # (3) G-step through the stacked graph
        gan_params, gan_opt, g_loss, g_tel = train(
            gan, gan_params, state.gan_opt, prng.stream(rng, "g"),
            {gan.input_names[0]: z2}, {gan.output_names[0]: on})
        # (4) gan generator -> standalone gen
        gen_params = _apply_sync(state.gen_params, gan_params, gan_to_gen)
        # (5) classifier on the labeled real batch
        clf_params = _apply_sync(state.clf_params, dis_params, dis_to_classifier)
        clf_params, clf_opt, c_loss, c_tel = train(
            classifier, clf_params, state.clf_opt, prng.stream(rng, "clf"),
            {classifier.input_names[0]: real},
            {classifier.output_names[0]: labels})
        if ema_decay:
            # one elementwise pass over gen params (~3% of the step);
            # traced out entirely when disabled (shared rule: optim/ema.py)
            ema_gen = ema_lib.ema_update(state.ema_gen, gen_params,
                                         ema_decay)
        else:
            ema_gen = state.ema_gen
        new_state = ProtocolState(
            dis_params, dis_opt, gan_params, gan_opt,
            clf_params, clf_opt, gen_params, step_idx + 1, ema_gen)
        losses = (d_loss, g_loss, c_loss)
        if not telemetry:
            return new_state, losses
        # one flat fixed-shape block: per-graph norms/ratios plus a
        # single total NaN/Inf counter (per-graph counts add no signal —
        # the alarm only needs "which step went bad")
        tel = {f"{pfx}_{k}": v
               for pfx, blk in (("d", d_tel), ("g", g_tel), ("clf", c_tel))
               for k, v in blk.items() if k != "nonfinite"}
        tel["nonfinite"] = (d_tel["nonfinite"] + g_tel["nonfinite"]
                            + c_tel["nonfinite"])
        return new_state, (losses, tel)

    if steps_per_call > 1:
        if not data_on_device:
            raise ValueError(
                "steps_per_call > 1 requires data_on_device=True (inner "
                "steps slice their own batches from the resident dataset)")
        if donate:
            # the scan-path donation exemption is OWNED by the program
            # contract (analysis/contracts/fused_multi.json, exemption
            # "scan-donation" — analysis/program.py holds the rationale)
            # and verified from the actual lowering by gan4j-prove; the
            # flip is announced, never silent
            telemetry_events.instant(
                "donation.disabled", reason="scan-donation",
                steps_per_call=steps_per_call)
            donate = False
        inner = step

        def _strip(s: ProtocolState) -> ProtocolState:
            return s._replace(
                gan_params=_dedup_strip(s.gan_params, dis_to_gan),
                gen_params=_dedup_strip(s.gen_params, gan_to_gen),
                clf_params=_dedup_strip(s.clf_params, dis_to_classifier))

        def _scan_steps(state, run_one):
            """``run_one(state) -> (state', losses)`` applied
            ``steps_per_call`` times under ``lax.scan``; with
            ``carry_dedup`` the duplicated W/b leave the carry (module
            dedup note) and step 0 runs unrolled for exactness against
            arbitrary (fresh-init) input states."""
            if not carry_dedup:
                return lax.scan(lambda s, _: run_one(s), state, None,
                                length=steps_per_call)
            state, l0 = run_one(state)

            def body(s, _):
                # gen W/b = the gan->gen sync of the previous step,
                # rebuilt by aliasing; the gan tail's and classifier's
                # W/b need no rebuild here — the body's own dis->* syncs
                # re-add them before any read
                full = s._replace(gen_params=_dedup_rebuild(
                    s.gen_params, s.gan_params, gan_to_gen))
                full, losses = run_one(full)
                return _strip(full), losses

            carry, ls = lax.scan(body, _strip(state), None,
                                 length=steps_per_call - 1)
            gan_params = _dedup_rebuild(
                carry.gan_params, carry.dis_params, dis_to_gan)
            gen_params = _dedup_rebuild(
                carry.gen_params, gan_params, gan_to_gen)
            state = carry._replace(
                gan_params=gan_params, gen_params=gen_params,
                clf_params=_dedup_rebuild(
                    carry.clf_params, carry.dis_params, dis_to_classifier))
            losses = jax.tree.map(
                lambda a, b: jnp.concatenate([jnp.expand_dims(a, 0), b]),
                l0, ls)
            return state, losses

        if chunk_indexed:
            def step(state, real, labels, row_idx, z_key, rng_key,
                     y_real, y_fake, ones):
                if codec_chunk_decode:
                    # one exact decode of the distinct-row table —
                    # amortized over the scan AND over row repetitions
                    real = dequant(real)
                return _scan_steps(
                    state,
                    lambda s: inner(s, real, labels, z_key, rng_key,
                                    y_real, y_fake, ones, row_idx=row_idx))
        else:
            def step(state, real, labels, z_key, rng_key, y_real, y_fake,
                     ones):
                if codec_chunk_decode:
                    # one exact decode of the whole chunk, amortized over
                    # the K scanned steps (the per-step decode would
                    # re-pay the one-hot matmul every iteration)
                    real = dequant(real)
                # each loss stacked [steps_per_call]
                return _scan_steps(
                    state,
                    lambda s: inner(s, real, labels, z_key, rng_key,
                                    y_real, y_fake, ones))

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    # with a device-resident dataset every replica holds the full table and
    # slices its own shard; streaming batches arrive pre-sharded
    data_spec = P() if data_on_device else P(axis)
    n_data = 3 if chunk_indexed else 2  # tables (+ row schedule)
    sharded = shard_map(
        step,
        mesh=mesh,
        # state (incl. device step counter), keys and global target
        # vectors replicated; real, labels batch-sharded (or resident);
        # the chunk_indexed row schedule replicated (each replica
        # gathers its own shard's ids)
        in_specs=(P(),) + (data_spec,) * n_data + (P(),) * 5,
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def state_from_graphs(dis, gen, gan, classifier, start_step: int = 0,
                      ema: bool = False) -> ProtocolState:
    """``ema``: seed the generator's EMA slot from its current params
    (restores from ``gen.ema_params`` when a resumed graph carries one)."""
    # fresh buffers, NOT aliases of gen_params — the donation rationale
    # lives with the shared rule in optim/ema.py
    ema_gen = ema_lib.ema_init(gen) if ema else None
    return ProtocolState(
        dis.params, dis.opt_state, gan.params, gan.opt_state,
        classifier.params, classifier.opt_state, gen.params,
        jnp.asarray(start_step, jnp.int32), ema_gen)


def state_to_graphs(state: ProtocolState, dis, gen, gan, classifier) -> None:
    dis.params, dis.opt_state = state.dis_params, state.dis_opt
    gan.params, gan.opt_state = state.gan_params, state.gan_opt
    classifier.params, classifier.opt_state = state.clf_params, state.clf_opt
    gen.params = state.gen_params
    gen.ema_params = state.ema_gen  # None unless the step maintains an EMA
