"""Fused protocol step — the whole GAN iteration as ONE XLA program.

The reference's iteration (SURVEY.md §3.2) is a host-driven dance: three
separate Spark fit jobs with an RDD serialization round trip each, plus
30+ ``setParam`` copies between them.  The unfused GANTrainer already
removes the serialization; this module removes the remaining per-fit
dispatch entirely: D-step, dis->gan sync, G-step, gan->gen sync,
dis->classifier sync, and classifier-step compile into a single jitted
(optionally shard_map-ed SPMD) program.  Inside XLA the "weight copies"
are pure aliasing — zero ops, zero HBM traffic — and the compiler can
overlap the three backward passes' HBM streams.  State buffers are
donated, so parameters update in place in HBM.

Under a mesh, every gradient/BN reduce is the same pmean-over-ICI as
parallel/data_parallel.py (sync-BN included); per-replica z draws fold in
``lax.axis_index``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from gan_deeplearning4j_tpu.runtime import prng


class ProtocolState(NamedTuple):
    """All four graphs' learnable state, one donated pytree."""

    dis_params: Dict
    dis_opt: Dict
    gan_params: Dict
    gan_opt: Dict
    clf_params: Dict
    clf_opt: Dict
    gen_params: Dict


def _apply_sync(dst_params: Dict, src_params: Dict, mapping) -> Dict:
    """The reference's setParam block as a pure pytree merge (free in XLA)."""
    out = dict(dst_params)
    for dst_layer, src_layer, names in mapping:
        out[dst_layer] = {
            **out[dst_layer],
            **{n: src_params[src_layer][n] for n in names},
        }
    return out


def make_protocol_step(
    dis, gen, gan, classifier,
    dis_to_gan, gan_to_gen, dis_to_classifier,
    z_size: int,
    num_features: int,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    donate: bool = True,
):
    """Build the fused step:
    (state, rng, real, labels, z1, z2, y_real, y_fake, ones) ->
    (state', (d_loss, g_loss, clf_loss)).

    ``real``/``labels`` are the per-iteration batch; ``z1``/``z2`` the
    host-drawn latent batches for the D- and G-steps (drawn outside so the
    fused and unfused paths share PRNG semantics and single-device ==
    multi-device parity holds exactly); ``y_real``/``y_fake``/``ones`` the
    (pre-softened, loop-invariant) target vectors.  ``rng`` only feeds
    dropout streams.
    """
    axis_name = axis if mesh is not None else None

    def reduce(loss, updates, grads):
        if axis_name is None:
            return loss, updates, grads
        return (lax.pmean(loss, axis_name), lax.pmean(updates, axis_name),
                lax.pmean(grads, axis_name))

    def step(state: ProtocolState, rng, real, labels, z1, z2, y_real, y_fake,
             ones):
        B = real.shape[0]
        if axis_name is not None:
            rng = prng.fold_in_index(rng, lax.axis_index(axis_name))
        # (1) D-step on [real; G(z)] — generator runs inference mode.
        # y_real/y_fake are sharded separately and concatenated LOCALLY so
        # each shard's label halves align with its own [real; fake] halves
        # (a globally pre-concatenated label vector would misalign).
        fake_vals, _ = gen._forward(
            state.gen_params, {gen.input_names[0]: z1}, False, None)
        fake = fake_vals[gen.output_names[0]].reshape(B, num_features)
        x = jnp.concatenate([real, fake])
        y_dis = jnp.concatenate([y_real, y_fake])
        dis_params, dis_opt, d_loss = dis._train_step(
            state.dis_params, state.dis_opt, prng.stream(rng, "d"),
            {dis.input_names[0]: x}, {dis.output_names[0]: y_dis},
            reduce, axis_name)
        # (2) dis -> gan frozen tail: pure aliasing
        gan_params = _apply_sync(state.gan_params, dis_params, dis_to_gan)
        # (3) G-step through the stacked graph
        gan_params, gan_opt, g_loss = gan._train_step(
            gan_params, state.gan_opt, prng.stream(rng, "g"),
            {gan.input_names[0]: z2}, {gan.output_names[0]: ones},
            reduce, axis_name)
        # (4) gan generator -> standalone gen
        gen_params = _apply_sync(state.gen_params, gan_params, gan_to_gen)
        # (5) classifier on the labeled real batch
        clf_params = _apply_sync(state.clf_params, dis_params, dis_to_classifier)
        clf_params, clf_opt, c_loss = classifier._train_step(
            clf_params, state.clf_opt, prng.stream(rng, "clf"),
            {classifier.input_names[0]: real},
            {classifier.output_names[0]: labels},
            reduce, axis_name)
        new_state = ProtocolState(
            dis_params, dis_opt, gan_params, gan_opt,
            clf_params, clf_opt, gen_params)
        return new_state, (d_loss, g_loss, c_loss)

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    sharded = shard_map(
        step,
        mesh=mesh,
        # state + rng replicated; real, labels, z1, z2, y_real, y_fake,
        # ones batch-sharded
        in_specs=(P(), P()) + (P(axis),) * 7,
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def state_from_graphs(dis, gen, gan, classifier) -> ProtocolState:
    return ProtocolState(
        dis.params, dis.opt_state, gan.params, gan.opt_state,
        classifier.params, classifier.opt_state, gen.params)


def state_to_graphs(state: ProtocolState, dis, gen, gan, classifier) -> None:
    dis.params, dis.opt_state = state.dis_params, state.dis_opt
    gan.params, gan.opt_state = state.gan_params, state.gan_opt
    classifier.params, classifier.opt_state = state.clf_params, state.clf_opt
    gen.params = state.gen_params
