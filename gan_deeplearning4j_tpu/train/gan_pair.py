"""Two-pytree GAN training — the idiomatic alternative to the three-graph
protocol, for the roadmap model families.

The reference needs THREE graphs (dis, stacked gan, standalone gen) plus
30+ per-iteration setParam copies because DL4J cannot differentiate
through a frozen submodel (SURVEY.md §3.2, §7 "hard parts").  JAX can:
``jax.grad`` flows through D(G(z)) with D's params held constant, so one
generator graph + one discriminator/critic graph suffice and weight sync
disappears entirely.  This engine powers the BASELINE.json roadmap
configs (conditional GAN CIFAR-10, WGAN-GP, CelebA-64 DCGAN) while the
fidelity-exact three-graph GANTrainer covers the reference's own two
workloads.

Mechanics:
  - D-step: fake = G(z) (inference mode, stop-gradient by construction —
    G's params aren't differentiated), D trains on [real; fake] in one
    concatenated batch; for WGAN-GP the gradient penalty (grad-of-grad
    through the conv stack) is added — ``mode="wgan-gp"``
  - G-step: loss backprops through D∘G with D frozen (inference mode,
    running BN stats — standard practice)
  - optional label conditioning: extra inputs forwarded to both graphs
  - optional data parallelism: the same pmean-reduce as
    parallel/data_parallel.py, applied inside shard_map over a mesh
  - each step is ONE jitted XLA program; with a mesh, ONE SPMD program
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from gan_deeplearning4j_tpu.graph.graph import ComputationGraph
from gan_deeplearning4j_tpu.ops import losses as loss_lib
from gan_deeplearning4j_tpu.optim import ema as ema_lib
from gan_deeplearning4j_tpu.parallel import mesh as mesh_lib
from gan_deeplearning4j_tpu.runtime import prng


class GANPair:
    def __init__(
        self,
        gen: ComputationGraph,
        dis: ComputationGraph,
        mode: str = "gan",
        gp_weight: float = 10.0,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        ms_weight: float = 0.0,
    ):
        if mode not in ("gan", "wgan-gp"):
            raise ValueError(f"unknown mode {mode!r}")
        self.gen = gen
        self.dis = dis
        self.mode = mode
        self.gp_weight = gp_weight
        self.mesh = mesh
        self.axis = axis
        # mode-seeking regularizer weight (Mao et al. 2019, MSGAN): adds
        # ms_weight / (|G(z1,c)-G(z2,c)| / |z1-z2|) to the G loss — the
        # direct counter to WITHIN-class mode shrinkage (the r5
        # conditional-diversity finding, RESULTS §-2): a generator that
        # maps different z to near-identical images pays an explicit
        # penalty.  0 disables (traced out entirely).
        if ms_weight < 0:
            raise ValueError(
                f"ms_weight must be >= 0, got {ms_weight} (a negative "
                "weight REWARDS mapping every z to the same image — the "
                "collapse this regularizer exists to counter)")
        self.ms_weight = float(ms_weight)
        self._step_rng = prng.stream(prng.root_key(gen.seed), "gan-pair")
        self._count = 0
        self._jit_d = self._build(self._d_step)
        self._jit_g = self._build(self._g_step)

    # -- pure forwards -------------------------------------------------------

    def _global_uniform(self, key, local_shape, axis_name, dtype,
                        minval=-1.0, maxval=1.0):
        """U[minval,maxval] draw with mesh == single-device parity: every
        replica draws the GLOBAL batch from the replicated key and slices
        its own shard (replicated per-shard draws would correlate shards;
        per-shard keys would break single-device equivalence).  The one
        home for the idiom the GP-alpha and mode-seeking paths share."""
        n_shards = self.mesh.shape[self.axis] if axis_name is not None else 1
        b = local_shape[0]
        g = jax.random.uniform(key, (b * n_shards,) + local_shape[1:],
                               dtype=dtype, minval=minval, maxval=maxval)
        if axis_name is not None:
            g = lax.dynamic_slice_in_dim(g, lax.axis_index(axis_name) * b, b)
        return g

    def _gen_forward(self, params_g, z_inputs, train, rng, axis_name=None):
        values, updates = self.gen._forward(params_g, z_inputs, train, rng,
                                            axis_name)
        out = values[self.gen.output_names[0]]
        return out.reshape(out.shape[0], -1), updates  # flat, dis-input layout

    def _dis_forward(self, params_d, x, cond, train, rng, axis_name=None):
        inputs = {self.dis.input_names[0]: x}
        if cond:
            inputs.update(cond)
        values, updates = self.dis._forward(params_d, inputs, train, rng,
                                            axis_name)
        return values[self.dis.output_names[0]], updates

    def _dis_loss(self, out, labels):
        name = getattr(self.dis.nodes[self.dis.output_names[0]].layer, "loss", "xent")
        return loss_lib.get(name)(out, labels)

    # -- steps ---------------------------------------------------------------

    def _d_step(self, params_d, opt_d, params_g, rng, real, z_inputs,
                cond_real, cond_fake, y_real, y_fake, axis_name=None):
        fake, _ = self._gen_forward(params_g, z_inputs, False, None)
        x = jnp.concatenate([real, fake])
        cond = {
            k: jnp.concatenate([cond_real[k], cond_fake[k]]) for k in cond_real
        }
        y = jnp.concatenate([y_real, y_fake])

        def loss_fn(p):
            out, updates = self._dis_forward(p, x, cond, True, rng, axis_name)
            loss = self._dis_loss(out, y)
            if self.mode == "wgan-gp":
                def critic(xi):
                    # GP critic: inference mode (per-example vmap makes
                    # batch stats meaningless), labels from the real batch
                    n = xi.shape[0]
                    c = {k: v[:n] for k, v in cond_real.items()}
                    o, _ = self._dis_forward(p, xi, c, False, None)
                    return o
                gp_key = prng.stream(rng, "gp")
                alpha = None
                if axis_name is not None:
                    # replicated per-shard draws would correlate the GP
                    # estimator across shards — _global_uniform's
                    # draw-global-slice-own-shard rule
                    alpha = self._global_uniform(
                        gp_key, (real.shape[0], 1), axis_name,
                        real.dtype, minval=0.0, maxval=1.0)
                gp = loss_lib.gradient_penalty(
                    critic, real, fake, gp_key, alpha=alpha)
                loss = loss + self.gp_weight * gp
            return loss, updates

        (loss, updates), grads = jax.value_and_grad(loss_fn, has_aux=True)(params_d)
        if axis_name is not None:
            loss = lax.pmean(loss, axis_name)
            grads = lax.pmean(grads, axis_name)
            updates = lax.pmean(updates, axis_name)
        new_params, new_opt = self.dis.updater.apply(params_d, grads, opt_d)
        for lname, upd in updates.items():
            new_params[lname] = {**new_params[lname], **upd}
        return new_params, new_opt, loss

    def _g_step(self, params_g, opt_g, params_d, rng, z_inputs, cond_fake,
                y_gen, axis_name=None):
        def loss_fn(p):
            # sync-BN for the generator too: global-batch stats under a mesh
            fake, updates = self._gen_forward(p, z_inputs, True,
                                              prng.stream(rng, "gen"),
                                              axis_name)
            out, _ = self._dis_forward(params_d, fake, cond_fake, False, None)
            loss = self._dis_loss(out, y_gen)
            if self.ms_weight:
                z_name = self.gen.input_names[0]
                z1 = z_inputs[z_name]
                z2 = self._global_uniform(
                    prng.stream(rng, "ms"), z1.shape, axis_name, z1.dtype)
                fake2, _ = self._gen_forward(
                    p, {**z_inputs, z_name: z2}, True,
                    prng.stream(rng, "gen-ms"), axis_name)
                img_d = jnp.mean(jnp.abs(fake - fake2))
                z_d = jnp.mean(jnp.abs(z1 - z2))
                if axis_name is not None:
                    # GLOBAL-mean distances before the ratio: pmean of
                    # per-shard 1/ratio != 1/(global ratio) (Jensen) —
                    # measured 1.75e-3 mesh-vs-1dev loss divergence
                    # without this, 6e-8 with it
                    img_d = lax.pmean(img_d, axis_name)
                    z_d = lax.pmean(z_d, axis_name)
                loss = loss + self.ms_weight / (
                    img_d / (z_d + 1e-8) + 1e-5)
            return loss, updates

        (loss, updates), grads = jax.value_and_grad(loss_fn, has_aux=True)(params_g)
        if axis_name is not None:
            loss = lax.pmean(loss, axis_name)
            grads = lax.pmean(grads, axis_name)
            updates = lax.pmean(updates, axis_name)
        new_params, new_opt = self.gen.updater.apply(params_g, grads, opt_g)
        for lname, upd in updates.items():
            new_params[lname] = {**new_params[lname], **upd}
        return new_params, new_opt, loss

    def _build(self, fn):
        if self.mesh is None:
            return jax.jit(partial(fn, axis_name=None))
        axis = self.axis
        # batched args after (params, opt, other_params, rng):
        #   d: real, z_inputs, cond_real, cond_fake, y_real, y_fake
        #   g: z_inputs, cond_fake, y_gen
        n_extra = {self._d_step: 6, self._g_step: 3}[fn]
        # specs: (params, opt, other_params, rng) replicated; the batched
        # args (real/z/cond/labels) sharded over the data axis
        in_specs = (P(), P(), P(), P()) + (P(axis),) * n_extra
        return jax.jit(shard_map(
            partial(fn, axis_name=axis),
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    # -- public API ----------------------------------------------------------

    def _rng(self):
        self._count += 1
        return jax.random.fold_in(self._step_rng, self._count)

    def _place(self, tree):
        if self.mesh is None:
            return tree
        sh = mesh_lib.batch_sharding(self.mesh, self.axis)
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), tree)

    def make_multistep(self, table_x, table_cond=None, *,
                       batch_size: int, steps_per_call: int,
                       n_critic: int = 1, real_label: float = 1.0,
                       z_size: int, seed_key=None, ema_decay: float = 0.0,
                       start_step: int = 0):
        """Fused multi-iteration training: ONE jitted program advances
        ``steps_per_call`` full (n_critic D-steps + 1 G-step) iterations
        via ``lax.scan``, with the dataset device-resident and batches
        sampled on-device (uniform with replacement, counter-based keys)
        — the same dispatch-amortization as the protocol trainer's
        steps_per_call (train/fused_step.py), for the roadmap engine.

        Under a mesh the whole scan is ONE shard_map SPMD program: the
        table/labels/keys are replicated, every replica draws the full
        GLOBAL batch (bitwise the single-device stream) and slices its own
        shard, and grads/losses/BN stats pmean over the axis — the
        multi-replica fast path for the CelebA roadmap config.
        Donation is off under the scan — the exemption is owned and
        verified by the program contract
        (analysis/contracts/pair_multi.json, exemption "scan-donation";
        gan4j-prove asserts the lowering carries NO input/output
        aliasing, so this is a checked fact, not a comment).
        Returns (step_fn, state0):
          step_fn(state) -> (state', (d_losses[K], g_losses[K]))
          state = (params_g, opt_g, params_d, opt_d, it, ema_or_None)
        """
        n_shards = self.mesh.shape[self.axis] if self.mesh is not None else 1
        if batch_size % n_shards != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide over {n_shards} "
                f"devices on the '{self.axis}' axis")
        local_b = batch_size // n_shards
        n_rows = table_x.shape[0]
        key0 = (seed_key if seed_key is not None
                else prng.stream(prng.root_key(self.gen.seed), "pair-multi"))
        # constant-fill label vectors: build at the per-shard size (==
        # batch_size when unmeshed) so the scan body never has to slice
        y_real_v = jnp.full((local_b, 1), real_label, jnp.float32)
        y_fake_v = (-jnp.ones((local_b, 1), jnp.float32)
                    if self.mode == "wgan-gp"
                    else jnp.zeros((local_b, 1), jnp.float32))
        y_gen_v = jnp.ones((local_b, 1), jnp.float32)
        label_name = self.gen.input_names[1] if len(
            self.gen.input_names) > 1 else None

        axis_name = self.axis if self.mesh is not None else None

        def _multi(state, table_x, table_cond, y_real_v, y_fake_v, y_gen_v,
                   key0):
            # the dataset/label vectors/keys arrive as ARGUMENTS, not
            # closed-over constants — the fused_step.py rule: on a
            # tunneled PJRT backend closure-captured device constants
            # cost per-execution overhead and bloat the program
            def draw(key, which):
                # GLOBAL draws on every replica (bitwise the single-device
                # stream), then each shard takes its own slice
                k = jax.random.fold_in(key, which)
                idx = jax.random.randint(
                    jax.random.fold_in(k, 0), (batch_size,), 0, n_rows)
                z = jax.random.uniform(
                    jax.random.fold_in(k, 1), (batch_size, z_size),
                    minval=-1.0, maxval=1.0)
                if axis_name is not None:
                    off = lax.axis_index(axis_name) * local_b
                    idx = lax.dynamic_slice_in_dim(idx, off, local_b)
                    z = lax.dynamic_slice_in_dim(z, off, local_b)
                return idx, z

            def cond_of(idx):
                if table_cond is None:
                    return {}
                return {label_name: table_cond[idx]}

            def one_iteration(carry, _):
                pg, og, pd, od, it, ema = carry
                key = jax.random.fold_in(key0, it)
                d_loss = jnp.zeros(())
                for j in range(n_critic):
                    idx, z = draw(key, j)
                    z_in = {self.gen.input_names[0]: z}
                    c = cond_of(idx)
                    z_in.update(c)
                    pd, od, d_loss = self._d_step(
                        pd, od, pg, prng.stream(key, f"d{j}"),
                        table_x[idx], z_in, c, c, y_real_v, y_fake_v,
                        axis_name=axis_name)
                idx, z = draw(key, n_critic)
                z_in = {self.gen.input_names[0]: z}
                c = cond_of(idx)
                z_in.update(c)
                pg, og, g_loss = self._g_step(
                    pg, og, pd, prng.stream(key, "g"), z_in, c, y_gen_v,
                    axis_name=axis_name)
                if ema_decay:
                    # trajectory-averaged generator (optim/ema.py — the
                    # same rule as the protocol trainer's fused step)
                    ema = ema_lib.ema_update(ema, pg, ema_decay)
                return (pg, og, pd, od, it + 1, ema), (d_loss, g_loss)

            return lax.scan(one_iteration, state, None,
                            length=steps_per_call)

        if self.mesh is None:
            jit_multi = jax.jit(_multi)
        else:
            # everything replicated: state, the resident table, label
            # vectors and keys; each shard slices its own batch rows.
            # Losses come out pmean'd (replicated).
            jit_multi = jax.jit(shard_map(
                _multi,
                mesh=self.mesh,
                in_specs=(P(),) * 7,
                out_specs=(P(), P()),
                check_vma=False,
            ))
        invariants = (table_x, table_cond, y_real_v, y_fake_v, y_gen_v,
                      key0)
        if self.mesh is not None:
            # commit the invariants (dataset table included) to an explicit
            # replicated placement ONCE — otherwise every chunk dispatch
            # re-broadcasts the whole table host->devices, the exact
            # per-call transfer the resident path exists to avoid (same
            # rule as gan_trainer.train's device_put of the dataset)
            rep = jax.sharding.NamedSharding(self.mesh, P())
            invariants = tuple(
                None if x is None else jax.device_put(x, rep)
                for x in invariants)

        def step_fn(state):
            return jit_multi(state, *invariants)

        # introspection hooks: the benchmark's cost-analysis path
        # (bench.py celeba block) lowers the jitted program against the
        # exact invariants this closure would pass
        step_fn.jitted = jit_multi
        step_fn.invariants = invariants

        ema0 = ema_lib.ema_init(self.gen) if ema_decay else None
        # ``start_step`` seeds the carry's iteration counter, which drives
        # the counter-based z/batch draws (fold_in(key0, it)) — a resumed
        # run continues the EXACT stream a straight-through run would use
        state0 = (self.gen.params, self.gen.opt_state,
                  self.dis.params, self.dis.opt_state,
                  jnp.asarray(start_step, jnp.int32), ema0)
        return step_fn, state0

    def adopt_state(self, state) -> None:
        """Write a multistep scan state back into the graph objects (for
        artifact dumps / serialization)."""
        (self.gen.params, self.gen.opt_state,
         self.dis.params, self.dis.opt_state, _, ema) = state
        if ema is not None:
            self.gen.ema_params = ema

    def d_step(self, real, z_inputs: Dict, cond_real: Optional[Dict] = None,
               cond_fake: Optional[Dict] = None,
               y_real=None, y_fake=None) -> jax.Array:
        B = real.shape[0]
        if y_real is None:
            y_real = jnp.ones((B, 1), dtype=jnp.float32)
            y_fake = (-jnp.ones((B, 1), dtype=jnp.float32)
                      if self.mode == "wgan-gp"
                      else jnp.zeros((B, 1), dtype=jnp.float32))
        args = self._place((real, z_inputs, cond_real or {}, cond_fake or {},
                            y_real, y_fake))
        self.dis.params, self.dis.opt_state, loss = self._jit_d(
            self.dis.params, self.dis.opt_state, self.gen.params, self._rng(),
            *args)
        self.dis.score = loss
        return loss

    def g_step(self, z_inputs: Dict, cond_fake: Optional[Dict] = None,
               y_gen=None) -> jax.Array:
        B = next(iter(z_inputs.values())).shape[0]
        if y_gen is None:
            y_gen = jnp.ones((B, 1), dtype=jnp.float32)
        args = self._place((z_inputs, cond_fake or {}, y_gen))
        self.gen.params, self.gen.opt_state, loss = self._jit_g(
            self.gen.params, self.gen.opt_state, self.dis.params, self._rng(),
            *args)
        self.gen.score = loss
        return loss
