"""The three-graph GAN training protocol — the reference's mains as an engine.

Reproduces the loop of SURVEY.md §3.2 (dl4jGANComputerVision.java:387-527 /
dl4jGANInsurance.java:329-469) for any workload that supplies the four
graphs and their weight-sync maps:

  per iteration:
    1. D-step: fit dis on [real batch (labels 1+eps), generated batch
       (labels 0+eps)] — label-softening noise sampled ONCE before the
       loop and reused (reference quirk, :384-385)
    2. copy all dis weights + BN stats into the gan graph's frozen tail
    3. G-step: fit the stacked gan on z ~ U[-1,1]^z labeled "real"
    4. copy the gan graph's generator weights back into the standalone gen
    5. copy dis feature weights into the classifier, fit it on the real
       labeled batch
    6. every print_every: dump the latent-grid synthesis CSV (+ workload
       extras); every save_every: dump test-set prediction CSV
    7. wrap the data iterator on exhaustion (multi-epoch)

Differences from the reference, on purpose (documented, SURVEY.md §7):
  - every network optionally trains data-parallel over a Mesh
    (gradient-sync all-reduce or DL4J param-averaging fidelity mode)
    instead of Spark jobs with per-iteration RDD serialization
  - the D-step's two minibatches are fed as ONE concatenated batch; under
    ``dp_mode="param_averaging"`` with 2 replicas this is bitwise the
    reference's [real-partition, fake-partition] Spark job layout
  - periodic training-state checkpoints with resume (reference gap)
  - structured per-step metrics (D/G/classifier loss, examples/sec)
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.checkpoint import (
    AsyncCheckpointer,
    NoVerifiedCheckpointError,
    TrainCheckpointer,
)
from gan_deeplearning4j_tpu.data import (
    CSVRecordReader,
    RecordReaderDataSetIterator,
    write_csv_matrix,
)
from gan_deeplearning4j_tpu.data.resilient import (
    DataHealth,
    RecordQuarantine,
    RetryingReader,
    RetryingSource,
    ValidatingSource,
)
from gan_deeplearning4j_tpu.graph import serialization
from gan_deeplearning4j_tpu.parallel import DataParallelGraph, data_mesh
from gan_deeplearning4j_tpu.parallel import mesh as mesh_lib
from gan_deeplearning4j_tpu.runtime import prng
from gan_deeplearning4j_tpu.telemetry import MetricsRegistry, events
from gan_deeplearning4j_tpu.utils import (
    MetricsLogger,
    device_fence,
    start_host_copy,
)
from gan_deeplearning4j_tpu.utils.async_dump import AsyncArtifactWriter

# fault-injection seam (testing/chaos.py ShrinkWorld / lost_device):
# called with the step counter at every step/chunk boundary, BEFORE the
# boundary's own bookkeeping — a raised exception simulates losing part
# of the device fleet mid-run (the process dies retryably; the next
# incarnation re-forms the mesh over the survivors).  Mirrors
# checkpoint/checkpointer.py's ``_chaos_hook`` discipline.
_chaos_step_hook: Optional[Callable[[int], None]] = None


def _chaos_step(step: int) -> None:
    if _chaos_step_hook is not None:
        _chaos_step_hook(step)


@dataclasses.dataclass
class GANTrainerConfig:
    """The reference's constants block as a config
    (dl4jGANComputerVision.java:59-85; dl4jGANInsurance.java:58-84)."""

    dataset_name: str
    num_features: int
    label_index: int
    num_classes: int            # classifier label width (10 CV, 1 insurance)
    batch_size: int             # batchSizePerWorker
    batch_size_pred: int        # batchSizePred
    num_iterations: int
    num_gen_samples: int        # latent grid edge -> n^2 samples
    z_size: int = 2
    print_every: int = 100
    save_every: int = 100
    seed: int = prng.NUMBER_OF_THE_BEAST
    res_path: str = "outputs"   # a flag, not a hardcoded absolute path
    # -- distribution (replaces useGpu/Spark local[4]) --
    n_devices: Optional[int] = None   # None = all attached; 1 = no mesh
    # Elastic mesh formation (parallel/elastic.py, ROADMAP item 4):
    # when the requested n_devices exceeds what this incarnation
    # attaches (a shrunken fleet after preemption / device loss), re-
    # form the mesh over the largest batch divisor that fits the
    # SURVIVORS — loudly — instead of refusing to start.  The global
    # batch is invariant (it is part of the protocol's math); only the
    # per-device shard grows.  Checkpoints restore onto the re-formed
    # mesh via reshard-on-restore.  False = the old demand-the-
    # original-world behavior (data_mesh raises).
    elastic: bool = True
    dp_mode: str = "gradient_sync"
    averaging_frequency: int = 1
    fused: bool = True                # one-XLA-program protocol iteration
    # Keep the whole training set in HBM and let the fused step slice its
    # own batches from the device counter — zero per-step host->device
    # traffic.  None = auto: on when fused and the table fits comfortably.
    data_on_device: Optional[bool] = None
    data_on_device_max_bytes: int = 2 << 30
    # Steps per XLA dispatch on the resident path (lax.scan inside the
    # fused program).  Per-step dispatch latency otherwise bounds
    # throughput — on a tunneled PJRT link at ~1/2ms regardless of how
    # fast the chip is.  None = auto (largest divisor <= 100 of the
    # artifact cadences, so chunks never cross a dump/checkpoint
    # boundary); 1 = one dispatch per step.
    steps_per_call: Optional[int] = None
    # Streaming (non-resident) data path: bound the PER-CHUNK device
    # footprint — transfer buffer plus, under the u8 codec, the
    # chunk-decoded f32 working copy that lives through the scan — and
    # advance each chunk with ONE multi-step dispatch (data/prefetch.py
    # ChunkPrefetchIterator).  One chunk transfer pays one tunnel round
    # trip instead of K; chunk k+1 transfers while chunk k trains
    # (double-buffered).  0 disables chunking (per-batch transfer +
    # per-step dispatch, the r3 behavior).
    stream_chunk_bytes: int = 256 << 20
    # Adaptive epoch-in-chunk dedup tier: None = auto (engage when one
    # chunk covers >= a full pass of the DETERMINISTIC iterator and the
    # distinct-row tables fit stream_chunk_bytes); False = never (the
    # escape hatch for nondeterministic/augmenting iterators, whose
    # changing pass content the dedup worker rejects by design).
    stream_dedup: Optional[bool] = None
    # Exact uint8 transport/residency codec (data/codec.py): when the
    # training features are bitwise the 2-decimal fixed-point contract,
    # the RESIDENT table is stored in HBM as u8 codes (4x residency
    # budget, 4x faster initial upload) and STREAMED chunks cross the
    # link as u8 — the fused program dequantizes after slicing, bitwise
    # the f32 values.  False = always f32 (identical numerics; the
    # codec only changes where bytes live).
    use_data_codec: bool = True
    # -- new capabilities over the reference --
    checkpoint_every: int = 0         # 0 = end-of-run models only
    checkpoint_keep: int = 3
    resume: bool = False
    # Crash-safe async checkpointing (checkpoint/async_checkpointer.py):
    # serialize/fsync on a background worker, the training thread pays
    # only the host snapshot — the goodput ``checkpoint`` phase then
    # measures the blocking portion alone.  On-disk bytes (manifest
    # hashes included) are identical to a synchronous save.
    async_checkpoint: bool = False
    # Comma-separated signal names ("SIGTERM" / "SIGTERM,SIGUSR1") that
    # arm the preemption path (train/preemption.py): let the in-flight
    # call finish, take an emergency checkpoint, write a resumable
    # PREEMPTED.json marker, raise PreemptionError (mains exit 75).
    # None = signals keep their inherited behavior.
    preempt_signals: Optional[str] = None
    metrics: bool = True
    # Generator EMA decay (0 = off).  >0 maintains an exponential moving
    # average of the generator weights inside the fused step; sampling/FID
    # from it damps the adversarial equilibrium's rounding sensitivity
    # (RESULTS.md FID variance note).  Fused path only.
    ema_decay: float = 0.0
    # Artifact dumps: device compute is dispatched on the training thread
    # (exact step-k snapshot), readback + CSV write run on a background
    # worker so the device never idles on the ~70ms tunnel round trip.
    # False = the reference's synchronous behavior.
    async_dumps: bool = True
    # In-graph numerics telemetry (telemetry/ingraph.py): per-step
    # grad/param norms, update ratios and NaN/Inf counters computed
    # INSIDE the fused program and logged as extra metrics columns —
    # zero additional dispatches, no host syncs on the training thread.
    # Fused path only (the unfused per-fit path has no single program to
    # ride).
    telemetry: bool = False
    # What the first non-finite step does (requires telemetry):
    #   None       — nothing (the counters still land in the metrics)
    #   "warn"     — log loudly, keep training
    #   "snapshot" — save a forensic checkpoint of the current state to
    #                res_path/nan_snapshot, keep training
    #   "abort"    — raise NanAlarmError; train_with_recovery classifies
    #                it FATAL (deterministic replay from the last
    #                checkpoint would march straight into the same NaN —
    #                restarting only burns the budget)
    #   "rollback" — heal instead of dying (train/rollback.py): restore
    #                the last verified checkpoint from BEFORE the bad
    #                step in-process, cut the LR by rollback_lr_factor
    #                and advance the noise stream so the replay is NOT
    #                deterministic; escalates to fatal after
    #                max_rollbacks (progress-aware).  Needs a shared
    #                RollbackManager (run_with_recovery wires one).
    #                The divergence sentinel shares this action.
    nan_alarm: Optional[str] = None
    # Windowed divergence sentinel (train/divergence.py): trip on loss
    # explosion / grad-norm blowup BEFORE NaNs appear, from the same
    # materialized telemetry records the NaN alarm watches.  The action
    # on a trip is nan_alarm's (warn when None).  Requires telemetry.
    divergence: bool = False
    divergence_window: int = 64       # rolling median window (records)
    divergence_factor: float = 20.0   # |value| > factor * median = outlier
    divergence_patience: int = 3      # consecutive outliers to trip
    # Rollback-with-perturbation knobs (used when nan_alarm="rollback")
    max_rollbacks: int = 3            # progress-aware budget, then fatal
    rollback_lr_factor: float = 0.5   # LR multiplier per rollback
    # Hang watchdog (train/watchdog.py): the trainer heartbeats at every
    # step/chunk boundary and around every blocking region (the goodput
    # phases); if no beat lands within the deadline the watchdog dumps a
    # flight record, attempts a best-effort emergency checkpoint and
    # raises WatchdogTimeout on the training thread — a hang becomes a
    # retryable failure for train_with_recovery instead of a run wedged
    # forever.
    watchdog: bool = False
    # None = auto-scale: watchdog_scale x the measured steady-state
    # inter-beat interval (EWMA), floored at watchdog_min_deadline_s;
    # watchdog_warmup_s applies until enough intervals are measured
    # (the XLA-compile allowance).  An explicit value is a fixed
    # deadline in seconds.
    watchdog_deadline_s: Optional[float] = None
    watchdog_warmup_s: float = 300.0
    watchdog_scale: float = 20.0
    watchdog_min_deadline_s: float = 5.0
    # -- resilient data plane (data/resilient.py) --
    # Bounded retries on TRANSIENT data-source I/O errors (OSError /
    # truncated reads), exponential backoff + jitter, at both the CSV
    # read and the streaming next() — exhaustion raises DataSourceError,
    # which train_with_recovery restarts instead of dying.  0 = the
    # reference's die-on-first-error behavior.
    data_retries: int = 3
    data_retry_backoff_s: float = 0.1
    # Corrupt-record quarantine budget: > 0 arms row-tolerant ingest —
    # malformed records (bad width/parse/non-finite, out-of-range
    # labels) are skipped, logged to res_path/quarantine.jsonl with
    # file:line provenance, and charged here; EXCEEDING the budget
    # raises DataQuarantineError, FATAL in the recovery wrapper (a
    # restart re-reads the same poison — the rollback-budget
    # semantics).  0 = strict: the first malformed record raises with
    # file:line provenance (CSVRowError).
    max_quarantine: int = 0
    # Structured event tracing (telemetry/events.py): spans/instants for
    # checkpoint stages, preemption, recovery, prefetch stalls etc. to
    # res_path/events.jsonl plus the always-on flight-recorder ring.
    # False = fully disabled (the bench --no-events A/B baseline).
    events: bool = True
    # Serve /metrics (Prometheus text) + /healthz on this port for the
    # duration of train() (telemetry/exporter.py).  None = off; 0 = an
    # ephemeral port (resolved port on ``trainer.metrics_port``).
    metrics_port: Optional[int] = None
    # Runtime trace sanitizers (analysis/sanitizers.py): arm a
    # RecompileSentinel over the run (any XLA compile after the first
    # steady-state fence = gan4j_recompiles_total + a compile.recompile
    # event + a loud warning) and wrap the fused hot-loop dispatches in
    # a transfer guard (an implicit host<->device transfer raises
    # TransferGuardError).  Observational about recompiles, strict
    # about transfers; the hook costs nothing at steady state (it fires
    # per COMPILE, not per step).  bench --dryrun and the pytest
    # fixtures run the STRICT version of both.
    sanitize: bool = False
    # -- DMA/compute overlap restructures (RESULTS.md "Overlap
    # experiment series"; each default-on flag keeps the previous
    # lowering reachable as its A/B baseline) --
    # Drop the mirrored W/b (gen mirror of the gan's gen side, the
    # gan's frozen dis tail, the classifier's frozen feature extractor)
    # from the multistep scan carry: two carry outputs can't alias one
    # buffer, so every mirror otherwise costs a per-step HBM copy of
    # the 1024x6272 dense weight (the 51.4MB sinks of hlo_cost_r5).
    # Bitwise-exact (step 0 runs unrolled; see fused_step._DEDUP_NAMES).
    carry_dedup: bool = True
    # Upsample backward as one reshape+strided-sum instead of the
    # autodiff broadcast+reduce chain (the 60.2MB sink), and maxpool
    # backward as a recomputed-argmax scatter instead of
    # select-and-scatter (the 41.9MB sink).  Trace-time process-global
    # toggles (ops/upsample.py, ops/pool.py) — set before tracing.
    upsample_sum_bwd: bool = True
    pool_argmax_bwd: bool = True
    # Extra XLA scheduling flags (space-separated, XLA_FLAGS syntax),
    # e.g. "--xla_tpu_enable_latency_hiding_scheduler=true".  XLA parses
    # the env var once at backend init, so these only take effect when
    # the trainer is constructed BEFORE anything initializes the jax
    # backend — bench.py's flag lanes re-exec a fresh process per flag
    # set for exactly this reason (runtime/backend.py apply_xla_flags).
    xla_flags: Optional[str] = None


class Workload:
    """What a model family must supply (models/dcgan_mnist.py and
    models/mlpgan_insurance.py both do)."""

    name: str
    classifier_model_name: str  # "CV" / "insurance" in the final zip names

    def build_graphs(self) -> Dict[str, object]:
        raise NotImplementedError

    # weight-sync maps: lists of (dst_layer, src_layer, param_names)
    dis_to_gan: list
    gan_to_gen: list
    dis_to_classifier: list

    def ensure_data(self, res_path: str):
        """Return (train_csv, test_csv)."""
        raise NotImplementedError

    def grid_extra_arrays(self, trainer: "GANTrainer", grid_out,
                          step: int) -> list:
        """Workload-specific extra artifacts at print_every, as
        ``[(path, array)]`` pairs (the insurance main dumps classifier
        predictions over the generated grid, dl4jGANInsurance.java:422-437).
        Dispatch any device compute here, on the training thread — the
        returned arrays are materialized and written by the async artifact
        writer."""
        return []


def _largest_batch_divisor(batch_size: int, limit: int) -> int:
    """Largest mesh size <= limit whose shards of ``batch_size`` are exact."""
    return max(d for d in range(1, limit + 1) if batch_size % d == 0)


def train_with_recovery(make_trainer: Callable[[bool], "GANTrainer"],
                        max_restarts: int = 2,
                        log: Callable[[str], None] = print,
                        backoff_base_s: float = 1.0,
                        backoff_max_s: float = 30.0) -> Dict[str, float]:
    """Failure detection / recovery (SURVEY.md §5): run the trainer; on a
    RETRYABLE exception, rebuild it and resume from the latest checkpoint.
    ``make_trainer(resume)`` constructs a fresh trainer (its config must
    set ``checkpoint_every`` — without checkpoints a restart replays from
    step 0, which the deterministic data/PRNG order makes correct but
    wasteful).  Deterministic resume (tests/test_train.py, chaos suite)
    makes restart-equals-never-failed exact.

    Error classification — not every failure deserves a restart:

    * FATAL, re-raised immediately: ``ValueError``/``TypeError`` (config
      errors and checkpoint structure mismatches — a restart replays the
      identical mistake), ``CheckpointCorruptError`` (an explicitly
      requested checkpoint is torn; retrying cannot un-tear it) and
      ``NanAlarmError`` (deterministic replay from the last checkpoint
      marches into the same NaN — restarting only burns the budget).
    * ``PreemptionError``: re-raised — the emergency checkpoint is on
      disk and the host is being evicted; the SCHEDULER restarts the
      job (mains exit 75 / EX_TEMPFAIL).
    * Everything else is retryable, with exponential backoff plus
      jitter (``backoff_base_s * 2^attempt``, capped, x[0.5, 1.5) —
      a fleet of evicted hosts must not hammer storage in lockstep).

    The restart budget is PROGRESS-AWARE: whenever a failure lands at a
    later step than the previous one, the run has advanced past the old
    failure point and the attempt counter resets — one flaky host taxes
    the run per incident, while a genuine crash-loop (failing at the
    same step every time) still exhausts ``max_restarts``.

    ``RollbackRequested`` (the ``--nan-alarm rollback`` healing path,
    train/rollback.py) is handled here but does NOT burn the restart
    budget or back off: the rollback budget is the RollbackManager's
    own (progress-aware, ``--max-rollbacks``), the manager already
    charged it before raising, and the restore is an in-process resume
    — the next incarnation restores the last verified checkpoint from
    before the bad step with a cut LR and a perturbed noise stream.
    ``RollbackError`` (budget exhausted) and ``DivergenceError`` (the
    sentinel's abort action — a deterministic replay re-diverges
    identically) join the fatal class.

    Data-plane classification (data/resilient.py): ``DataSourceError``
    (a source still failing after its bounded retries) stays in the
    RETRYABLE class — the restart rebuilds the reader stack with fresh
    file handles, exactly the medicine for storage flakiness that
    outlives one read — while ``DataQuarantineError`` (corrupt-record
    budget exhausted) is FATAL: a restart re-reads the same poisoned
    dataset and re-exhausts the same budget.

    Elastic recovery (parallel/elastic.py): every retryable restart
    passes the ``multihost.agree_world`` mesh-formation barrier before
    rebuilding — the surviving hosts agree on the world, and the next
    incarnation forms its mesh over it (``GANTrainerConfig.elastic``)
    and reshards the latest checkpoint onto it instead of demanding
    the original world size.  A simulated device loss
    (testing/chaos.py ``DeviceLostError``) is retryable by
    construction — the restart IS the reshard point."""
    import random as _random

    from gan_deeplearning4j_tpu.checkpoint import CheckpointCorruptError
    from gan_deeplearning4j_tpu.data.resilient import DataQuarantineError
    from gan_deeplearning4j_tpu.telemetry import NanAlarmError
    from gan_deeplearning4j_tpu.train.divergence import DivergenceError
    from gan_deeplearning4j_tpu.train.preemption import PreemptionError
    from gan_deeplearning4j_tpu.train.rollback import (
        RollbackError,
        RollbackRequested,
    )

    def quiesce_checkpointer(trainer) -> None:
        # quiesce the failed incarnation's checkpoint writer BEFORE
        # rebuilding: an async save still in flight must become
        # durable (or surface its error in the log) before the new
        # trainer's init reclaims temp dirs out from under the old
        # worker — and close() also reaps the worker thread, which
        # would otherwise leak one per restart
        ck_close = getattr(getattr(trainer, "checkpointer", None),
                           "close", None)
        if ck_close is not None:
            try:
                ck_close()
            except Exception as ce:
                log(f"checkpoint writer failed during restart "
                    f"quiesce ({ce!r}); the restart will fall back "
                    "to the previous verified checkpoint")

    attempt = 0
    resume_next = False
    last_failure_step: Optional[int] = None
    while True:
        trainer = make_trainer(resume_next)
        try:
            return trainer.train(log=log)
        except (KeyboardInterrupt, PreemptionError):
            raise  # preemption: checkpointed; the scheduler requeues
        except (ValueError, TypeError, CheckpointCorruptError,
                NanAlarmError, DivergenceError, RollbackError,
                DataQuarantineError):
            raise  # fatal class: a restart replays the identical failure
        except RollbackRequested as e:
            # in-process heal: no budget burned here (the manager's was
            # charged), no backoff (nothing external to wait out) — the
            # rebuild below resumes before the bad step, LR cut and
            # noise stream advanced (the rollback.request/restore
            # events carry the timeline)
            quiesce_checkpointer(trainer)
            resume_next = True
            log(f"rolling back at step {e.step} (rollback "
                f"#{e.rollbacks}): {e} — restoring the last verified "
                "pre-failure checkpoint with a cut LR and a perturbed "
                "noise stream")
            continue
        except Exception as e:  # noqa: BLE001 — retryable class
            quiesce_checkpointer(trainer)
            step = int(getattr(trainer, "batch_counter", 0) or 0)
            # flight record FIRST, while the failed incarnation's ring
            # still holds the events that led here (the save/preempt
            # span in flight at the crash is in it) — even the final,
            # budget-exhausted failure leaves its timeline behind
            recorder = getattr(trainer, "_events", None)
            if recorder is not None:
                try:
                    recorder.dump_flight_record(
                        trainer.c.res_path, "training_failure",
                        extra={"step": step, "error": repr(e)})
                except Exception:  # gan4j-lint: disable=swallowed-exception — the flight-record dump must never mask the failure being dumped
                    pass
            if last_failure_step is not None and step > last_failure_step:
                attempt = 0  # progress since the last failure: reset budget
            last_failure_step = step
            attempt += 1
            resume_next = True
            if attempt > max_restarts:
                raise
            delay = 0.0
            if backoff_base_s > 0:
                delay = min(backoff_max_s,
                            backoff_base_s * (2 ** (attempt - 1)))
                delay *= 0.5 + _random.random()  # jitter: x[0.5, 1.5)
            # the mesh-formation barrier itself runs in the rebuilt
            # trainer's _maybe_resume (inside a watchdog region) —
            # every retry resumes, so every restart passes it exactly
            # once; a second allgather here would double the fleet's
            # synchronization points for a log line
            log(f"training failed ({e!r}) at step {step}; restart "
                f"{attempt}/{max_restarts} from the latest checkpoint "
                f"on the surviving world ({len(jax.devices())} local "
                f"device(s))"
                + (f" after {delay:.1f}s backoff" if delay else ""))
            # the restart marker must land in the run's events.jsonl,
            # but the failed incarnation's recorder is already closed
            # and the next one not yet open — append through a
            # transient recorder (the resumed run appends after it, so
            # the timeline stays one contiguous file)
            cfg = getattr(trainer, "c", None)
            if getattr(cfg, "events", False) \
                    and getattr(cfg, "res_path", None):
                try:
                    with events.EventRecorder(
                            path=os.path.join(cfg.res_path,
                                              events.EVENTS_NAME),
                            append=True, flush_every=1) as tr_rec:
                        tr_rec.instant(
                            "recovery.restart", step=step,
                            attempt=attempt,
                            backoff_s=round(delay, 3), error=repr(e))
                except Exception:  # gan4j-lint: disable=swallowed-exception — never-mask discipline (see below)
                    # same never-mask discipline as the flight-record
                    # dump above: ANY recorder failure (unwritable res
                    # dir is OSError, but a concurrently-removed dir
                    # can surface as ValueError from the closed/invalid
                    # recorder state) must not eat the retry — the
                    # marker is diagnostics, the restart is the product
                    pass
            if delay:
                time.sleep(delay)


def add_health_args(parser) -> None:
    """Shared CLI flags for the training-health supervision layer
    (watchdog / divergence sentinel / rollback) — one definition so the
    protocol mains cannot drift apart.  ``--nan-alarm`` itself stays
    with each main (its help text carries workload-specific paths)."""
    parser.add_argument(
        "--divergence", action="store_true",
        help="arm the windowed divergence sentinel (needs --telemetry): "
             "trip on loss explosion / grad-norm blowup BEFORE NaNs "
             "appear; the action on a trip is --nan-alarm's (warn when "
             "unset) — pair with '--nan-alarm rollback' to heal")
    parser.add_argument(
        "--max-rollbacks", type=int, default=3, metavar="N",
        help="rollback budget for '--nan-alarm rollback' (progress-"
             "aware like --max-restarts: a rollback at a later step "
             "than the previous one resets the counter); exhausted = "
             "fatal escalation")
    parser.add_argument(
        "--rollback-lr-factor", type=float, default=0.5, metavar="F",
        help="learning-rate multiplier applied per rollback "
             "(compounding) — the healing half of rollback-with-"
             "perturbation")
    parser.add_argument(
        "--watchdog", action="store_true",
        help="arm the hang watchdog: heartbeat at every step/chunk "
             "boundary and around every blocking region; a silent hang "
             "(dead data source, wedged readback/collective) dumps a "
             "flight record, takes a best-effort emergency checkpoint "
             "and becomes a retryable WatchdogTimeout for "
             "--max-restarts instead of a run stuck forever")
    parser.add_argument(
        "--watchdog-deadline", type=float, default=None, metavar="SEC",
        help="fixed watchdog deadline in seconds (default: auto-scale "
             "from the measured steady-state step time)")
    parser.add_argument(
        "--sanitize", action="store_true",
        help="arm the runtime trace sanitizers "
             "(analysis/sanitizers.py): any post-warmup XLA recompile "
             "is counted (gan4j_recompiles_total), traced "
             "(compile.recompile) and warned about, and the fused "
             "hot-loop dispatches run under a transfer guard that "
             "raises on implicit host<->device transfers — the runtime "
             "half of gan4j-lint (docs/STATIC_ANALYSIS.md)")


def add_data_args(parser) -> None:
    """Shared CLI flags for the resilient data plane
    (data/resilient.py) — one definition so the protocol mains cannot
    drift apart, like ``add_health_args``."""
    parser.add_argument(
        "--data-retries", type=int, default=3, metavar="N",
        help="bounded retries (exponential backoff + jitter) on "
             "transient data-source I/O errors — a flaky disk or NFS "
             "blip becomes gan4j_data_retries_total instead of a dead "
             "run; exhaustion is a retryable DataSourceError for "
             "--max-restarts (0 = die on the first I/O error)")
    parser.add_argument(
        "--max-quarantine", type=int, default=0, metavar="N",
        help="corrupt-record tolerance: skip up to N malformed records "
             "(bad width/parse/non-finite/label), logging each to "
             "res-path/quarantine.jsonl with file:line provenance; "
             "EXCEEDING the budget is a fatal DataQuarantineError "
             "(docs/FAULT_TOLERANCE.md).  0 = strict: the first "
             "malformed record raises, naming its file:line")


def data_config_kwargs(args) -> Dict:
    """The add_data_args flags as GANTrainerConfig overrides."""
    return dict(
        data_retries=args.data_retries,
        max_quarantine=args.max_quarantine,
    )


def health_config_kwargs(args) -> Dict:
    """The add_health_args flags as GANTrainerConfig overrides."""
    return dict(
        divergence=args.divergence,
        max_rollbacks=args.max_rollbacks,
        rollback_lr_factor=args.rollback_lr_factor,
        watchdog=args.watchdog,
        watchdog_deadline_s=args.watchdog_deadline,
        sanitize=args.sanitize,
    )


def check_recovery_args(parser, args) -> None:
    """Shared CLI validation for the mains' recovery/health flags."""
    if args.max_restarts > 0 and args.checkpoint_every <= 0:
        parser.error("--max-restarts needs --checkpoint-every (without "
                     "checkpoints every restart replays from step 0)")
    if getattr(args, "nan_alarm", None) == "rollback" \
            and args.checkpoint_every <= 0:
        parser.error("--nan-alarm rollback needs --checkpoint-every "
                     "(rollback restores the last verified checkpoint "
                     "from before the bad step; without checkpoints it "
                     "can only replay from step 0)")


def run_with_recovery(config: "GANTrainerConfig", make_workload,
                      max_restarts: int = 0):
    """Shared main wiring: construct the trainer (fresh workload each
    attempt, resume=True on retries) and train, optionally under
    train_with_recovery.  Returns (trainer, result) — the trainer is the
    last (successful) one, for post-run evaluation.

    With ``nan_alarm="rollback"`` a single ``RollbackManager`` is
    created HERE and shared by every incarnation (the LR scale, RNG
    epoch and rollback budget must survive the rebuild — a fresh
    manager per attempt would reset them and loop forever), and the run
    goes through ``train_with_recovery`` even at ``max_restarts=0`` so
    the rollback restart path exists (other failures still re-raise
    immediately: the restart budget stays 0)."""
    holder = {}
    manager = None
    if config.nan_alarm == "rollback":
        from gan_deeplearning4j_tpu.train.rollback import RollbackManager

        manager = RollbackManager(max_rollbacks=config.max_rollbacks,
                                  lr_factor=config.rollback_lr_factor)

    def make_trainer(resume: bool) -> "GANTrainer":
        cfg = dataclasses.replace(config, resume=True) if resume else config
        holder["trainer"] = GANTrainer(make_workload(), cfg,
                                       rollback_manager=manager)
        return holder["trainer"]

    if max_restarts > 0 or manager is not None:
        result = train_with_recovery(make_trainer, max_restarts=max_restarts)
    else:
        result = make_trainer(False).train()
    return holder["trainer"], result


def sync_params(dst, src, mapping) -> None:
    for dst_layer, src_layer, names in mapping:
        dst.set_layer_params(
            dst_layer, {n: src.get_param(src_layer, n) for n in names}
        )


class GANTrainer:
    def __init__(self, workload: Workload, config: GANTrainerConfig,
                 rollback_manager=None):
        self.w = workload
        self.c = config
        self._rollback_mgr = rollback_manager
        if config.n_devices is not None and config.n_devices > 1 \
                and config.batch_size % config.n_devices != 0:
            # an EXPLICIT mesh size must divide the batch — fail before
            # ANY side effect (no res dir, no graph construction)
            usable = _largest_batch_divisor(config.batch_size,
                                             config.n_devices)
            raise ValueError(
                f"batch_size {config.batch_size} is not divisible by "
                f"--n-devices {config.n_devices}; shards are exact "
                f"(largest usable mesh for this batch: {usable})")
        if (config.elastic and config.n_devices is not None
                and config.n_devices > len(jax.devices())):
            # elastic mesh formation: the requested (VALID — the
            # divisibility check above already passed it) world is
            # gone, a shrunken fleet after preemption/device loss —
            # re-form over the survivors instead of refusing to
            # start.  The global batch is held; per-device shards
            # grow.  Deliberately AFTER the validation: a config that
            # never divided the batch must fail identically on every
            # host size, not be silently clamped into legality.
            import logging

            avail = len(jax.devices())
            resolved = _largest_batch_divisor(config.batch_size, avail)
            logging.getLogger(__name__).warning(
                "elastic mesh: %d devices requested but only %d "
                "attached; re-forming on a %d-device mesh (global "
                "batch %d held, per-device shard %d -> %d)",
                config.n_devices, avail, resolved, config.batch_size,
                config.batch_size // config.n_devices,
                config.batch_size // resolved)
            config = dataclasses.replace(config, n_devices=resolved)
            self.c = config
        # validate preemption signals EAGERLY (same fail-before-side-
        # effects discipline: an unknown name must not surface inside a
        # preemption grace window)
        self._preempt_signal_nums = ()
        self._preempt_guard = None
        if config.preempt_signals:
            from gan_deeplearning4j_tpu.train.preemption import parse_signals

            self._preempt_signal_nums = parse_signals(config.preempt_signals)
        # overlap-restructure toggles are trace-time process globals —
        # set them before ANY graph construction below traces an op
        from gan_deeplearning4j_tpu.ops import pool as _pool
        from gan_deeplearning4j_tpu.ops import upsample as _upsample

        _upsample.set_sum_bwd(config.upsample_sum_bwd)
        _pool.set_argmax_bwd(config.pool_argmax_bwd)
        if config.xla_flags:
            from gan_deeplearning4j_tpu.runtime.backend import apply_xla_flags

            apply_xla_flags(config.xla_flags)
        os.makedirs(config.res_path, exist_ok=True)

        graphs = workload.build_graphs()
        self.dis = graphs["dis"]
        self.gen = graphs["gen"]
        self.gan = graphs["gan"]
        self.classifier = graphs["classifier"]

        # Distribution: fit() through DataParallelGraph when a mesh is used;
        # gen stays local (it only ever runs inference on the driver).
        # The mesh size must divide every fitted batch (B and the D-step's
        # 2B), so auto-selection picks the largest divisor of B that fits
        # the attached devices (the reference's local[4] with batch 50 has
        # the same constraint, satisfied as 50 = 4*12+2 only because DL4J
        # pads partitions; we keep shards exact instead).
        if config.n_devices is None:
            avail = len(jax.devices())
            resolved = _largest_batch_divisor(config.batch_size, avail)
            if resolved < avail:
                import logging

                logging.getLogger(__name__).warning(
                    "batch_size %d is not divisible by the %d attached "
                    "devices; using a %d-device mesh (%d idle)",
                    config.batch_size, avail, resolved, avail - resolved)
            # don't mutate the caller's config object (a reused config would
            # silently inherit this host's resolution)
            config = dataclasses.replace(config, n_devices=resolved)
            self.c = config

        # PRNG streams (seed 666 discipline; see runtime/prng.py).  The
        # training z-stream is COUNTER-BASED — z1 under fold_in(base, 2i),
        # z2 under fold_in(base, 2i+1) for step i — so the fused step can
        # derive it on-device from the step index alone and resume needs no
        # saved RNG state.
        root = prng.root_key(config.seed)
        self._z_base = prng.stream(root, "train-z")
        self._fused_rng = prng.stream(root, "fused-step")
        # label softening: sampled once, reused every iteration (reference
        # quirk — dl4jGANComputerVision.java:384-385)
        B = config.batch_size
        self.soften_real = 0.05 * jax.random.normal(
            prng.stream(root, "soften-real"), (B, 1), dtype=jnp.float32)
        self.soften_fake = 0.05 * jax.random.normal(
            prng.stream(root, "soften-fake"), (B, 1), dtype=jnp.float32)
        self._ones = jnp.ones((B, 1), dtype=jnp.float32)

        # Fused mode (default for gradient_sync): the whole protocol
        # iteration is ONE jitted/SPMD program (train/fused_step.py) —
        # cross-graph syncs are free aliasing, state buffers donated, and
        # the per-step host work is a single dispatch on the step index.
        # param_averaging keeps the unfused per-fit path (its job-level
        # broadcast/average semantics are inherently per-network).
        self._fused_step = None
        self._fused_enabled = (
            config.fused and config.dp_mode == "gradient_sync")
        if config.ema_decay > 0 and not self._fused_enabled:
            raise ValueError(
                "ema_decay > 0 requires the fused step (fused=True, "
                "dp_mode='gradient_sync') — only it maintains the EMA; "
                "silently training without one would misreport fid_ema")
        mesh = data_mesh(config.n_devices) if config.n_devices > 1 else None
        self._mesh = mesh
        if self._fused_enabled:
            from gan_deeplearning4j_tpu.train import fused_step as fused

            self._fused_lib = fused
            # the step itself is built in train(): it is specialized on the
            # data_on_device residency decision, which needs the dataset
            self._batch_sharding = (
                mesh_lib.batch_sharding(mesh) if mesh is not None else None)
        elif config.n_devices == 1:
            self._fit_dis = self.dis.fit
            self._fit_gan = self.gan.fit
            self._fit_clf = self.classifier.fit
        else:
            kw = dict(mesh=mesh, mode=config.dp_mode,
                      averaging_frequency=config.averaging_frequency)
            self.spark_dis = DataParallelGraph(self.dis, **kw)
            self.spark_gan = DataParallelGraph(self.gan, **kw)
            self.spark_clf = DataParallelGraph(self.classifier, **kw)
            self._fit_dis = self.spark_dis.fit
            self._fit_gan = self.spark_gan.fit
            self._fit_clf = self.spark_clf.fit

        if config.nan_alarm not in (None, "warn", "snapshot", "abort",
                                    "rollback"):
            raise ValueError(
                f"nan_alarm must be None/'warn'/'snapshot'/'abort'/"
                f"'rollback', got {config.nan_alarm!r}")
        if config.nan_alarm and not config.telemetry:
            raise ValueError(
                "nan_alarm needs telemetry=True — without the in-graph "
                "NaN/Inf counters there is nothing to trip on")
        if config.divergence and not config.telemetry:
            raise ValueError(
                "divergence=True needs telemetry=True — the sentinel "
                "watches the in-graph grad-norm/loss records")
        if config.telemetry and not self._fused_enabled:
            raise ValueError(
                "telemetry=True requires the fused step (fused=True, "
                "dp_mode='gradient_sync') — only the fused program "
                "computes the in-graph numerics block")
        if config.nan_alarm == "rollback" and rollback_manager is None:
            raise ValueError(
                "nan_alarm='rollback' needs a RollbackManager shared "
                "across trainer incarnations (run_with_recovery wires "
                "one; pass rollback_manager= when driving GANTrainer "
                "directly) — a per-incarnation manager would reset the "
                "LR cut, the RNG epoch and the budget on every rollback")
        self._nan_alarm = None
        self._nan_handled = False
        if config.nan_alarm:
            from gan_deeplearning4j_tpu.telemetry import NanAlarm

            self._nan_alarm = NanAlarm()
        self._divergence = None
        self._div_handled = False
        if config.divergence:
            from gan_deeplearning4j_tpu.train.divergence import (
                DivergenceSentinel,
            )

            self._divergence = DivergenceSentinel(
                window=config.divergence_window,
                factor=config.divergence_factor,
                patience=config.divergence_patience)
        # rollback plumbing: a pending (reason, bad_step) set by the
        # alarm polls and consumed by _maybe_rollback at the next
        # boundary (multi-host: after the fleet consensus); the resume
        # bound is installed by RollbackManager.apply below
        self._rollback_pending: Optional[tuple] = None
        self._resume_max_step: Optional[int] = None
        self._watchdog = None
        # runtime trace sanitizers (analysis/sanitizers.py), armed by
        # config.sanitize: a RecompileSentinel for the whole run (armed
        # post-warmup at the first steady fence) and a transfer guard
        # around the fused dispatches
        self._sanitizer = None
        # scrape registry (telemetry/exporter.py): fed from every
        # materialized metrics record (on the logger's worker thread)
        # and, at scrape time, from the live goodput ledger; served
        # over HTTP when config.metrics_port is set
        self.registry = MetricsRegistry()
        self.registry.observe_goodput(
            lambda: self.goodput.report()
            if getattr(self, "goodput", None) is not None else None)
        # resilient data plane (data/resilient.py): one health feed for
        # the gan4j_data_* series and the /healthz "data" block, plus
        # the per-run corrupt-record quarantine when a budget is set
        if config.data_retries < 0:
            raise ValueError(
                f"data_retries must be >= 0, got {config.data_retries}")
        if config.max_quarantine < 0:
            raise ValueError(
                f"max_quarantine must be >= 0, got {config.max_quarantine}")
        self.data_health = DataHealth()
        self.registry.observe_data(self.data_health.report)
        # elastic-mesh surface (parallel/elastic.py): the live mesh
        # size, reshard totals and formation state feed the
        # gan4j_mesh_devices / gan4j_reshard_* series and the /healthz
        # "mesh" block — ok:false while mesh formation is quorum-
        # blocked (the agree_world barrier in _maybe_resume), so a
        # probe can tell "waiting for the fleet" from "training"
        self._mesh_forming = False
        self._reshard_total = 0
        self._reshard_seconds = 0.0
        self.registry.observe_mesh(self._mesh_report)
        self._quarantine = None
        if config.max_quarantine:
            self._quarantine = RecordQuarantine(
                os.path.join(config.res_path, "quarantine.jsonl"),
                budget=config.max_quarantine, health=self.data_health)
        # O(1) resumable iterator state: the live train iterator and
        # (on streaming paths) the consuming prefetch wrapper, read by
        # _checkpoint_extra to stamp every checkpoint with the consumed
        # data position (data/csv.py state contract)
        self._train_iter = None
        self._data_stream = None
        self._iter_state_consumed = None
        self.metrics_port: Optional[int] = None  # resolved in train()
        self._events: Optional[events.EventRecorder] = None
        self.metrics = MetricsLogger(
            os.path.join(config.res_path, f"{config.dataset_name}_metrics.jsonl")
            if config.metrics else None,
            on_record=self._observe_record,
            # a resumed incarnation APPENDS to its own history — the
            # same one-contiguous-timeline discipline as events.jsonl,
            # and what lets a post-crash resume be compared bit-for-bit
            # against an uninterrupted run's full timeline
            append=config.resume,
        )
        # a checkpointer also exists for resume-only runs and preemption-
        # armed runs (the emergency save needs somewhere durable to land
        # even when no periodic cadence was configured)
        self.checkpointer = None
        if (config.checkpoint_every or config.resume
                or self._preempt_signal_nums):
            ck = TrainCheckpointer(
                os.path.join(config.res_path, "checkpoints"),
                keep=config.checkpoint_keep,
            )
            if config.async_checkpoint:
                ck = AsyncCheckpointer(ck)
            self.checkpointer = ck

        # latent evaluation grid: the cartesian product of linspace(-1,1,n)
        # per latent dim, row-major with the first dim outermost — reference
        # order for z_size=2 (:363-370); generalizes to any z_size (n^z
        # rows, so keep n small for z_size > 2)
        n = config.num_gen_samples
        grid = np.linspace(-1.0, 1.0, n, dtype=np.float32)
        self.z_grid = jnp.asarray(
            np.stack(
                np.meshgrid(*([grid] * config.z_size), indexing="ij"), axis=-1
            ).reshape(-1, config.z_size)
        )

        if not 0.0 <= config.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in [0, 1), got {config.ema_decay} "
                "(1.0 would pin the EMA at initialization forever)")
        self.batch_counter = 0
        self._final_state = None   # fused-state as of the last dispatch
        self._final_losses = None
        self.goodput = None       # GoodputTimer, created per train() run
        self.run_manifest = None  # run_manifest.json payload, ditto
        self._test_batches = None
        self._steps_per_call = 1
        self._fused_multi = None
        self._stream_codec = None
        self._stream_dedup = False
        self._table_codec = None
        self._codec_lib = None
        # inline writer until train() swaps in the background one, so the
        # dump methods also work when called directly (tests, notebooks)
        self._dumper = AsyncArtifactWriter(synchronous=True)
        if self._rollback_mgr is not None:
            mgr = self._rollback_mgr
            # mirror the manager's lifetime count into the scrape
            # series at scrape time (monotonic — set_counter only
            # raises it) and install the current perturbation: LR
            # scale, noise-stream epoch, resume bound.  Must run before
            # anything traces the updaters' LR constants into a program.
            self.registry.add_callback(
                lambda reg: reg.set_counter("gan4j_rollback_total",
                                            float(mgr.total)))
            mgr.apply(self)

    def _observe_record(self, rec: Dict) -> None:
        """MetricsLogger ``on_record`` hook (worker thread): every
        materialized record feeds the NaN alarm, the divergence
        sentinel AND the scrape registry."""
        if self._nan_alarm is not None:
            self._nan_alarm.observe(rec)
        if self._divergence is not None:
            self._divergence.observe(rec)
        self.registry.observe_record(rec)

    # -- artifact dumps ------------------------------------------------------

    def _dump_grid(self) -> None:
        # dispatch on this thread (step-k param snapshot), write on the worker
        out = self.gen.output(self.z_grid)[0]
        out = out.reshape(self.z_grid.shape[0], self.c.num_features)
        path = os.path.join(
            self.c.res_path,
            f"{self.c.dataset_name}_out_{self.batch_counter}.csv")
        extras = self.w.grid_extra_arrays(self, out, self.batch_counter)
        start_host_copy((out, extras))

        def write(out=out, path=path, extras=extras):
            write_csv_matrix(path, np.asarray(out))
            for p, arr in extras:
                write_csv_matrix(p, np.asarray(arr))

        self._dumper.submit(write)

    def _dump_predictions(self, iter_test: RecordReaderDataSetIterator) -> None:
        # the test set is loop-invariant: transfer it once and reuse the
        # device-resident batches across every save_every dump (a per-dump
        # re-upload over a tunneled PJRT link would dominate the dump)
        if self._test_batches is None:
            iter_test.reset()
            batches = []
            while iter_test.has_next():
                batches.append(jnp.asarray(iter_test.next().features))
            # fuse into ONE resident array when it fits: a single
            # classifier dispatch per dump instead of one per test batch
            # (batch_size_pred exists for host memory in the reference's
            # loop, dl4jGANComputerVision.java:498-522 — inference over
            # running-stats BN is batch-size independent)
            if len(batches) > 1 and sum(b.nbytes for b in batches) <= 256 << 20:
                batches = [jnp.concatenate(batches)]
            self._test_batches = batches
        # dispatch every batch on this thread, then hand the overlapped
        # readback (per-batch round trips would serialize on a tunneled
        # link) and the CSV write to the worker
        from gan_deeplearning4j_tpu.utils import overlap_device_get

        outs = [self.classifier.output(xb)[0] for xb in self._test_batches]
        path = os.path.join(
            self.c.res_path,
            f"{self.c.dataset_name}_test_predictions_{self.batch_counter}.csv")

        start_host_copy(outs)

        def write(outs=outs, path=path):
            write_csv_matrix(path, np.vstack(overlap_device_get(outs)))

        self._dumper.submit(write)

    # -- checkpointing -------------------------------------------------------

    def _graphs(self) -> Dict[str, object]:
        return {"dis": self.dis, "gen": self.gen, "gan": self.gan,
                "classifier": self.classifier}

    def _mesh_spec_dict(self) -> Dict:
        """The live topology as a checkpoint-manifest ``mesh_spec``
        (parallel/elastic.py) — stamped into EVERY save so a restore on
        a different world reshards instead of trusting the shapes."""
        from gan_deeplearning4j_tpu.parallel.elastic import MeshSpec

        return MeshSpec.from_mesh(self._mesh).to_dict()

    def _mesh_report(self) -> Dict:
        """Scrape feed for the elastic-mesh surface: current device
        count, reshard accounting and the formation state (the
        /healthz "mesh" block is ``ok: false`` only while the
        agree_world quorum barrier is in flight)."""
        mesh = self._mesh
        return {
            "devices": int(mesh.devices.size) if mesh is not None else 1,
            "reshard_total": int(self._reshard_total),
            "reshard_seconds": float(self._reshard_seconds),
            "forming": bool(self._mesh_forming),
            "ok": not self._mesh_forming,
        }

    def _iter_state(self) -> Optional[Dict]:
        """O(1) consumed-position of the training data, for the
        checkpoint ``extra`` dict.  Streaming paths read the snapshot
        the bookkeeping stashed at the last STEP BOUNDARY (exact for
        ANY source that exposes ``state()``, including non-tabular
        ones — and, being boundary-aligned, safe for the watchdog's
        emergency checkpoint, which fires while the training thread
        may have already consumed the next batch); the resident path —
        which never consumes the host iterator — derives the canonical
        position arithmetically from the step counter.  None when
        neither is available (the resume then falls back to the legacy
        replay)."""
        st = self._iter_state_consumed
        if st is not None:
            return st
        fn = getattr(self._train_iter, "state_for_step", None)
        if fn is not None:
            try:
                return fn(self.batch_counter)
            except ValueError:
                return None  # no full batch: nothing derivable
        return None

    def _checkpoint_extra(self) -> Dict:
        """Run state the graphs' params don't carry.  No RNG state
        needed: the z-stream is counter-based, derived from
        batch_counter (the checkpoint step) alone.  The data-iterator
        position DOES ride along (``iter_state``, a JSON scalar): it is
        what lets ``_maybe_resume`` restore the data plane in O(1)
        instead of replaying every consumed batch."""
        extra = {"soften_real": self.soften_real,
                 "soften_fake": self.soften_fake}
        it_state = self._iter_state()
        if it_state is not None:
            import json as _json

            from gan_deeplearning4j_tpu.parallel.elastic import (
                pack_iter_state,
            )

            # single host: the bare data/csv.py state (bit-compatible
            # with pre-elastic checkpoints); a fleet packs the
            # boundary-aligned cursor per host (equal under SPMD
            # lockstep — elastic.pack_iter_state documents why) so a
            # restore at a different host count merges instead of
            # guessing
            extra["iter_state"] = _json.dumps(
                pack_iter_state(it_state, jax.process_count()),
                sort_keys=True)
        # the generator EMA is state the graphs' params don't carry;
        # without it a crash-resume would silently restart the
        # trajectory average from the current weights
        ema = getattr(self.gen, "ema_params", None)
        if ema is not None:
            for layer, lp in ema.items():
                for n, v in lp.items():
                    extra[f"ema:{layer}:{n}"] = v
        return extra

    def _maybe_checkpoint(self) -> None:
        if (self.checkpointer and self.c.checkpoint_every
                and self.batch_counter % self.c.checkpoint_every == 0):
            # drain queued artifact writes first: once this checkpoint
            # exists, a crash-resume continues past this step and would
            # never re-create artifacts that were still in the queue
            self._dumper.flush()
            with events.span("checkpoint.save", step=self.batch_counter):
                self.checkpointer.save(
                    self.batch_counter, self._graphs(),
                    extra=self._checkpoint_extra(),
                    mesh_spec=self._mesh_spec_dict())

    def _emergency_checkpoint(self, directory: Optional[str] = None,
                              keep: int = 1) -> str:
        """The ONE "state to disk NOW" mechanism — preemption saves and
        NaN forensic snapshots both exit through here (a second ad-hoc
        save path would inevitably drift from the real one).  Captures
        the state as of the last dispatched step, saves through the run
        checkpointer (or a dedicated directory, e.g. ``nan_snapshot``)
        and BARRIERS on async serialization: an emergency save that is
        not durable when the process exits saved nothing."""
        with events.span("checkpoint.emergency", step=self.batch_counter,
                         directory=directory or "checkpoints"):
            if self._fused_step is not None \
                    and self._final_state is not None:
                self._fused_lib.state_to_graphs(
                    self._final_state, self.dis, self.gen, self.gan,
                    self.classifier)
            if directory is None:
                ck = self.checkpointer
                if ck is None:  # no cadence: land in the usual spot
                    ck = TrainCheckpointer(
                        os.path.join(self.c.res_path, "checkpoints"),
                        keep=self.c.checkpoint_keep)
                    self.checkpointer = ck
            else:
                ck = TrainCheckpointer(directory, keep=keep)
            path = ck.save(self.batch_counter, self._graphs(),
                           extra=self._checkpoint_extra(),
                           mesh_spec=self._mesh_spec_dict())
            wait = getattr(ck, "wait", None)
            if wait is not None:
                wait()
            return path

    def _maybe_preempt(self) -> None:
        """Boundary poll of the preemption guard: the in-flight fused
        call has returned, so take the emergency checkpoint, write the
        resumable marker and leave through ``PreemptionError`` (the
        recovery wrapper re-raises it; mains exit 75).

        Multi-host: the consensus allgather is entered by EVERY host at
        every boundary while the guard is armed — ``any_triggered``
        preempts the whole fleet together, so a single evicted host
        (partial signal delivery) cannot strand its peers inside a
        mismatched collective."""
        guard = self._preempt_guard
        if guard is None:
            return
        if jax.process_count() > 1:
            from gan_deeplearning4j_tpu.parallel import multihost

            with self._wd_region("collective.agree_preemption"):
                any_trig, agreed = multihost.agree_preemption(
                    guard.triggered, self.batch_counter)
        else:
            any_trig, agreed = guard.triggered, self.batch_counter
        if not any_trig:
            return
        if agreed != self.batch_counter:
            import logging

            logging.getLogger(__name__).warning(
                "preemption: fleet-agreed step %d != local step %d "
                "(straggler host)", agreed, self.batch_counter)
        from gan_deeplearning4j_tpu.train.preemption import preempt_exit

        with self._phase("checkpoint"):
            path = self._emergency_checkpoint()
        preempt_exit(self.c.res_path, guard,
                     local_step=self.batch_counter, fleet_min_step=agreed,
                     checkpoint=path,
                     run_id=(self.run_manifest or {}).get("run_id"))

    def _maybe_resume(self, iter_train: RecordReaderDataSetIterator) -> None:
        if not (self.c.resume and self.checkpointer):
            return
        # a PREEMPTED.json marker from the evicted incarnation is
        # consumed here — this restart IS the resume it asked for
        from gan_deeplearning4j_tpu.train.preemption import MARKER_NAME

        marker = os.path.join(self.c.res_path, MARKER_NAME)
        if os.path.exists(marker):
            import logging

            logging.getLogger(__name__).info(
                "resuming a preempted run (consuming %s)", marker)
            os.remove(marker)
        # mesh-formation barrier (elastic recovery): agree on the
        # surviving world BEFORE restoring — on a fleet the allgather
        # holds every host here until all survivors check in, and the
        # /healthz "mesh" block answers ok:false for the duration
        # (quorum-blocked is an observable state, not a silent wait).
        # Single process: passthrough, no device contact.
        from gan_deeplearning4j_tpu.parallel import multihost

        self._mesh_forming = True
        try:
            with self._wd_region("collective.agree_world"):
                n_proc, n_dev = multihost.agree_world()
        finally:
            self._mesh_forming = False
        mesh_devs = (self._mesh.devices.size
                     if self._mesh is not None else 1)
        events.instant("mesh.form", step=self.batch_counter,
                       processes=n_proc, devices=n_dev,
                       mesh_devices=mesh_devs)
        if n_dev < mesh_devs:
            # the barrier exists to CATCH world changes, not narrate
            # them: a mesh spanning more devices than the agreed
            # world would die later inside shard_map with an opaque
            # sharding error — fail here, naming both numbers (fatal
            # in the recovery wrapper: every restart of this config
            # re-agrees on the same too-small world)
            raise ValueError(
                f"mesh formation: the fleet agreed on {n_dev} "
                f"device(s) ({n_proc} process(es)) but this "
                f"incarnation's mesh spans {mesh_devs} — the "
                f"surviving world cannot carry it; resume with "
                f"n_devices <= {n_dev}")
        # a rollback resume is BOUNDED: the manager recorded the first
        # known-bad step, and restoring at-or-after it would replay the
        # poisoned state the rollback exists to discard
        max_step = self._resume_max_step
        try:
            step, extra = self.checkpointer.restore(
                self._graphs(), max_step=max_step,
                target_mesh=self._mesh)
        except NoVerifiedCheckpointError as e:
            # restore() already fell back as far as it could; an empty or
            # fully-torn directory means: start from step 0 (the
            # deterministic data/PRNG order makes that correct) rather
            # than crash the restart the checkpoints were meant to enable
            import logging

            logging.getLogger(__name__).warning(
                "resume requested but %s; starting from step 0", e)
            if max_step is not None:
                # still a rollback: the checkpoints ABOVE the bound are
                # known-poisoned and must not be resumable later
                self.checkpointer.prune_above(max_step)
                self._consume_rollback_restore(0, max_step)
            return
        if max_step is not None:
            # the restore point is committed: drop the poisoned suffix
            # (a later plain restart must never resume into it) and
            # mark the timeline
            self.checkpointer.prune_above(step)
            self._consume_rollback_restore(step, max_step)
        reshard_info = extra.pop("__reshard__", None)
        if reshard_info is not None:
            # reshard-on-restore happened (checkpoint/checkpointer.py
            # _load_elastic): account it — the overlay marker, the
            # counter a chaos lane asserts on, and the time paid.
            # These fields are the SINGLE source of truth for the
            # gan4j_reshard_* series (the observe_mesh callback
            # mirrors them at scrape time — a second direct writer
            # here could silently drift from it).
            self._reshard_total += 1
            self._reshard_seconds += float(reshard_info["seconds"])
            events.instant(
                "reshard.restore", step=step,
                from_devices=reshard_info["from"].get("device_count"),
                to_devices=reshard_info["to"].get("device_count"),
                seconds=round(float(reshard_info["seconds"]), 4))
        self.batch_counter = step
        self.soften_real = jnp.asarray(extra["soften_real"])
        self.soften_fake = jnp.asarray(extra["soften_fake"])
        ema = {}
        for k, v in extra.items():
            if k.startswith("ema:"):
                _, layer, name = k.split(":", 2)
                ema.setdefault(layer, {})[name] = jnp.asarray(v)
        if ema:
            # mirror gen.params' full layer structure: stateless layers
            # (e.g. upsample) carry empty dicts the flat keys can't encode
            self.gen.ema_params = {
                layer: ema.get(layer, {}) for layer in self.gen.params}
        # (older checkpoints carried a "z_key" entry; the z-stream is now
        # counter-based and needs no restored state)
        # Data-plane position: O(1) restore from the checkpoint's
        # iter_state when it carries one (data/csv.py state contract) —
        # constant-time regardless of step, the property a true
        # streaming source needs.  Checkpoints from before the resilient
        # data plane (or foreign iterators without restore_state) fall
        # back to the legacy replay of the consumption pattern.
        restored = False
        raw_state = extra.get("iter_state")
        restore = getattr(iter_train, "restore_state", None)
        if raw_state is not None and restore is not None:
            import json as _json
            import logging

            from gan_deeplearning4j_tpu.parallel.elastic import (
                unpack_iter_state,
            )

            try:
                # a fleet checkpoint carries per-host cursors; unpack
                # merges them deterministically when the host count
                # changed (lagging position wins: records may be
                # re-fed, never dropped) — a single-host bare state
                # passes through untouched
                it_state = unpack_iter_state(
                    _json.loads(raw_state), jax.process_count(),
                    jax.process_index())
                restore(it_state)
                restored = True
                events.instant("data.resume_state", step=step,
                               epoch=it_state.get("epoch"),
                               cursor=it_state.get("cursor"))
            except ValueError as e:
                # shuffle-contract mismatch / undecodable state: the
                # replay below reproduces the position the hard way —
                # unless the contract REALLY changed, in which case the
                # replayed order differs too and only the config owner
                # can fix it; warn either way
                logging.getLogger(__name__).warning(
                    "checkpoint iter_state not restorable (%s); "
                    "falling back to replay fast-forward", e)
        if restored:
            return
        self._replay_fast_forward(iter_train, step)

    def _replay_fast_forward(self, iter_train, step: int) -> None:
        """Legacy O(step) resume: replay the training loop's exact
        consumption pattern — partial epoch tails are consumed-and-
        skipped WITHOUT counting as a step, and exhaustion wraps
        (mirrors train() so a resumed run sees identical batches).
        Guarded against a source that can never yield a full batch:
        two consecutive wraps without progress (or an exhausted-empty
        source) raise a clear ValueError instead of spinning forever —
        the zero-batch reset loop a short tail's ``continue`` used to
        enter."""
        steps_done = 0
        fruitless_wraps = 0
        while steps_done < step:
            if not iter_train.has_next():
                iter_train.reset()
            try:
                ds = iter_train.next()
            except StopIteration:
                raise ValueError(
                    f"cannot fast-forward to step {step}: the data "
                    "source is empty even after reset") from None
            if ds.num_examples() < self.c.batch_size:
                iter_train.reset()
                fruitless_wraps += 1
                if fruitless_wraps > 1:
                    # a WHOLE pass produced no full batch: every later
                    # pass replays the same rows and spins identically
                    raise ValueError(
                        f"cannot fast-forward to step {step}: the data "
                        f"source never yields a full batch of "
                        f"{self.c.batch_size} (pass exhausted after "
                        f"{steps_done} full batches)")
                continue
            fruitless_wraps = 0
            steps_done += 1
            if not iter_train.has_next():
                iter_train.reset()

    def _consume_rollback_restore(self, restored_step: int,
                                  max_step: int) -> None:
        """One rollback restore happened: emit the ``rollback.restore``
        timeline marker (the overlay vocabulary, telemetry/events.py)
        and clear the resume bound — it applied to THIS restore only; a
        later plain restart of the same run must resume from wherever
        the healed run has checkpointed since."""
        mgr = self._rollback_mgr
        events.instant(
            "rollback.restore", step=restored_step,
            bad_step=max_step + 1,
            rollbacks=getattr(mgr, "total", None),
            lr_scale=getattr(mgr, "lr_scale", None))
        self._resume_max_step = None
        if mgr is not None:
            mgr.restore_before = None

    # -- the loop ------------------------------------------------------------

    def train(self, log: Callable[[str], None] = print) -> Dict[str, float]:
        """Run the training loop; with ``preempt_signals`` configured,
        the whole run is bracketed by the preemption guard (handlers
        restored on every exit path).  The run's event recorder
        (``events.jsonl`` + flight-recorder ring) is installed as the
        process-wide current recorder for the duration, so checkpoint
        workers, prefetch threads and collectives land their events in
        this run's file; with ``metrics_port`` set, the /metrics +
        /healthz exporter serves the scrape registry for the same
        window."""
        from gan_deeplearning4j_tpu.train.shell import SupervisionShell

        c = self.c
        # the install/teardown bracket lives in train/shell.py now —
        # single-model runs and fleets share ONE shell; this trainer is
        # just one payload behind it (ROADMAP item 3 refactor)
        shell = SupervisionShell(
            self.registry, c.res_path,
            events_enabled=c.events, events_append=c.resume,
            watchdog=c.watchdog,
            watchdog_deadline_s=c.watchdog_deadline_s,
            watchdog_warmup_s=c.watchdog_warmup_s,
            watchdog_scale=c.watchdog_scale,
            watchdog_min_deadline_s=c.watchdog_min_deadline_s,
            watchdog_on_timeout=self._watchdog_emergency,
            sanitize=c.sanitize,
            step_fn=lambda: self.batch_counter,
            metrics_port=c.metrics_port,
            preempt_signal_nums=self._preempt_signal_nums,
            log=log)

        def _payload():
            # mirror the live handles the loop (and the recovery
            # wrapper) reads off the trainer
            self._watchdog = shell.watchdog
            self._sanitizer = shell.sanitizer
            self._preempt_guard = shell.guard
            self.metrics_port = shell.metrics_port
            return self._train_impl(log)

        def _expose_recorder(recorder):
            # set as soon as the recorder installs, so the flight record
            # of a run that fails later in SETUP is still dumpable
            self._events = recorder

        try:
            return shell.run(_payload, on_recorder=_expose_recorder)
        finally:
            self._watchdog = None
            self._sanitizer = None
            self._preempt_guard = None

    def _train_impl(self, log: Callable[[str], None]) -> Dict[str, float]:
        c = self.c
        from gan_deeplearning4j_tpu.telemetry import (
            GoodputTimer,
            write_run_manifest,
        )

        # goodput phase accounting covers the WHOLE run from here; the
        # manifest pins run id + config + software/topology so metrics
        # and bench records are attributable to an exact setup
        self.goodput = GoodputTimer()
        self.run_manifest = write_run_manifest(
            c.res_path, config=c, mesh=self._mesh,
            extra={"workload": self.w.name})
        run_id = self.run_manifest.get("run_id")
        if self._events is not None:
            self._events.run_id = run_id
        self.registry.run_id = run_id
        events.instant("train.start", step=self.batch_counter,
                       workload=self.w.name)
        with self.goodput.phase("data_wait"), \
                events.span("data.prepare"):
            train_csv, test_csv = self.w.ensure_data(c.res_path)
            # resilient ingest: the CSV decode retries transient I/O
            # errors, and (with a quarantine budget) tolerates corrupt
            # records row-by-row instead of dying on the first one
            reader = CSVRecordReader()
            if c.data_retries:
                reader = RetryingReader(
                    reader, retries=c.data_retries,
                    backoff_s=c.data_retry_backoff_s,
                    health=self.data_health, seed=c.seed)
            iter_kw = dict(reader=reader)
            if self._quarantine is not None:
                iter_kw["quarantine"] = self._quarantine
            iter_train = RecordReaderDataSetIterator(
                train_csv, c.batch_size, c.label_index, c.num_classes,
                **iter_kw)
            iter_test = RecordReaderDataSetIterator(
                test_csv, c.batch_size_pred, c.label_index, c.num_classes,
                **iter_kw)
            self._train_iter = iter_train
            self._iter_state_consumed = None
        with self.goodput.phase("checkpoint"), \
                events.span("train.resume"):
            self._maybe_resume(iter_train)

        ones = self._ones
        y_dis = jnp.concatenate([ones + self.soften_real, self.soften_fake])

        fused_state = None
        start_counter = self.batch_counter
        self._steady_t0 = None
        self._steady_start_step = start_counter
        run_t0 = time.perf_counter()
        # Two-tier residency: f32 residency (fastest steady state) when
        # the table fits; u8 residency (1/4 HBM, per-step exact decode —
        # a capacity tier) when only the encoded table fits; streaming
        # otherwise.  The codec rides the stream chunks in the last tier.
        # The lossless scan (one blocked pass over the table) only runs
        # when its result can matter — i.e. NOT when f32 already fits.
        resident_f32 = self._fused_enabled and self._resident_data_ok(
            iter_train)
        table_codec = None
        if (self._fused_enabled and not resident_f32 and c.use_data_codec
                and getattr(iter_train, "preprocessor", None) is None):
            from gan_deeplearning4j_tpu.data import codec as codec_lib

            self._codec_lib = codec_lib
            if codec_lib.u8x100_lossless(iter_train.features):
                table_codec = "u8x100"
        resident = resident_f32 or (
            table_codec is not None
            and self._resident_data_ok(iter_train, codec=table_codec))
        self._table_codec = table_codec if resident else None
        if self._fused_enabled:
            if self._fused_step is None:
                kw = dict(
                    z_size=c.z_size, num_features=c.num_features,
                    mesh=self._mesh, ema_decay=c.ema_decay,
                    telemetry=c.telemetry,
                )
                graphs = (self.dis, self.gen, self.gan, self.classifier)
                maps = (self.w.dis_to_gan, self.w.gan_to_gen,
                        self.w.dis_to_classifier)
                # resident: the program slices the (possibly u8-encoded)
                # table; streaming: per-batch single steps ship f32 (the
                # chunked path below carries the codec instead)
                self._fused_step = self._fused_lib.make_protocol_step(
                    *graphs, *maps, data_on_device=resident,
                    data_codec=self._table_codec, **kw)
                # the streaming transport codec is the SAME eligibility
                # decision (fused + no preprocessor + lossless table),
                # applied to the chunk transfers instead of the table
                self._stream_codec = None if resident else table_codec
                byte_cap = None if resident else c.stream_chunk_bytes
                # adaptive epoch-in-chunk tier (dedup): when one uncapped
                # chunk covers >= a full pass of the (deterministic)
                # iterator AND the distinct-row tables fit the chunk
                # budget, ship the tables once and stream only the
                # per-chunk row-index schedule — re-shipping each row
                # once per occurrence is pure waste on a bandwidth-bound
                # link (the r4 e2e_stream driver capture's bound).
                self._stream_dedup = False
                if not resident and c.stream_dedup is not False:
                    # UNCAPPED_STREAM: streaming-path semantics (resume-
                    # step chunk alignment stays active) without a byte
                    # bound — in dedup mode only the index schedule
                    # streams per chunk, so the per-chunk transfer budget
                    # doesn't constrain K
                    UNCAPPED_STREAM = 1 << 62
                    k_nocap = self._resolve_steps_per_call(
                        byte_cap=UNCAPPED_STREAM, codec=self._stream_codec)
                    n_full = iter_train.num_examples() // c.batch_size
                    fb = 1 if self._stream_codec == "u8x100" else 4
                    table_bytes = n_full * c.batch_size * (
                        fb * c.num_features + 4 * c.num_classes)
                    if (0 < n_full <= k_nocap and k_nocap > 1
                            and table_bytes <= c.stream_chunk_bytes):
                        self._stream_dedup = True
                        byte_cap = UNCAPPED_STREAM
                self._steps_per_call = self._resolve_steps_per_call(
                    byte_cap=byte_cap, codec=self._stream_codec)
                if self._steps_per_call <= 1:
                    # chunking never engages: batches ship f32 through the
                    # per-batch PrefetchIterator — the codec flag must not
                    # claim otherwise (it keys benchmarks' records)
                    self._stream_codec = None
                    self._stream_dedup = False
                if self._steps_per_call > 1:
                    # the multi-step program always slices on-device: on
                    # the resident path from the whole table, on the
                    # streaming path from the current K-batch chunk (the
                    # slicing arithmetic is identical — ``it % K`` walks
                    # a chunk exactly when steps are chunk-aligned, which
                    # _resolve_steps_per_call guarantees).  Streamed u8
                    # chunks decode ONCE per chunk (amortized); a
                    # u8-resident table decodes per sliced batch (keeps
                    # the 1/4-HBM footprint for its whole life).
                    multi_codec = (self._table_codec if resident
                                   else self._stream_codec)
                    self._fused_multi = self._fused_lib.make_protocol_step(
                        *graphs, *maps, data_on_device=True,
                        steps_per_call=self._steps_per_call,
                        carry_dedup=c.carry_dedup,
                        data_codec=multi_codec,
                        codec_chunk_decode=(multi_codec is not None
                                            and not resident),
                        chunk_indexed=self._stream_dedup, **kw)
            # loop-invariant step arguments, device-resident once —
            # COMMITTED like the state below: under a mesh, uncommitted
            # single-device invariants (the key, the soften vectors)
            # would be re-broadcast device-to-device on EVERY dispatch
            # (found by the --sanitize transfer guard; tiny arrays, but
            # a per-dispatch transfer on the hot path all the same)
            self._fused_invariants = jax.device_put(
                (self._z_base, self._fused_rng,
                 ones + self.soften_real, self.soften_fake, ones),
                mesh_lib.replicated(self._mesh) if self._mesh is not None
                else jax.sharding.SingleDeviceSharding(jax.devices()[0]))
            fused_state = self._fused_lib.state_from_graphs(
                self.dis, self.gen, self.gan, self.classifier,
                start_step=self.batch_counter, ema=c.ema_decay > 0)
            # Commit the state to a concrete sharding up front.  The
            # program's outputs are committed arrays, so an uncommitted
            # initial state would give call 1 a different argument-
            # sharding signature than every later call — jit then
            # RECOMPILES the whole program on step/chunk 2 (measured:
            # ~16s, landing inside the steady-throughput window).
            fused_state = jax.device_put(
                fused_state,
                mesh_lib.replicated(self._mesh) if self._mesh is not None
                else jax.sharding.SingleDeviceSharding(jax.devices()[0]))

        # artifact materialization runs on a background worker for the
        # whole loop; the with-block guarantees every dump is on disk (or
        # its error raised) before the end-of-run models/metrics below
        self._dumper = AsyncArtifactWriter(synchronous=not c.async_dumps)
        with self._dumper:
            if resident:
                # the whole training table lives in HBM; the fused step
                # slices its own batches from the device counter — no
                # per-step host->device traffic and no host data loop at
                # all.  Under a mesh, place it replicated ONCE (an
                # uncommitted single-device array would be re-broadcast by
                # jit every step).
                feats = iter_train.features
                if self._table_codec:
                    # u8 residency: 1/4 the HBM and 1/4 the upload bytes;
                    # the program dequantizes each sliced batch bitwise
                    feats = self._codec_lib.u8x100_encode(feats)
                if self._mesh is not None:
                    rep = mesh_lib.replicated(self._mesh)
                    dev_features = jax.device_put(feats, rep)
                    dev_labels = jax.device_put(iter_train.labels, rep)
                else:
                    dev_features = jnp.asarray(feats)
                    dev_labels = jnp.asarray(iter_train.labels)
                self._resident_loop(dev_features, dev_labels, iter_test,
                                    fused_state, log)
            elif self._fused_multi is not None:
                # Chunked streaming: the worker thread assembles K full
                # batches into ONE array pair and starts a single
                # host->device transfer; the device advances all K steps
                # in one multi-step dispatch, slicing its own batches from
                # the chunk.  Chunk k+1's transfer overlaps chunk k's
                # compute (double-buffered) — the per-step tunnel round
                # trip that bounded the r3 streaming path at ~1/latency
                # is paid once per chunk instead of once per step.
                from gan_deeplearning4j_tpu.data.prefetch import (
                    ChunkPrefetchIterator,
                )

                if self._mesh is not None:
                    # the data_on_device program reads the chunk
                    # replicated (each replica slices its own shard)
                    chunk_sh = mesh_lib.replicated(self._mesh)
                else:
                    chunk_sh = jax.sharding.SingleDeviceSharding(
                        jax.devices()[0])
                # depth 1 = three chunks in flight (training, queued,
                # staging) — full transfer/compute overlap at the least
                # HBM footprint
                encode = (self._codec_lib.u8x100_encode
                          if self._stream_codec == "u8x100" else None)
                chunks = ChunkPrefetchIterator(
                    self._wrap_stream(iter_train), self._steps_per_call,
                    c.batch_size,
                    prefetch_depth=1, sharding=chunk_sh,
                    encode_features=encode, dedup=self._stream_dedup)
                self._data_stream = chunks
                try:
                    self._chunked_stream_loop(chunks, iter_test,
                                              fused_state, log)
                finally:
                    self._data_stream = None
                    chunks.close()
            else:
                # Background prefetch (SURVEY.md §3.2 hot-loop note: the
                # reference decodes CSV on the training thread every
                # iteration — here a worker thread decodes AND starts the
                # host->device transfer for batch k+depth while the device
                # computes batch k).  The fused path transfers straight to
                # its batch sharding; other paths keep host arrays
                # (DataParallelGraph owns their placement).
                from gan_deeplearning4j_tpu.data.prefetch import (
                    PrefetchIterator,
                )

                sharding = None
                if self._fused_step is not None:
                    sharding = self._batch_sharding
                    if sharding is None:
                        sharding = jax.sharding.SingleDeviceSharding(
                            jax.devices()[0])
                prefetch = PrefetchIterator(
                    self._wrap_stream(iter_train), prefetch_depth=2,
                    sharding=sharding,
                    loop=True, min_rows=c.batch_size)
                self._data_stream = prefetch
                try:
                    self._train_loop(prefetch, iter_test, fused_state, ones,
                                     y_dis, log)
                finally:
                    self._data_stream = None
                    prefetch.close()

        if self._fused_step is not None and self._final_state is not None:
            self._fused_lib.state_to_graphs(
                self._final_state, self.dis, self.gen, self.gan,
                self.classifier)
            if self.batch_counter > start_counter:
                d_loss, g_loss, c_loss = self._final_losses
                self.dis.score, self.gan.score = d_loss, g_loss
                self.classifier.score = c_loss

        # steady-state throughput: wall clock from the post-compile mark to
        # the last step's completion (async per-step timestamps measure
        # dispatch, not the device; device_fence documents why
        # block_until_ready is not enough here)
        if self._final_losses is not None:
            with self.goodput.phase("readback"):
                device_fence(self._final_losses)
        steady = None
        steps_timed = self.batch_counter - self._steady_start_step
        if self._steady_t0 is not None and steps_timed > 0:
            steady = steps_timed * c.batch_size / (
                time.perf_counter() - self._steady_t0)
        elif self.batch_counter > start_counter:
            # the whole run fit in the first (compile-paying) chunk: the
            # only honest rate is whole-run wall including the compile
            steady = ((self.batch_counter - start_counter) * c.batch_size
                      / (time.perf_counter() - run_t0))

        # end-of-run model zips, exactly the reference's four files (:529-533)
        name = c.dataset_name
        with self.goodput.phase("checkpoint"):
            serialization.write_model(
                self.dis, os.path.join(c.res_path, f"{name}_dis_model.zip"))
            serialization.write_model(
                self.gan, os.path.join(c.res_path, f"{name}_gan_model.zip"))
            serialization.write_model(
                self.gen, os.path.join(c.res_path, f"{name}_gen_model.zip"))
            serialization.write_model(
                self.classifier,
                os.path.join(
                    c.res_path,
                    f"{name}_{self.w.classifier_model_name}_model.zip"))
            # exit barrier: an async checkpointer's queued save must be
            # durable before the run reports success (the wait lands in
            # the checkpoint phase — it IS checkpoint time)
            ck_wait = getattr(self.checkpointer, "wait", None)
            if ck_wait is not None:
                ck_wait()
        # drain + close the logger FIRST (the final flush's readback of
        # up to flush_every stacked records is the run's last big device
        # wait and must be attributed), THEN close the goodput ledger
        # and write its record — the closed logger materializes it
        # synchronously, so nothing unattributed remains but that one
        # host-side JSON write.  close() also joins the async worker:
        # records() etc. keep working, just synchronously, and a worker
        # thread never outlives its trainer's run.
        with self.goodput.phase("readback"):
            self.metrics.flush(wait=True)
            self.metrics.close()
        # multi-process: phase means across hosts, recorded by process 0
        # only (parallel/multihost.py)
        from gan_deeplearning4j_tpu.parallel import multihost

        goodput = multihost.aggregate_goodput(self.goodput.report())
        run_id = (self.run_manifest or {}).get("run_id")
        if jax.process_index() == 0:
            self.metrics.log_record(
                {"goodput": goodput, "run_id": run_id})
            self.metrics.flush()
        # trips materialized only by the final flush still get their
        # action — including a rollback of the run's last window
        self._poll_nan_alarm()
        self._poll_divergence()
        self._maybe_rollback()
        events.instant("train.end", step=self.batch_counter)
        return {
            "steps": self.batch_counter,
            "examples_per_sec": (
                steady if steady is not None else self.metrics.throughput()),
            "examples_per_sec_includes_compile": (
                self._steady_t0 is None or steps_timed <= 0),
            "d_loss": float(self.dis.score),
            "g_loss": float(self.gan.score),
            "run_id": run_id,
            "goodput": goodput,
        }

    def _z(self, i: int, which: int) -> jax.Array:
        """Counter-based training latent: z ~ U[-1,1]^z for step ``i``
        (``which`` 0 = D-step draw, 1 = G-step draw) — the same stream the
        fused step derives on-device from the step index."""
        key = jax.random.fold_in(self._z_base, 2 * i + which)
        return jax.random.uniform(
            key, (self.c.batch_size, self.c.z_size), minval=-1.0, maxval=1.0)

    def _resolve_steps_per_call(self, byte_cap: Optional[int] = None,
                                codec: Optional[str] = None) -> int:
        """Steps-per-dispatch: the largest K <= cap dividing every
        artifact cadence AND the iteration count, so chunks never cross a
        dump/checkpoint boundary and the run length is an exact number of
        chunks — the resident loop then needs ONLY the multi-step program
        (a remainder would force a second XLA compile mid-run, which would
        land inside the steady-throughput window).  An explicit config
        value acts as the cap and is reduced (with a warning) if it does
        not divide the cadences — a non-dividing K would silently send
        every partial chunk down the latency-bound single-step path.

        ``byte_cap``: on the streaming path, additionally bound K so one
        chunk's feature+label bytes fit the transfer-buffer budget (two
        chunks are in flight — the one training and the one staging).
        0/None-cap semantics: ``byte_cap=0`` disables chunking entirely
        (K=1); ``None`` applies no byte bound (the resident path)."""
        import math

        from gan_deeplearning4j_tpu.train.fused_step import MAX_STEPS_PER_CALL

        c = self.c
        cap = (MAX_STEPS_PER_CALL if c.steps_per_call is None
               else max(1, c.steps_per_call))
        byte_capped = False
        if byte_cap is not None:
            # per-step device footprint of one chunk: with the codec the
            # u8 transfer copy AND the chunk-decoded f32 working copy are
            # both live during the scan (5 bytes/feature); plain f32 is 4
            feat_bytes = 5 if codec == "u8x100" else 4
            step_bytes = c.batch_size * (
                feat_bytes * c.num_features + 4 * c.num_classes)
            byte_steps = max(1, byte_cap // step_bytes)
            byte_capped = byte_steps < cap
            cap = min(cap, byte_steps)
        g = c.num_iterations
        for cad in (c.print_every, c.save_every, c.checkpoint_every):
            if cad:
                g = math.gcd(g, cad)
        if byte_cap is not None and self.batch_counter:
            # STREAMING chunks slice batch ``it % K``, so a resumed run's
            # start step must be a multiple of K or slicing
            # desynchronizes from the step counter — and the checkpoint
            # may come from a run with DIFFERENT cadences, so alignment
            # with this config's cadences alone is not enough.  (The
            # resident program slices ``it % table_batches`` — correct at
            # any start step, no constraint there.)
            g = math.gcd(g, self.batch_counter)
        if g <= 0:
            return 1
        k = max(d for d in range(1, min(cap, g) + 1) if g % d == 0)
        if c.steps_per_call is not None and k != c.steps_per_call:
            import logging

            logging.getLogger(__name__).warning(
                "steps_per_call=%d reduced to %d (%s)", c.steps_per_call, k,
                "chunk transfer-byte budget stream_chunk_bytes"
                if byte_capped and k == cap else
                "must divide the artifact cadences and the resume step "
                "so chunks stay aligned")
        return k

    def _wrap_stream(self, iter_train):
        """Resilience wrappers for the STREAMING consumption paths
        (data/resilient.py): transient next()/reset() errors retry
        with backoff (RetryingSource), and — with a quarantine budget —
        every emitted batch passes the per-record shape/finite contract
        (ValidatingSource), bad rows skipped and charged.  The resident
        path never goes through here: its table was already validated
        at ingest and it performs no runtime reads to retry.  The
        wrappers delegate ``state``/``features``/... so the prefetch
        state capture and the dedup verification see through them."""
        src = iter_train
        c = self.c
        if c.data_retries:
            src = RetryingSource(src, retries=c.data_retries,
                                 backoff_s=c.data_retry_backoff_s,
                                 health=self.data_health, seed=c.seed)
        if self._quarantine is not None:
            src = ValidatingSource(src, self._quarantine,
                                   num_features=c.num_features,
                                   name=f"{c.dataset_name}:train-stream")
        return src

    def _resident_data_ok(self, iter_train, codec=None) -> bool:
        """Decide the device-resident data path (config override, else
        auto: the table must hold at least one full batch and fit the
        byte budget — at u8 size when the residency codec applies, so
        lossless-contract datasets up to 4x the budget stay resident)."""
        c = self.c
        if iter_train.num_examples() < c.batch_size:
            return False
        if getattr(iter_train, "preprocessor", None) is not None:
            # the resident path reads the raw backing table; a per-batch
            # preprocessor would be silently skipped there
            if c.data_on_device:
                import logging

                logging.getLogger(__name__).warning(
                    "data_on_device=True overridden: the iterator has a "
                    "preprocessor, which the resident path cannot apply")
            return False
        if c.data_on_device is not None:
            return bool(c.data_on_device)
        feat_bytes = iter_train.features.nbytes
        if codec == "u8x100":
            feat_bytes //= 4  # stored as u8 codes in HBM
        size = feat_bytes + iter_train.labels.nbytes
        return size <= c.data_on_device_max_bytes

    def _next_chunk(self) -> int:
        """Steps until the next artifact/checkpoint boundary or the end of
        the run, capped at steps_per_call."""
        c = self.c
        run = min(self._steps_per_call,
                  c.num_iterations - self.batch_counter)
        for cad in (c.print_every, c.save_every, c.checkpoint_every):
            if cad:
                run = min(run, cad - self.batch_counter % cad)
        return run

    def _unpack(self, out):
        """Split a fused-step result into (state, losses, telemetry) —
        telemetry is None unless the config enables it (the program then
        returns ((losses), tel) in the second slot, fused_step.py)."""
        state, rest = out
        if self.c.telemetry:
            losses, tel = rest
            return state, losses, tel
        return state, rest, None

    def _dispatch_guard(self):
        """Sanitizer context for a fused hot-loop dispatch
        (config.sanitize).  Always a sentinel WATCH region — compiles
        landing outside the watched dispatches (the first eval-cadence
        inference program, a reader) are recorded as benign, so only
        the hot path's own cache promise is enforced.  Plus
        jax.transfer_guard("disallow") once the steady window has
        begun — the warmup dispatch stays unguarded (compile-time
        constant staging may legitimately transfer); everything the
        steady loop dispatches is device-resident by construction, so
        any implicit transfer there is a regression."""
        from contextlib import ExitStack, nullcontext

        if self._sanitizer is None:
            return nullcontext()
        stack = ExitStack()
        stack.enter_context(self._sanitizer.watch())
        if self._steady_t0 is not None:
            from gan_deeplearning4j_tpu.analysis.sanitizers import (
                no_implicit_transfers,
            )

            stack.enter_context(no_implicit_transfers())
        return stack

    def _phase(self, name: str):
        """Goodput phase context, or a no-op outside train() (tests and
        notebooks may drive the dump/bookkeeping methods directly).
        With the watchdog armed, every phase doubles as a heartbeat
        region: beat on entry and exit, and the phase name is what a
        timeout reports as "in flight" — the goodput phases are exactly
        the trainer's blocking regions (data wait, dispatch, readback,
        checkpoint barrier, eval)."""
        from contextlib import nullcontext

        ctx = (self.goodput.phase(name) if self.goodput is not None
               else nullcontext())
        wd = self._watchdog
        if wd is None:
            return ctx
        from contextlib import ExitStack

        stack = ExitStack()
        stack.enter_context(wd.region(name))
        stack.enter_context(ctx)
        return stack

    def _resident_loop(self, features, labels, iter_test, fused_state,
                       log) -> None:
        """Hot loop of the device-resident data path: batch slicing,
        latent draws and the step counter all live on device, and (when
        steps_per_call > 1) ONE dispatch advances a whole chunk of steps
        — per-step dispatch latency is the throughput bound this removes."""
        self._final_state, self._final_losses = fused_state, None
        K = self._steps_per_call
        while self.batch_counter < self.c.num_iterations:
            run = self._next_chunk()
            if K > 1 and run == K:
                # whole-chunk bookkeeping: the (K,) loss arrays stay
                # stacked on device — per-step slicing would cost 3 tiny
                # dispatches per step plus 3 scalar readbacks per step at
                # metrics flush, host-side work that scales with steps and
                # (on a tunneled link) dominates no matter how large K is
                with self._phase("dispatch"), \
                        events.span("train.chunk",
                                    step=self.batch_counter, n=run), \
                        self._dispatch_guard():
                    out = self._fused_multi(
                        fused_state, features, labels,
                        *self._fused_invariants)
                fused_state, (d, g, cl), tel = self._unpack(out)
                self._final_state = fused_state
                self._final_losses = (d[-1], g[-1], cl[-1])
                self._mark_steady(self._final_losses, steps=run)
                self._chunk_bookkeeping(iter_test, d, g, cl, run, log, tel)
            else:
                per_step = []
                for _ in range(run):
                    with self._phase("dispatch"), self._dispatch_guard():
                        out = self._fused_step(
                            fused_state, features, labels,
                            *self._fused_invariants)
                    fused_state, losses, tel = self._unpack(out)
                    per_step.append((losses, tel))
                self._final_state = fused_state
                self._mark_steady(per_step[-1][0], steps=len(per_step))
                for (d_loss, g_loss, c_loss), tel in per_step:
                    self._final_losses = (d_loss, g_loss, c_loss)
                    self._step_bookkeeping(iter_test, d_loss, g_loss,
                                           c_loss, log, tel)

    def _chunked_stream_loop(self, chunks, iter_test, fused_state,
                             log) -> None:
        """Streaming counterpart of _resident_loop: ONE host->device
        transfer and ONE multi-step dispatch per K-step chunk.  The
        worker thread stages chunk k+1 while the device trains chunk k,
        so steady-state throughput approaches the resident path's for any
        dataset size — the 2 GiB residency budget no longer gates it."""
        K = self._steps_per_call
        self._final_state, self._final_losses = fused_state, None
        while self.batch_counter < self.c.num_iterations:
            run = self._next_chunk()
            if run != K:
                # _resolve_steps_per_call aligns K with every cadence,
                # the run length AND the resume step, so a partial chunk
                # cannot occur; a silent mismatch would desynchronize the
                # step counter from the chunk slicing
                raise RuntimeError(
                    f"chunk misalignment: next boundary in {run} steps "
                    f"but chunk size is {K}")
            try:
                # plain: (features, labels); dedup: (feature table,
                # label table, row-index schedule) — the chunk_indexed
                # program takes the extra argument in this position
                with self._phase("data_wait"):
                    chunk = next(chunks)
            except StopIteration:  # dataset empty even after reset
                break
            with self._phase("dispatch"), \
                    events.span("train.chunk", step=self.batch_counter,
                                n=run), \
                    self._dispatch_guard():
                out = self._fused_multi(
                    fused_state, *chunk, *self._fused_invariants)
            fused_state, (d, g, cl), tel = self._unpack(out)
            self._final_state = fused_state
            self._final_losses = (d[-1], g[-1], cl[-1])
            self._mark_steady(self._final_losses, steps=run)
            self._chunk_bookkeeping(iter_test, d, g, cl, run, log, tel)

    def _mark_steady(self, loss, steps: int = 1) -> None:
        """After the FIRST step/chunk of a run (the one that pays the XLA
        compile), fence once and start the steady-state wall clock —
        per-step host timestamps in an async-dispatch loop measure
        dispatch, not device time.  ``steps``: how many protocol steps the
        fenced dispatch advanced (they are excluded from the steady
        window — fencing mid-chunk would credit already-finished steps to
        the window and overstate throughput)."""
        if self._steady_t0 is None:
            # goodput: this first fence waits out the XLA compile plus
            # the first chunk's compute — the run's one big readback
            with self._phase("readback"), \
                    events.span("train.compile_fence",
                                step=self.batch_counter):
                device_fence(loss)
            self._steady_t0 = time.perf_counter()
            self._steady_start_step = self.batch_counter + steps
            if self._sanitizer is not None:
                # the compile-paying first step/chunk just fenced: every
                # compile from here on is a recompile
                self._sanitizer.arm()

    def _train_loop(self, prefetch, iter_test, fused_state, ones, y_dis,
                    log) -> None:
        c = self.c
        B = c.batch_size
        self._final_state, self._final_losses = fused_state, None
        while self.batch_counter < c.num_iterations:
            try:
                with self._phase("data_wait"):
                    features, labels = next(prefetch)
            except StopIteration:   # dataset empty even after reset
                break
            if features.shape[0] < B:  # partial epoch tail: wrap like :524
                continue
            real = jnp.asarray(features)
            labels = jnp.asarray(labels)

            tel = None
            if self._fused_step is not None:
                # the whole iteration — D-step, syncs, G-step, classifier,
                # latent draws, step-counter bump — is one donated-state
                # XLA program; the only per-step host work is this dispatch
                with self._phase("dispatch"), self._dispatch_guard():
                    out = self._fused_step(
                        fused_state, real, labels, *self._fused_invariants)
                fused_state, (d_loss, g_loss, c_loss), tel = \
                    self._unpack(out)
                self._final_state = fused_state
                self._final_losses = (d_loss, g_loss, c_loss)
                self._mark_steady(d_loss)
            else:
                with self._phase("dispatch"):
                    # (1) D-step on [real(1+eps), fake(0+eps)]
                    z = self._z(self.batch_counter, 0)
                    fake = self.gen.output(z)[0].reshape(B, c.num_features)
                    d_loss = self._fit_dis(
                        jnp.concatenate([real, fake]), y_dis)

                    # (2) dis -> gan frozen tail (weights + BN stats)
                    sync_params(self.gan, self.dis, self.w.dis_to_gan)

                    # (3) G-step: fool the frozen discriminator
                    z = self._z(self.batch_counter, 1)
                    g_loss = self._fit_gan(z, ones)

                    # (4) gan generator -> standalone gen
                    sync_params(self.gen, self.gan, self.w.gan_to_gen)

                    # (5) classifier: dis features, fit on the real
                    # labeled batch
                    sync_params(self.classifier, self.dis,
                                self.w.dis_to_classifier)
                    c_loss = self._fit_clf(real, labels)
                self._final_losses = (d_loss, g_loss, c_loss)
                self._mark_steady(c_loss)

            self._step_bookkeeping(iter_test, d_loss, g_loss, c_loss, log,
                                   tel)

    def _wd_region(self, name: str):
        """Watchdog heartbeat region (no goodput phase) — for blocking
        regions that are not phases, e.g. the multihost consensus
        collectives."""
        if self._watchdog is not None:
            return self._watchdog.region(name)
        from contextlib import nullcontext

        return nullcontext()

    def _chunk_bookkeeping(self, iter_test, d, g, cl, n, log,
                           tel=None) -> None:
        """Bookkeeping for one multi-step dispatch: ONE chunk metrics
        record holding the stacked (n,) loss arrays, then cadence
        triggers — which by construction (_resolve_steps_per_call /
        _next_chunk) can only fire at the chunk end.  ``tel``: the
        telemetry block of stacked (n,) device arrays, logged as extra
        columns of the same record (no readback here — the async worker
        materializes them with the losses)."""
        c = self.c
        start = self.batch_counter
        self.batch_counter += n
        if self._watchdog is not None:
            self._watchdog.beat(step=self.batch_counter)
        self._stash_iter_state()
        # examples=0: on the async resident path the host free-runs ahead
        # of the device, so inter-chunk wall time measures dispatch, not
        # compute — a per-step examples_per_sec from it would be fiction.
        # The run-level number comes from the fenced steady window.
        self.metrics.log_chunk(
            start + 1, n, 0,
            {"d_loss": d, "g_loss": g, "classifier_loss": cl,
             **(tel or {})})
        for s in range(start - start % 100 + 100, self.batch_counter + 1,
                       100):
            log(f"Completed Batch {s}!")
        self._boundary_bookkeeping(iter_test)

    def _step_bookkeeping(self, iter_test, d_loss, g_loss, c_loss, log,
                          tel=None) -> None:
        c = self.c
        self.batch_counter += 1
        if self._watchdog is not None:
            self._watchdog.beat(step=self.batch_counter)
        self._stash_iter_state()
        self.metrics.log_step(
            self.batch_counter, examples=c.batch_size,
            d_loss=d_loss, g_loss=g_loss, classifier_loss=c_loss,
            **(tel or {}),
        )
        if self.batch_counter % 100 == 0:
            log(f"Completed Batch {self.batch_counter}!")
        self._boundary_bookkeeping(iter_test)

    def _stash_iter_state(self) -> None:
        """Snapshot the stream's consumed-position at this step/chunk
        boundary — the one moment it is guaranteed aligned with
        ``batch_counter``.  Checkpoints (periodic, emergency, watchdog)
        read the stash, never the live stream: between boundaries the
        training thread may have consumed the NEXT batch already, and
        stamping that position against the current step would shift
        the resumed run's batch sequence by one."""
        stream = self._data_stream
        if stream is not None:
            st = stream.state()
            if st is not None:
                self._iter_state_consumed = st

    def _boundary_bookkeeping(self, iter_test) -> None:
        """Artifact/checkpoint cadence triggers at the current counter
        (shared by the per-step and chunk paths)."""
        c = self.c
        # device-loss injection seam (testing/chaos.py ShrinkWorld):
        # fires BEFORE this boundary's checkpoint, so the resume comes
        # from an earlier save — exactly what a real mid-step loss
        # leaves behind
        _chaos_step(self.batch_counter)
        if self._fused_step is not None and (
            self.batch_counter % c.print_every == 0
            or self.batch_counter % c.save_every == 0
            or (c.checkpoint_every
                and self.batch_counter % c.checkpoint_every == 0)):
            # artifact/checkpoint points read through the graph objects
            self._fused_lib.state_to_graphs(
                self._final_state, self.dis, self.gen, self.gan,
                self.classifier)

        # health polls FIRST: a tripped alarm with an abort/rollback
        # action must unwind BEFORE this boundary checkpoints the
        # known-bad state it just detected (detection granularity is
        # the metrics flush cadence, so this only narrows the window —
        # the rollback resume bound closes it for good)
        self._poll_nan_alarm()
        self._poll_divergence()
        self._maybe_rollback()
        if self.batch_counter % c.print_every == 0:
            with self._phase("eval"), \
                    events.span("eval.grid", step=self.batch_counter):
                self._dump_grid()
        if self.batch_counter % c.save_every == 0:
            with self._phase("eval"), \
                    events.span("eval.predictions",
                                step=self.batch_counter):
                self._dump_predictions(iter_test)
        if c.checkpoint_every:
            with self._phase("checkpoint"):
                self._maybe_checkpoint()
        self._maybe_preempt()

    def _poll_nan_alarm(self) -> None:
        """Apply the configured nan_alarm action once the async worker
        has observed a bad record.  Detection granularity is the metrics
        flush cadence (flush_every steps, or one chunk on the chunked
        paths) — the hot path never reads telemetry back, and no flush
        is forced here: a per-poll flush would degrade the logger to
        one-record batches, re-paying the per-step readback cost the
        batching exists to amortize."""
        alarm = self._nan_alarm
        if alarm is None or self._nan_handled or not alarm.tripped:
            return
        self._nan_handled = True
        run_id = (self.run_manifest or {}).get("run_id", "?")
        msg = (f"NaN alarm: first non-finite telemetry at step "
               f"{alarm.step} (run {run_id})")
        events.instant("alarm.nan", step=alarm.step,
                       action=self.c.nan_alarm)
        if self.c.nan_alarm == "abort":
            from gan_deeplearning4j_tpu.telemetry import NanAlarmError

            # the abort is FATAL in the recovery wrapper — this dump is
            # the timeline the post-mortem gets
            events.dump_flight_record(self.c.res_path, "nan_alarm",
                                      extra={"step": alarm.step})
            raise NanAlarmError(msg)
        import logging

        logging.getLogger(__name__).warning("%s", msg)
        if self.c.nan_alarm == "snapshot":
            # forensic snapshot of the state as of the LAST dispatched
            # step — through the shared emergency-checkpoint mechanism
            # (one save path, manifest-verified like any checkpoint),
            # into its own directory so it never collides with the run's
            # resumable checkpoints
            snap_dir = os.path.join(self.c.res_path, "nan_snapshot")
            with self._phase("checkpoint"):
                self._emergency_checkpoint(directory=snap_dir, keep=1)
            # the snapshot carries the event timeline that led to it
            events.dump_flight_record(snap_dir, "nan_alarm",
                                      extra={"step": alarm.step})
        elif self.c.nan_alarm == "rollback":
            # the heal path: consumed by _maybe_rollback at this same
            # boundary (multi-host: after the fleet consensus).  The
            # params went non-finite AT alarm.step, so the restore must
            # land strictly before it.
            self._request_rollback(msg, alarm.step)

    def _request_rollback(self, reason: str, bad_step) -> None:
        """Record a rollback request for the next ``_maybe_rollback``
        poll.  When BOTH alarms trip in one detection window (the NaN
        alarm and the divergence sentinel can fire off the same flush),
        the EARLIER bad step wins — restoring inside the later alarm's
        window could land on a checkpoint the earlier alarm already
        condemned."""
        if bad_step is None:
            bad_step = self.batch_counter
        pending = self._rollback_pending
        if pending is not None and pending[1] <= bad_step:
            return  # the existing request already bounds tighter
        self._rollback_pending = (reason, bad_step)

    def _poll_divergence(self) -> None:
        """Apply the configured action once the divergence sentinel has
        tripped (same latched/poll discipline as the NaN alarm — the
        sentinel observes on the metrics worker thread, the loop reacts
        at its bookkeeping points).  The action vocabulary is shared
        with nan_alarm (warn when unset); abort raises DivergenceError,
        FATAL in the recovery wrapper (a deterministic replay
        re-diverges identically) — rollback is the action that heals."""
        sentinel = self._divergence
        if sentinel is None or self._div_handled or not sentinel.tripped:
            return
        self._div_handled = True
        action = self.c.nan_alarm or "warn"
        run_id = (self.run_manifest or {}).get("run_id", "?")
        msg = f"{sentinel.describe()} (run {run_id})"
        events.instant("alarm.divergence", step=sentinel.step,
                       key=sentinel.key, value=sentinel.value,
                       baseline=sentinel.baseline, action=action)
        if action == "abort":
            from gan_deeplearning4j_tpu.train.divergence import (
                DivergenceError,
            )

            events.dump_flight_record(
                self.c.res_path, "divergence",
                extra={"step": sentinel.step, "key": sentinel.key})
            raise DivergenceError(msg)
        import logging

        logging.getLogger(__name__).warning("%s", msg)
        if action == "snapshot":
            snap_dir = os.path.join(self.c.res_path,
                                    "divergence_snapshot")
            with self._phase("checkpoint"):
                self._emergency_checkpoint(directory=snap_dir, keep=1)
            events.dump_flight_record(
                snap_dir, "divergence",
                extra={"step": sentinel.step, "key": sentinel.key})
        elif action == "rollback":
            self._request_rollback(msg, sentinel.step)

    def _maybe_rollback(self) -> None:
        """Boundary poll of the rollback path (train/rollback.py).

        Multi-host: the ``agree_rollback`` allgather is entered by
        EVERY host at every boundary while a manager is armed — the
        same unconditional-collective discipline as ``_maybe_preempt``
        — so one host's alarm rolls the whole fleet back together and
        a partially-alarmed fleet can never strand itself inside a
        mismatched collective.  On agreement: charge the (progress-
        aware) budget, leave the timeline behind, and unwind through
        ``RollbackRequested`` — or ``RollbackError`` once the budget is
        exhausted (fatal in the recovery wrapper)."""
        mgr = self._rollback_mgr
        if mgr is None:
            return
        pending = self._rollback_pending
        if jax.process_count() > 1:
            from gan_deeplearning4j_tpu.parallel import multihost

            with self._wd_region("collective.agree_rollback"):
                any_trig, agreed, fleet_bad = multihost.agree_rollback(
                    pending is not None, self.batch_counter,
                    pending[1] if pending is not None else None)
        else:
            any_trig, agreed = pending is not None, self.batch_counter
            fleet_bad = pending[1] if pending is not None else None
        if not any_trig:
            return
        from gan_deeplearning4j_tpu.train.rollback import (
            RollbackError,
            RollbackRequested,
        )

        # EVERY host restores before the fleet-agreed (min) bad step —
        # per-host restore points would desync the SPMD state; with no
        # agreed bad step (defensive: cannot happen when any_trig came
        # from a real alarm) fall back to the boundary step
        bad_step = fleet_bad if fleet_bad is not None else agreed
        reason = (pending[0] if pending is not None
                  else "peer host rollback consensus")
        self._rollback_pending = None
        ok = mgr.request(self.batch_counter, reason, bad_step=bad_step)
        events.instant("rollback.request", step=self.batch_counter,
                       bad_step=bad_step, rollbacks=mgr.total,
                       attempts=mgr.attempts,
                       lr_scale=mgr.lr_scale, reason=reason)
        events.dump_flight_record(
            self.c.res_path, "rollback",
            extra={"step": self.batch_counter, "bad_step": bad_step,
                   "rollbacks": mgr.total, "reason": reason})
        if not ok:
            raise RollbackError(
                f"rollback budget exhausted ({mgr.attempts - 1}/"
                f"{mgr.max_rollbacks} at step {self.batch_counter} "
                f"without progress): {reason}")
        raise RollbackRequested(
            f"rollback #{mgr.total} at step {self.batch_counter}: "
            f"{reason}",
            step=self.batch_counter, rollbacks=mgr.total)

    def _watchdog_emergency(self) -> None:
        """Watchdog ``on_timeout`` action (runs on the watchdog's
        sacrificial thread, bounded join): best-effort emergency
        checkpoint of the state as of the last dispatched step.  On a
        DATA hang the device is idle and this commits a resume point at
        the exact stall step; on a device hang it blocks on the same
        hang and is abandoned by the watchdog — the restart then falls
        back to the last periodic checkpoint."""
        self._emergency_checkpoint()
