"""The three-graph GAN training protocol — the reference's mains as an engine.

Reproduces the loop of SURVEY.md §3.2 (dl4jGANComputerVision.java:387-527 /
dl4jGANInsurance.java:329-469) for any workload that supplies the four
graphs and their weight-sync maps:

  per iteration:
    1. D-step: fit dis on [real batch (labels 1+eps), generated batch
       (labels 0+eps)] — label-softening noise sampled ONCE before the
       loop and reused (reference quirk, :384-385)
    2. copy all dis weights + BN stats into the gan graph's frozen tail
    3. G-step: fit the stacked gan on z ~ U[-1,1]^z labeled "real"
    4. copy the gan graph's generator weights back into the standalone gen
    5. copy dis feature weights into the classifier, fit it on the real
       labeled batch
    6. every print_every: dump the latent-grid synthesis CSV (+ workload
       extras); every save_every: dump test-set prediction CSV
    7. wrap the data iterator on exhaustion (multi-epoch)

Differences from the reference, on purpose (documented, SURVEY.md §7):
  - every network optionally trains data-parallel over a Mesh
    (gradient-sync all-reduce or DL4J param-averaging fidelity mode)
    instead of Spark jobs with per-iteration RDD serialization
  - the D-step's two minibatches are fed as ONE concatenated batch; under
    ``dp_mode="param_averaging"`` with 2 replicas this is bitwise the
    reference's [real-partition, fake-partition] Spark job layout
  - periodic training-state checkpoints with resume (reference gap)
  - structured per-step metrics (D/G/classifier loss, examples/sec)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.checkpoint import TrainCheckpointer
from gan_deeplearning4j_tpu.data import (
    RecordReaderDataSetIterator,
    write_csv_matrix,
)
from gan_deeplearning4j_tpu.graph import serialization
from gan_deeplearning4j_tpu.parallel import DataParallelGraph, data_mesh
from gan_deeplearning4j_tpu.parallel import mesh as mesh_lib
from gan_deeplearning4j_tpu.runtime import prng
from gan_deeplearning4j_tpu.utils import MetricsLogger


@dataclasses.dataclass
class GANTrainerConfig:
    """The reference's constants block as a config
    (dl4jGANComputerVision.java:59-85; dl4jGANInsurance.java:58-84)."""

    dataset_name: str
    num_features: int
    label_index: int
    num_classes: int            # classifier label width (10 CV, 1 insurance)
    batch_size: int             # batchSizePerWorker
    batch_size_pred: int        # batchSizePred
    num_iterations: int
    num_gen_samples: int        # latent grid edge -> n^2 samples
    z_size: int = 2
    print_every: int = 100
    save_every: int = 100
    seed: int = prng.NUMBER_OF_THE_BEAST
    res_path: str = "outputs"   # a flag, not a hardcoded absolute path
    # -- distribution (replaces useGpu/Spark local[4]) --
    n_devices: Optional[int] = None   # None = all attached; 1 = no mesh
    dp_mode: str = "gradient_sync"
    averaging_frequency: int = 1
    fused: bool = True                # one-XLA-program protocol iteration
    # -- new capabilities over the reference --
    checkpoint_every: int = 0         # 0 = end-of-run models only
    checkpoint_keep: int = 3
    resume: bool = False
    metrics: bool = True


class Workload:
    """What a model family must supply (models/dcgan_mnist.py and
    models/mlpgan_insurance.py both do)."""

    name: str
    classifier_model_name: str  # "CV" / "insurance" in the final zip names

    def build_graphs(self) -> Dict[str, object]:
        raise NotImplementedError

    # weight-sync maps: lists of (dst_layer, src_layer, param_names)
    dis_to_gan: list
    gan_to_gen: list
    dis_to_classifier: list

    def ensure_data(self, res_path: str):
        """Return (train_csv, test_csv)."""
        raise NotImplementedError

    def grid_extra_dump(self, trainer: "GANTrainer", grid_out: np.ndarray,
                        step: int) -> None:
        """Workload-specific extra artifact at print_every (the insurance
        main dumps classifier predictions over the generated grid,
        dl4jGANInsurance.java:422-437)."""


def sync_params(dst, src, mapping) -> None:
    for dst_layer, src_layer, names in mapping:
        dst.set_layer_params(
            dst_layer, {n: src.get_param(src_layer, n) for n in names}
        )


class GANTrainer:
    def __init__(self, workload: Workload, config: GANTrainerConfig):
        self.w = workload
        self.c = config
        os.makedirs(config.res_path, exist_ok=True)

        graphs = workload.build_graphs()
        self.dis = graphs["dis"]
        self.gen = graphs["gen"]
        self.gan = graphs["gan"]
        self.classifier = graphs["classifier"]

        # Distribution: fit() through DataParallelGraph when a mesh is used;
        # gen stays local (it only ever runs inference on the driver).
        # The mesh size must divide every fitted batch (B and the D-step's
        # 2B), so auto-selection picks the largest divisor of B that fits
        # the attached devices (the reference's local[4] with batch 50 has
        # the same constraint, satisfied as 50 = 4*12+2 only because DL4J
        # pads partitions; we keep shards exact instead).
        if config.n_devices is None:
            avail = len(jax.devices())
            resolved = max(
                d for d in range(1, avail + 1) if config.batch_size % d == 0
            )
            if resolved < avail:
                import logging

                logging.getLogger(__name__).warning(
                    "batch_size %d is not divisible by the %d attached "
                    "devices; using a %d-device mesh (%d idle)",
                    config.batch_size, avail, resolved, avail - resolved)
            # don't mutate the caller's config object (a reused config would
            # silently inherit this host's resolution)
            config = dataclasses.replace(config, n_devices=resolved)
            self.c = config
        # Fused mode (default for gradient_sync): the whole protocol
        # iteration is ONE jitted/SPMD program (train/fused_step.py) —
        # cross-graph syncs are free aliasing, state buffers donated.
        # param_averaging keeps the unfused per-fit path (its job-level
        # broadcast/average semantics are inherently per-network).
        self._fused_step = None
        mesh = data_mesh(config.n_devices) if config.n_devices > 1 else None
        if config.fused and config.dp_mode == "gradient_sync":
            from gan_deeplearning4j_tpu.train import fused_step as fused

            self._fused_lib = fused
            self._fused_step = fused.make_protocol_step(
                self.dis, self.gen, self.gan, self.classifier,
                workload.dis_to_gan, workload.gan_to_gen,
                workload.dis_to_classifier,
                z_size=config.z_size, num_features=config.num_features,
                mesh=mesh,
            )
            self._batch_sharding = (
                mesh_lib.batch_sharding(mesh) if mesh is not None else None)
        elif config.n_devices == 1:
            self._fit_dis = self.dis.fit
            self._fit_gan = self.gan.fit
            self._fit_clf = self.classifier.fit
        else:
            kw = dict(mesh=mesh, mode=config.dp_mode,
                      averaging_frequency=config.averaging_frequency)
            self.spark_dis = DataParallelGraph(self.dis, **kw)
            self.spark_gan = DataParallelGraph(self.gan, **kw)
            self.spark_clf = DataParallelGraph(self.classifier, **kw)
            self._fit_dis = self.spark_dis.fit
            self._fit_gan = self.spark_gan.fit
            self._fit_clf = self.spark_clf.fit

        self.metrics = MetricsLogger(
            os.path.join(config.res_path, f"{config.dataset_name}_metrics.jsonl")
            if config.metrics else None
        )
        self.checkpointer = (
            TrainCheckpointer(
                os.path.join(config.res_path, "checkpoints"),
                keep=config.checkpoint_keep,
            )
            if config.checkpoint_every else None
        )

        # PRNG streams (seed 666 discipline; see runtime/prng.py)
        root = prng.root_key(config.seed)
        self._z_keys = prng.KeySequence(prng.stream(root, "train-z"))
        self._fused_rng = prng.stream(root, "fused-step")
        # label softening: sampled once, reused every iteration (reference
        # quirk — dl4jGANComputerVision.java:384-385)
        B = config.batch_size
        self.soften_real = 0.05 * jax.random.normal(
            prng.stream(root, "soften-real"), (B, 1), dtype=jnp.float32)
        self.soften_fake = 0.05 * jax.random.normal(
            prng.stream(root, "soften-fake"), (B, 1), dtype=jnp.float32)

        # latent evaluation grid: the cartesian product of linspace(-1,1,n)
        # per latent dim, row-major with the first dim outermost — reference
        # order for z_size=2 (:363-370); generalizes to any z_size (n^z
        # rows, so keep n small for z_size > 2)
        n = config.num_gen_samples
        grid = np.linspace(-1.0, 1.0, n, dtype=np.float32)
        self.z_grid = jnp.asarray(
            np.stack(
                np.meshgrid(*([grid] * config.z_size), indexing="ij"), axis=-1
            ).reshape(-1, config.z_size)
        )

        self.batch_counter = 0

    # -- artifact dumps ------------------------------------------------------

    def _dump_grid(self) -> None:
        out = self.gen.output(self.z_grid)[0]
        out = np.asarray(out).reshape(self.z_grid.shape[0], self.c.num_features)
        write_csv_matrix(
            os.path.join(self.c.res_path,
                         f"{self.c.dataset_name}_out_{self.batch_counter}.csv"),
            out,
        )
        self.w.grid_extra_dump(self, out, self.batch_counter)

    def _dump_predictions(self, iter_test: RecordReaderDataSetIterator) -> None:
        iter_test.reset()
        preds = []
        while iter_test.has_next():
            ds = iter_test.next()
            preds.append(np.asarray(
                self.classifier.output(jnp.asarray(ds.features))[0]))
        write_csv_matrix(
            os.path.join(
                self.c.res_path,
                f"{self.c.dataset_name}_test_predictions_{self.batch_counter}.csv"),
            np.vstack(preds),
        )

    # -- checkpointing -------------------------------------------------------

    def _graphs(self) -> Dict[str, object]:
        return {"dis": self.dis, "gen": self.gen, "gan": self.gan,
                "classifier": self.classifier}

    def _maybe_checkpoint(self) -> None:
        if self.checkpointer and self.batch_counter % self.c.checkpoint_every == 0:
            self.checkpointer.save(
                self.batch_counter, self._graphs(),
                extra={"soften_real": self.soften_real,
                       "soften_fake": self.soften_fake,
                       "z_key": jax.random.key_data(self._z_keys._key)},
            )

    def _maybe_resume(self, iter_train: RecordReaderDataSetIterator) -> None:
        if not (self.c.resume and self.checkpointer
                and self.checkpointer.latest_step() is not None):
            return
        step, extra = self.checkpointer.restore(self._graphs())
        self.batch_counter = step
        self.soften_real = jnp.asarray(extra["soften_real"])
        self.soften_fake = jnp.asarray(extra["soften_fake"])
        self._z_keys._key = jax.random.wrap_key_data(jnp.asarray(extra["z_key"]))
        # Fast-forward the data iterator (views, cheap), replaying the
        # training loop's exact consumption pattern: partial epoch tails are
        # consumed-and-skipped WITHOUT counting as a step, and exhaustion
        # wraps (mirrors train() so a resumed run sees identical batches).
        steps_done = 0
        while steps_done < step:
            if not iter_train.has_next():
                iter_train.reset()
            ds = iter_train.next()
            if ds.num_examples() < self.c.batch_size:
                iter_train.reset()
                continue
            steps_done += 1
            if not iter_train.has_next():
                iter_train.reset()

    # -- the loop ------------------------------------------------------------

    def train(self, log: Callable[[str], None] = print) -> Dict[str, float]:
        c = self.c
        train_csv, test_csv = self.w.ensure_data(c.res_path)
        iter_train = RecordReaderDataSetIterator(
            train_csv, c.batch_size, c.label_index, c.num_classes)
        iter_test = RecordReaderDataSetIterator(
            test_csv, c.batch_size_pred, c.label_index, c.num_classes)
        self._maybe_resume(iter_train)

        B = c.batch_size
        ones = jnp.ones((B, 1), dtype=jnp.float32)
        zeros = jnp.zeros((B, 1), dtype=jnp.float32)
        y_dis = jnp.concatenate([ones + self.soften_real,
                                 zeros + self.soften_fake])

        fused_state = None
        start_counter = self.batch_counter
        if self._fused_step is not None:
            fused_state = self._fused_lib.state_from_graphs(
                self.dis, self.gen, self.gan, self.classifier)

        while iter_train.has_next() and self.batch_counter < c.num_iterations:
            ds = iter_train.next()
            if ds.num_examples() < B:   # partial epoch tail: wrap like :524
                iter_train.reset()
                continue
            real = jnp.asarray(ds.features)
            labels = jnp.asarray(ds.labels)

            if self._fused_step is not None:
                # the whole iteration — D-step, syncs, G-step, classifier —
                # is one donated-state XLA program; z drawn host-side from
                # the same stream as the unfused path
                z1 = jax.random.uniform(next(self._z_keys), (B, c.z_size),
                                        minval=-1.0, maxval=1.0)
                z2 = jax.random.uniform(next(self._z_keys), (B, c.z_size),
                                        minval=-1.0, maxval=1.0)
                if self._batch_sharding is not None:
                    real = jax.device_put(real, self._batch_sharding)
                    labels = jax.device_put(labels, self._batch_sharding)
                rng = jax.random.fold_in(self._fused_rng, self.batch_counter + 1)
                fused_state, (d_loss, g_loss, c_loss) = self._fused_step(
                    fused_state, rng, real, labels, z1, z2,
                    ones + self.soften_real, zeros + self.soften_fake, ones)
            else:
                # (1) D-step on [real(1+eps), fake(0+eps)]
                z = jax.random.uniform(next(self._z_keys), (B, c.z_size),
                                       minval=-1.0, maxval=1.0)
                fake = self.gen.output(z)[0].reshape(B, c.num_features)
                d_loss = self._fit_dis(jnp.concatenate([real, fake]), y_dis)

                # (2) dis -> gan frozen tail (weights + BN running stats)
                sync_params(self.gan, self.dis, self.w.dis_to_gan)

                # (3) G-step: fool the frozen discriminator
                z = jax.random.uniform(next(self._z_keys), (B, c.z_size),
                                       minval=-1.0, maxval=1.0)
                g_loss = self._fit_gan(z, ones)

                # (4) gan generator -> standalone gen
                sync_params(self.gen, self.gan, self.w.gan_to_gen)

                # (5) classifier: dis features, fit on the real labeled batch
                sync_params(self.classifier, self.dis, self.w.dis_to_classifier)
                c_loss = self._fit_clf(real, labels)

            self.batch_counter += 1
            self.metrics.log_step(
                self.batch_counter, examples=B,
                d_loss=d_loss, g_loss=g_loss, classifier_loss=c_loss,
            )
            if self.batch_counter % 100 == 0:
                log(f"Completed Batch {self.batch_counter}!")

            if self._fused_step is not None and (
                self.batch_counter % c.print_every == 0
                or self.batch_counter % c.save_every == 0
                or (c.checkpoint_every
                    and self.batch_counter % c.checkpoint_every == 0)):
                # artifact/checkpoint points read through the graph objects
                self._fused_lib.state_to_graphs(
                    fused_state, self.dis, self.gen, self.gan, self.classifier)

            if self.batch_counter % c.print_every == 0:
                self._dump_grid()
            if self.batch_counter % c.save_every == 0:
                self._dump_predictions(iter_test)
            if self.c.checkpoint_every:
                self._maybe_checkpoint()

            if not iter_train.has_next():
                iter_train.reset()

        if self._fused_step is not None and fused_state is not None:
            self._fused_lib.state_to_graphs(
                fused_state, self.dis, self.gen, self.gan, self.classifier)
            if self.batch_counter > start_counter:
                self.dis.score, self.gan.score = d_loss, g_loss
                self.classifier.score = c_loss

        # end-of-run model zips, exactly the reference's four files (:529-533)
        name = c.dataset_name
        serialization.write_model(
            self.dis, os.path.join(c.res_path, f"{name}_dis_model.zip"))
        serialization.write_model(
            self.gan, os.path.join(c.res_path, f"{name}_gan_model.zip"))
        serialization.write_model(
            self.gen, os.path.join(c.res_path, f"{name}_gen_model.zip"))
        serialization.write_model(
            self.classifier,
            os.path.join(c.res_path,
                         f"{name}_{self.w.classifier_model_name}_model.zip"))
        self.metrics.flush()
        return {
            "steps": self.batch_counter,
            "examples_per_sec": self.metrics.throughput(),
            "d_loss": float(self.dis.score),
            "g_loss": float(self.gan.score),
        }
