"""Insurance MLP-GAN trainer — ``dl4jGANInsurance`` equivalent.

Reference: ``Java/src/main/java/org/deeplearning4j/dl4jGANInsurance.java``
(protocol :329-469, constants :58-84).  Extra artifact vs the CV main: at
every ``printEvery`` the classifier's predictions over the generated
latent-grid lattices are dumped too (``insurance_out_pred_{k}.csv``,
:422-437) — the notebook's AUROC lattice plots read these.

Run: ``python -m gan_deeplearning4j_tpu.train.insurance_main``
"""

from __future__ import annotations

import argparse
import os
from typing import Dict

import jax.numpy as jnp

from gan_deeplearning4j_tpu.data import ensure_insurance_csv
from gan_deeplearning4j_tpu.models import mlpgan_insurance as M
from gan_deeplearning4j_tpu.train.gan_trainer import (
    GANTrainer,
    GANTrainerConfig,
    Workload,
    add_data_args,
    add_health_args,
    check_recovery_args,
    data_config_kwargs,
    health_config_kwargs,
    run_with_recovery,
)


class InsuranceWorkload(Workload):
    name = "insurance"
    classifier_model_name = "insurance"

    def __init__(self, cfg: M.InsuranceConfig = M.InsuranceConfig()):
        self.cfg = cfg
        self.dis_to_gan = M.DIS_TO_GAN
        self.gan_to_gen = M.GAN_TO_GEN
        self.dis_to_classifier = M.DIS_TO_CLASSIFIER

    def build_graphs(self) -> Dict[str, object]:
        dis = M.build_discriminator(self.cfg)
        return {
            "dis": dis,
            "gen": M.build_generator(self.cfg),
            "gan": M.build_gan(self.cfg),
            "classifier": M.build_classifier(dis, self.cfg),
        }

    def ensure_data(self, res_path: str):
        return ensure_insurance_csv(res_path)

    def grid_extra_arrays(self, trainer, grid_out, step: int):
        # classifier predictions over the generated lattice grid
        # (dl4jGANInsurance.java:422-437); dispatched here on the training
        # thread, written by the async artifact writer
        preds = trainer.classifier.output(jnp.asarray(grid_out))[0]
        path = os.path.join(trainer.c.res_path,
                            f"insurance_out_pred_{step}.csv")
        return [(path, preds)]


def default_config(**overrides) -> GANTrainerConfig:
    base = dict(
        dataset_name="insurance",
        num_features=12,
        label_index=12,
        num_classes=1,          # sigmoid target (dl4jGANInsurance.java:61)
        batch_size=50,
        batch_size_pred=700,
        num_iterations=5000,
        num_gen_samples=50,
        averaging_frequency=5,
    )
    base.update(overrides)
    return GANTrainerConfig(**base)


def main(argv=None) -> Dict[str, float]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iterations", type=int, default=5000)
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--res-path", default="outputs/insurance")
    p.add_argument("--print-every", type=int, default=100)
    p.add_argument("--save-every", type=int, default=100)
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument("--dp-mode", default="gradient_sync",
                   choices=["gradient_sync", "param_averaging"])
    p.add_argument("--averaging-frequency", type=int, default=5)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="cap on lax.scan protocol steps per XLA dispatch "
                        "on the device-resident path (None = auto)")
    p.add_argument("--sync-dumps", action="store_true",
                   help="write artifacts synchronously on the training "
                        "thread (the reference's behavior) instead of the "
                        "background artifact writer")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="auto-resume from the latest checkpoint on failure, "
                        "up to N times (needs --checkpoint-every); the "
                        "budget is progress-aware and fatal errors "
                        "(config/structure mismatch, NaN abort) are not "
                        "retried (docs/FAULT_TOLERANCE.md)")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="serialize/fsync checkpoints on a background "
                        "worker — the training thread pays only the host "
                        "snapshot; on-disk bytes (manifest hashes "
                        "included) identical to the synchronous save")
    p.add_argument("--preempt-signal", action="append", default=None,
                   metavar="SIG",
                   help="signal name (e.g. SIGTERM; repeatable) that "
                        "triggers an emergency checkpoint + resumable "
                        "PREEMPTED.json marker, then exit code 75 "
                        "(EX_TEMPFAIL) — requeue and resume with --resume")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into DIR")
    from gan_deeplearning4j_tpu.runtime import prng as _prng

    p.add_argument("--seed", type=int, default=_prng.NUMBER_OF_THE_BEAST,
                   help="model-init + training-stream seed (default: the "
                        "reference's 666; the DATASET keeps its own fixed "
                        "seed, so variance runs share identical data)")
    p.add_argument("--live-ui", type=int, default=0, metavar="PORT",
                   help="serve a live loss dashboard over the metrics "
                        "JSONL on this port (the Spark-web-UI analog)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve /metrics (Prometheus text: step/loss/"
                        "goodput/NaN series) + /healthz on this port "
                        "for the duration of training (0 = ephemeral; "
                        "docs/OBSERVABILITY.md)")
    p.add_argument("--telemetry", action="store_true",
                   help="in-graph numerics telemetry: per-step grad/param "
                        "norms, update ratios and NaN/Inf counters "
                        "computed inside the fused program and logged as "
                        "metrics columns (zero extra dispatches); the run "
                        "also always writes res-path/run_manifest.json "
                        "and a goodput phase breakdown")
    p.add_argument("--nan-alarm", default=None,
                   choices=["warn", "snapshot", "abort", "rollback"],
                   help="action on the first non-finite step (needs "
                        "--telemetry): warn = log and continue; snapshot "
                        "= save a forensic checkpoint to "
                        "res-path/nan_snapshot (through the emergency-"
                        "checkpoint path) and continue; abort = raise; "
                        "the recovery wrapper classifies the abort as "
                        "FATAL — a deterministic replay would hit the "
                        "same NaN, so --max-restarts is not burned on it; "
                        "rollback = heal in-process: restore the last "
                        "verified pre-NaN checkpoint, cut the LR by "
                        "--rollback-lr-factor and perturb the noise "
                        "stream so the replay differs (needs "
                        "--checkpoint-every; docs/FAULT_TOLERANCE.md)")
    add_health_args(p)
    add_data_args(p)
    from gan_deeplearning4j_tpu.runtime import backend

    backend.add_bf16_flag(p)
    backend.add_mp_flag(p)
    args = p.parse_args(argv)

    if args.bf16:
        backend.configure(matmul_bf16=True)
    if args.mp:
        backend.configure(compute_bf16=True)
    check_recovery_args(p, args)

    config = default_config(
        num_iterations=args.iterations,
        batch_size=args.batch_size,
        res_path=args.res_path,
        print_every=args.print_every,
        save_every=args.save_every,
        n_devices=args.n_devices,
        dp_mode=args.dp_mode,
        averaging_frequency=args.averaging_frequency,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
        async_checkpoint=args.async_checkpoint,
        preempt_signals=(",".join(args.preempt_signal)
                         if args.preempt_signal else None),
        steps_per_call=args.steps_per_call,
        async_dumps=not args.sync_dumps,
        seed=args.seed,
        telemetry=args.telemetry,
        nan_alarm=args.nan_alarm,
        metrics_port=args.metrics_port,
        **health_config_kwargs(args),
        **data_config_kwargs(args),
    )
    from gan_deeplearning4j_tpu.utils import maybe_trace, print_trace_summary

    stop_ui = None
    if args.live_ui:
        from gan_deeplearning4j_tpu.utils.live_ui import serve_for_config

        stop_ui = serve_for_config(config, args.live_ui)
    from gan_deeplearning4j_tpu.train.preemption import PreemptionError

    try:
        with maybe_trace(args.profile):
            trainer, result = run_with_recovery(
                config,
                lambda: InsuranceWorkload(
                    cfg=M.InsuranceConfig(seed=args.seed)),
                max_restarts=args.max_restarts)
        if args.profile:
            # where the step time went, without leaving the terminal
            print_trace_summary(args.profile)
        result.update(evaluate(trainer))
    except PreemptionError as e:
        # the emergency checkpoint is durable; report the resumable state
        # instead of a traceback (cli() exits 75 so the scheduler requeues)
        result = {"preempted": True, "step": e.step,
                  "checkpoint": e.checkpoint, "res_path": args.res_path}
    finally:
        if stop_ui is not None:
            stop_ui()  # release the port before the JSON line
    import json

    # one JSON line (numpy scalars coerced) — machine-consumable, cf.
    # bench.py and benchmarks/acceptance.py
    print(json.dumps(result, default=float))
    return result


def evaluate(trainer: GANTrainer) -> Dict[str, float]:
    """End-of-run evaluation: the notebook's cell-10 weighted AUROC over
    the final prediction dump plus the lattice-grid PNG (gan.ipynb raw
    lines 1483-1516)."""
    from gan_deeplearning4j_tpu.eval import metrics as metrics_lib
    from gan_deeplearning4j_tpu.eval.plots import save_grid_png

    c = trainer.c
    out: Dict[str, float] = {}
    step = trainer.batch_counter
    pred_csv = os.path.join(
        c.res_path, f"insurance_test_predictions_{step}.csv")
    test_csv = os.path.join(c.res_path, "insurance_test.csv")
    if os.path.exists(pred_csv) and os.path.exists(test_csv):
        from gan_deeplearning4j_tpu.data import read_csv_matrix

        preds = read_csv_matrix(pred_csv)
        labels = read_csv_matrix(test_csv)[:, c.label_index]
        out["test_auroc"] = metrics_lib.auroc_from_predictions(preds, labels)
        out.update(metrics_lib.write_evaluation_report(
            c.res_path, preds, labels, num_classes=2, f1_cls=1,
            metrics_jsonl=os.path.join(c.res_path,
                                       "insurance_metrics.jsonl")))
    grid_csv = os.path.join(c.res_path, f"insurance_out_{step}.csv")
    if os.path.exists(grid_csv):
        from gan_deeplearning4j_tpu.data import read_csv_matrix
        from gan_deeplearning4j_tpu.eval.plots import (
            save_lattice_example_pngs,
        )

        grid = read_csv_matrix(grid_csv)  # parsed once, both renders
        save_grid_png(
            os.path.join(c.res_path, "DCGAN_Generated_Lattices.png"),
            grid, (4, 3))
        # the reference's single-lattice artifacts (raw + annotated)
        save_lattice_example_pngs(
            os.path.join(c.res_path, "DCGAN_Generated_Lattice_Example.png"),
            os.path.join(c.res_path,
                         "DCGAN_Generated_Lattice_Example_Plotted.png"),
            grid, (4, 3))
    return out


def cli(argv=None) -> None:
    """Console-script / python -m entry: swallow main()'s result dict
    so the setuptools wrapper's sys.exit() sees None (exit status 0),
    and honor JAX_PLATFORMS — a fresh process by definition, so this
    cannot clobber an in-process override (unlike main(), which tests
    import and call under a conftest-forced CPU platform).  A preempted
    run exits 75 (EX_TEMPFAIL): "requeue me", not success or crash."""
    import sys

    from gan_deeplearning4j_tpu.runtime import backend as _backend
    from gan_deeplearning4j_tpu.train.preemption import EXIT_PREEMPTED

    _backend.apply_env_platform()
    result = main(argv)
    if result.get("preempted"):
        sys.exit(EXIT_PREEMPTED)


if __name__ == "__main__":
    cli()
