"""Tenant lifecycle layer — heterogeneous elastic fleets, per-tenant
fault domains.

``train/fleet.py`` (PR 12) runs N tenants of ONE architecture with N
fixed at build time and a shared blast radius: one poisoned tenant's
``DataQuarantineError`` or NaN could take the whole dispatch down.
This module closes ROADMAP item 3 — every tenant becomes its own fault
domain and membership becomes a runtime value:

  - **Heterogeneous cohorts**: tenants are grouped by architecture
    (``TenantSpec.hidden`` x ``TenantSpec.gen_layers``) into vmap
    *cohorts*; each cohort is one donated masked fleet step
    (``make_fleet_step(masked=True)``), and all cohorts advance inside
    the one supervised window loop (``LifecycleFleetTrainer`` puts the
    whole fleet behind the single ``SupervisionShell``).
  - **Bucketed capacity, zero recompiles**: the serving-bucket
    discipline applied to the tenant axis.  Each cohort is padded to a
    bucketed slot count (``DEFAULT_TENANT_BUCKETS``); unoccupied slots
    are *ghosts* — template params, mask off, zero data — so onboard/
    offboard/quarantine are mask flips and host-array surgery, never a
    new program shape.  ``warmup()`` compiles every (cohort, bucket)
    program once; after that an armed ``RecompileSentinel`` sees
    nothing (the lifecycle-chaos e2e pins this).
  - **Isolation**: per-tenant NaN/divergence tripping
    (``FleetHealthSentinel``) quarantines — freezes + masks — only the
    sick tenant; the ``TenantRouter``'s per-tenant quarantine budgets
    run in ``raise_on_budget=False`` mode so a poisoned feed trips one
    tenant instead of raising through the fleet loop; token-bucket
    ingest quotas cap a hot tenant's routing share.  Because lanes are
    element-wise independent (the PR-12 bitwise pin), every surviving
    tenant's loss timeline stays bit-equal (f32) to an undisturbed
    control run through arbitrary lifecycle events.

Checkpoints: one ``FleetCheckpointer`` directory per cohort, each save
carrying the tenant-id -> slot/cohort map (``tenant_map``) so
``restore(tenants=<id>)`` resolves by IDENTITY, refuses a disagreeing
mapping (``TenantMappingError``), and stays bit-equal per tenant.
Offboarding writes a final single-tenant checkpoint the tenant can be
re-onboarded from.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.data import resilient
from gan_deeplearning4j_tpu.runtime import prng
from gan_deeplearning4j_tpu.telemetry import events as telemetry_events
from gan_deeplearning4j_tpu.train import fleet as fleet_lib
from gan_deeplearning4j_tpu.train import fused_step as fused_lib
from gan_deeplearning4j_tpu.train.fused_step import ProtocolState
from gan_deeplearning4j_tpu.utils import device_fence

# Bucketed slot counts for the tenant axis — the serving-bucket
# discipline (parallel/inference.py) applied to fleet membership: a
# cohort's capacity is always one of these, so membership changes are
# mask flips within a warmed program, or a hop to the NEXT warmed
# bucket.  The gan4j-prove fleet_step contract lists this set as its
# cohort coverage.
DEFAULT_TENANT_BUCKETS = (2, 4, 8, 16, 32, 64)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``n`` (capacity for ``n`` occupied slots)."""
    for b in sorted(buckets):
        if b >= n:
            return int(b)
    raise ValueError(
        f"{n} tenants exceed the largest tenant bucket "
        f"{max(buckets)} — extend LifecycleConfig.buckets")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's identity + architecture.  ``tenant_id`` is the
    STABLE routing identity (``TenantRouter`` segment); the
    architecture pair is the cohort key — tenants share a vmap cohort
    iff their (hidden, gen_layers) agree."""

    tenant_id: int
    hidden: int = 100
    gen_layers: int = 3

    @property
    def cohort_key(self) -> str:
        return f"h{self.hidden}_l{self.gen_layers}"


@dataclasses.dataclass
class LifecycleConfig:
    """Knobs for a lifecycle-managed heterogeneous fleet."""

    batch_size: int = 4
    seed: int = prng.NUMBER_OF_THE_BEAST
    res_path: str = "outputs/lifecycle"
    buckets: Tuple[int, ...] = DEFAULT_TENANT_BUCKETS
    # buckets compiled per cohort at warmup; None = every bucket up to
    # ONE above the cohort's initial occupancy (room to grow once
    # without a recompile).  The zero-recompile guarantee covers
    # exactly the warmed set.
    warm_buckets: Optional[Tuple[int, ...]] = None
    # fixed segment universe for the router; None = max tenant id + 1
    # over the INITIAL specs — pass explicitly when later onboards use
    # higher ids
    num_segments: Optional[int] = None
    quarantine_budget: int = 8   # bad rows per tenant before trip
    quota_rows: Optional[float] = None          # token-bucket capacity
    quota_refill_per_s: Optional[float] = None  # rows/s refill
    divergence_factor: float = 1e3
    divergence_patience: int = 2
    keep_checkpoints: int = 3
    checkpoint_every: int = 0    # steps; 0 = only explicit saves
    record_timelines: bool = False  # keep per-step per-tenant losses


class FleetHealthSentinel:
    """Per-tenant divergence/NaN tripping over window loss vectors.

    A non-finite d/g-loss trips immediately (``"nan"``); a window whose
    mean loss magnitude exceeds ``factor`` x the tenant's own rolling
    median for ``patience`` consecutive windows trips as
    ``"divergence"``.  Scope is ONE tenant — the caller freezes + masks
    that lane; cohort-mates never see a rollback."""

    def __init__(self, factor: float = 1e3, patience: int = 2,
                 history: int = 16):
        self.factor = float(factor)
        self.patience = int(patience)
        self._hist: Dict[int, deque] = {}
        self._strikes: Dict[int, int] = {}

    def observe(self, tenant: int, d_losses, g_losses) -> Optional[str]:
        """Feed one window of per-step losses; returns a trip reason or
        None."""
        d = np.asarray(d_losses, np.float64)
        g = np.asarray(g_losses, np.float64)
        if not (np.isfinite(d).all() and np.isfinite(g).all()):
            return "nan"
        mag = float(np.abs(d).mean() + np.abs(g).mean())
        hist = self._hist.setdefault(
            tenant, deque(maxlen=max(4, self.patience * 8)))
        if len(hist) >= 3:
            med = float(np.median(hist))
            if med > 0.0 and mag > self.factor * med:
                self._strikes[tenant] = self._strikes.get(tenant, 0) + 1
                if self._strikes[tenant] >= self.patience:
                    return "divergence"
                return None  # a strike is not yet a trip
        self._strikes[tenant] = 0
        hist.append(mag)
        return None

    def forget(self, tenant: int) -> None:
        self._hist.pop(tenant, None)
        self._strikes.pop(tenant, None)


class _PendingOps:
    """Thread-safe boundary-op queue: chaos/ops threads enqueue
    lifecycle mutations; the training loop drains them at step-window
    boundaries, the only place fleet membership may change."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ops: List[Callable[[], None]] = []

    def push(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._ops.append(fn)

    def drain(self) -> List[Callable[[], None]]:
        with self._lock:
            ops, self._ops = self._ops, []
        return ops


def _np_state(state: ProtocolState) -> ProtocolState:
    """The stacked state as HOST numpy (fences; bit-preserving)."""
    return jax.tree.map(np.asarray, state)


def _stack_rows(rows: Sequence[ProtocolState]) -> ProtocolState:
    """Host-side stack of single-tenant rows -> a stacked fleet state
    (numpy; ``device_put`` to dispatch — no eager device ops, which is
    what keeps lifecycle surgery off the compile path)."""
    return jax.tree.map(lambda *xs: np.stack(xs), *rows)


def _row(state: ProtocolState, slot: int) -> ProtocolState:
    """Host slice of one slot (call on a ``_np_state`` result)."""
    return jax.tree.map(lambda x: np.asarray(x)[slot], state)


class Cohort:
    """One architecture's slice of the fleet: a bucketed slot vector, a
    masked donated step, and the host-surgery lifecycle verbs."""

    def __init__(self, key: str, hidden: int, gen_layers: int,
                 config: LifecycleConfig):
        from gan_deeplearning4j_tpu.models import mlpgan_insurance as M

        self.key = key
        self.hidden = hidden
        self.gen_layers = gen_layers
        self.c = config
        cfg = M.InsuranceConfig(seed=config.seed, hidden=hidden,
                                gen_layers=gen_layers)
        self.model_cfg = cfg
        dis = M.build_discriminator(cfg)
        self.graphs = (dis, M.build_generator(cfg), M.build_gan(cfg),
                       M.build_classifier(dis, cfg))
        self.maps = (M.DIS_TO_GAN, M.gan_to_gen_map(cfg),
                     M.DIS_TO_CLASSIFIER)
        self.step = fleet_lib.make_fleet_step(
            *self.graphs, *self.maps,
            z_size=cfg.z_size, num_features=cfg.num_features,
            per_tenant_data=True, data_on_device=True, masked=True)
        # ghost rows hold the template init: a fresh onboard is a pure
        # mask flip (the ghost already IS the init state, it=0)
        self._template = _np_state(
            fused_lib.state_from_graphs(*self.graphs))
        self.slots: List[Optional[int]] = []
        self.mask = np.zeros((0,), bool)
        self.state: Optional[ProtocolState] = None

    # -- membership ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        return len(self.slots)

    def active_ids(self) -> List[int]:
        return [t for t, on in zip(self.slots, self.mask)
                if t is not None and on]

    def occupied_ids(self) -> List[int]:
        return [t for t in self.slots if t is not None]

    def slot_of(self, tenant: int) -> int:
        return self.slots.index(tenant)

    def _ensure_capacity(self, need_slots: int) -> None:
        """Grow to the bucket holding ``need_slots`` occupied slots —
        host re-pad with template ghost rows (a boundary op; the larger
        bucket's program comes from the warmed set)."""
        cap = bucket_for(need_slots, self.c.buckets)
        if cap <= self.capacity:
            return
        grow = cap - self.capacity
        if self.state is not None:
            host = _np_state(self.state)
            rows = [_row(host, s) for s in range(self.capacity)]
            rows += [self._template] * grow
            self.state = jax.device_put(_stack_rows(rows))
        self.slots += [None] * grow
        self.mask = np.concatenate([self.mask, np.zeros(grow, bool)])
        telemetry_events.instant("fleet.cohort_grow", cohort=self.key,
                                 capacity=cap)

    def admit(self, tenant: int,
              params: Optional[ProtocolState] = None) -> int:
        """Occupy a slot for ``tenant`` (growing if full) and unmask
        it.  ``params``: a host single-tenant state (re-onboard from a
        final checkpoint); None = the template init the ghost already
        holds."""
        if tenant in self.slots:
            raise ValueError(f"tenant {tenant} already holds a slot "
                             f"in cohort {self.key}")
        free = [i for i, t in enumerate(self.slots) if t is None]
        if not free:
            self._ensure_capacity(len(self.occupied_ids()) + 1)
            free = [i for i, t in enumerate(self.slots) if t is None]
        slot = free[0]
        if params is not None:
            # materialize state FIRST: a brand-new cohort (state None)
            # must not silently drop the restored params and restart
            # the tenant from the template init
            self.ensure_state()
            host = _np_state(self.state)
            rows = [_row(host, s) for s in range(self.capacity)]
            rows[slot] = jax.tree.map(np.asarray, params)
            self.state = jax.device_put(_stack_rows(rows))
        elif self.state is not None:
            # the vacated slot may hold a previous occupant's rows —
            # reset to the template so a fresh onboard starts at init
            host = _np_state(self.state)
            rows = [_row(host, s) for s in range(self.capacity)]
            rows[slot] = self._template
            self.state = jax.device_put(_stack_rows(rows))
        self.slots[slot] = tenant
        self.mask[slot] = True
        return slot

    def vacate(self, tenant: int) -> ProtocolState:
        """Mask off + free ``tenant``'s slot; returns its final host
        single-tenant state (the offboard checkpoint payload)."""
        slot = self.slot_of(tenant)
        final = _row(_np_state(self.state), slot)
        self.slots[slot] = None
        self.mask[slot] = False
        return final

    def freeze(self, tenant: int) -> None:
        """Quarantine form: mask off but KEEP the slot (state frozen in
        place for forensics; the id stays attached to the slot so the
        checkpoint tenant map still names it)."""
        self.mask[self.slot_of(tenant)] = False

    def ensure_state(self) -> None:
        if self.state is None:
            self.state = jax.device_put(
                _stack_rows([self._template] * max(1, self.capacity)))

    def tenant_map(self) -> Dict:
        """The slot semantics persisted with every cohort checkpoint."""
        return {"slots": self.slots,
                "cohorts": {str(t): self.key for t in self.slots
                            if t is not None}}


class FleetManager:
    """The lifecycle-managed heterogeneous fleet: cohorts, bucketed
    capacity, onboard/offboard/quarantine as boundary operations, and
    per-tenant health.  Drive it with :meth:`step_window`; mutate
    membership directly between windows or from another thread via
    :meth:`request` (applied at the next window boundary)."""

    def __init__(self, specs: Sequence[TenantSpec],
                 config: LifecycleConfig,
                 registry=None,
                 health: Optional[resilient.DataHealth] = None):
        self.c = config
        os.makedirs(config.res_path, exist_ok=True)
        self.specs: Dict[int, TenantSpec] = {}
        self.health = health if health is not None else \
            resilient.DataHealth()
        num_segments = config.num_segments
        if num_segments is None:
            num_segments = max((s.tenant_id for s in specs),
                               default=0) + 1
        self.router = fleet_lib.TenantRouter(
            config.res_path, budget=config.quarantine_budget,
            health=self.health,
            tenants=[s.tenant_id for s in specs],
            num_segments=num_segments,
            quota_rows=config.quota_rows,
            quota_refill_per_s=config.quota_refill_per_s,
            raise_on_budget=False)
        self.cohorts: Dict[str, Cohort] = {}
        self._keys: Dict[int, Tuple[jax.Array, jax.Array]] = {}
        self._key_vecs: Dict[str, Tuple[jax.Array, jax.Array]] = {}
        self._checkpointers: Dict[str, fleet_lib.FleetCheckpointer] = {}
        for s in specs:
            self._admit_spec(s)
        for cohort in self.cohorts.values():
            cohort.ensure_state()
        self.sentinel = FleetHealthSentinel(
            config.divergence_factor, config.divergence_patience)
        self.registry = registry
        self.quarantined: Dict[int, str] = {}
        self.onboarded_total = 0
        self.offboarded_total = 0
        self.throttled_total = 0
        self.step_count = 0
        self._onboard_ms: deque = deque(maxlen=64)
        self._pending = _PendingOps()
        self._warmed = False
        self._steps_per_sec = 0.0
        self._dispatch_ms = 0.0
        self.loss_history: Dict[int, Dict[str, list]] = {}
        root = prng.root_key(config.seed)
        self._z_base = prng.stream(root, "fleet-z")
        self._r_base = prng.stream(root, "fleet-rng")
        B = config.batch_size
        self._ones = jnp.ones((B, 1), jnp.float32)
        self._y_real = self._ones + 0.05 * jax.random.normal(
            prng.stream(root, "soften-real"), (B, 1), dtype=jnp.float32)
        self._y_fake = 0.05 * jax.random.normal(
            prng.stream(root, "soften-fake"), (B, 1), dtype=jnp.float32)

    # -- plumbing ------------------------------------------------------------

    def _admit_spec(self, spec: TenantSpec,
                    params: Optional[ProtocolState] = None) -> Cohort:
        cohort = self.cohorts.get(spec.cohort_key)
        if cohort is None:
            cohort = Cohort(spec.cohort_key, spec.hidden,
                            spec.gen_layers, self.c)
            self.cohorts[spec.cohort_key] = cohort
        cohort.admit(spec.tenant_id, params=params)
        self.specs[spec.tenant_id] = spec
        self._key_vecs.pop(spec.cohort_key, None)
        return cohort

    def _tenant_keys(self, tenant: int) -> Tuple[jax.Array, jax.Array]:
        """fold_in(base, tenant_id) — the SAME folding a single-tenant
        control uses, so lifecycle lanes keep the PR-12 bitwise
        fleet/control equivalence."""
        got = self._keys.get(tenant)
        if got is None:
            got = (jax.random.fold_in(self._z_base, tenant),
                   jax.random.fold_in(self._r_base, tenant))
            self._keys[tenant] = got
        return got

    def _cohort_key_vecs(self, cohort: Cohort):
        """(capacity,) z/rng key vectors in slot order; ghosts reuse
        the base key (their lanes are masked — the value never lands
        in any surviving state)."""
        got = self._key_vecs.get(cohort.key)
        if got is not None and int(got[0].shape[0]) == cohort.capacity:
            return got
        zs, rs = [], []
        for t in cohort.slots:
            if t is None:
                zs.append(self._z_base)
                rs.append(self._r_base)
            else:
                zk, rk = self._tenant_keys(t)
                zs.append(zk)
                rs.append(rk)
        got = (jnp.stack(zs), jnp.stack(rs))
        self._key_vecs[cohort.key] = got
        return got

    def checkpointer_for(self, cohort_key: str
                         ) -> fleet_lib.FleetCheckpointer:
        ck = self._checkpointers.get(cohort_key)
        if ck is None:
            ck = fleet_lib.FleetCheckpointer(
                os.path.join(self.c.res_path, "checkpoints", cohort_key),
                keep=self.c.keep_checkpoints)
            self._checkpointers[cohort_key] = ck
        return ck

    def request(self, fn: Callable[[], None]) -> None:
        """Enqueue a lifecycle op from any thread; it runs at the next
        window boundary (membership never changes mid-dispatch)."""
        self._pending.push(fn)

    def drain_pending(self) -> int:
        ops = self._pending.drain()
        for fn in ops:
            fn()
        return len(ops)

    # -- warmup --------------------------------------------------------------

    def _warm_caps(self, cohort: Cohort) -> List[int]:
        if self.c.warm_buckets is not None:
            return sorted(set(self.c.warm_buckets))
        caps = sorted(self.c.buckets)
        upto = [b for b in caps if b <= cohort.capacity]
        nxt = [b for b in caps if b > cohort.capacity][:1]
        return upto + nxt

    def _warm_cohort(self, cohort: Cohort) -> List[int]:
        """Compile one cohort's (bucket) programs against scratch
        state; returns the warmed bucket list."""
        B = self.c.batch_size
        cfg = cohort.model_cfg
        caps = self._warm_caps(cohort)
        for cap in caps:
            scratch = jax.device_put(
                _stack_rows([cohort._template] * cap))
            data = jnp.asarray(
                np.full((cap, B, cfg.num_features), 0.5, np.float32))
            labs = jnp.asarray(np.ones((cap, B, 1), np.float32))
            zks = jnp.stack([self._z_base] * cap)
            rks = jnp.stack([self._r_base] * cap)
            mask = jnp.asarray(np.ones((cap,), bool))
            out, losses = cohort.step(scratch, data, labs, zks, rks,
                                      mask, self._y_real,
                                      self._y_fake, self._ones)
            device_fence(losses)
            del out
        return caps

    def warmup(self) -> Dict[str, List[int]]:
        """Compile every (cohort, bucket) program + the lifecycle
        helper ops once.  After this, membership churn within the
        warmed bucket set causes ZERO further compiles — the armed
        ``RecompileSentinel`` in the lifecycle-chaos e2e is the
        proof.  (A post-warmup onboard of a NEW architecture warms its
        cohort inside :meth:`onboard`, charged to onboard latency.)"""
        warmed: Dict[str, List[int]] = {}
        for cohort in self.cohorts.values():
            warmed[cohort.key] = self._warm_cohort(cohort)
        # the checkpoint tree form's empty-dict marker is the one eager
        # device op on the save path — warm its tiny fill program
        device_fence(jnp.zeros((), jnp.int32))
        self._warmed = True
        telemetry_events.instant(
            "fleet.warmup",
            cohorts=len(self.cohorts),
            programs=sum(len(v) for v in warmed.values()))
        return warmed

    # -- lifecycle verbs -----------------------------------------------------

    def active_ids(self) -> List[int]:
        out: List[int] = []
        for cohort in self.cohorts.values():
            out.extend(cohort.active_ids())
        return sorted(out)

    def cohort_of(self, tenant: int) -> Cohort:
        for cohort in self.cohorts.values():
            if tenant in cohort.slots:
                return cohort
        raise KeyError(f"tenant {tenant} holds no slot in any cohort")

    def onboard(self, spec: TenantSpec,
                from_checkpoint: Optional[str] = None) -> float:
        """Onboard ``spec`` at this boundary: fill a ghost slot (or
        hop the cohort to its next warmed bucket), slice in init or
        checkpointed params, start routing its segment.  Returns the
        onboard latency in milliseconds — the bench's
        ``onboard_latency_ms`` headline."""
        t0 = time.perf_counter()
        if spec.tenant_id in self.specs:
            raise ValueError(f"tenant {spec.tenant_id} is already "
                             "onboarded")
        params = None
        if from_checkpoint is not None:
            ck = fleet_lib.FleetCheckpointer(from_checkpoint,
                                             sweep_debris=False)
            _, params, _ = ck.restore(tenants=spec.tenant_id)
        new_cohort = spec.cohort_key not in self.cohorts
        cohort = self._admit_spec(spec, params=params)
        cohort.ensure_state()
        if new_cohort and self._warmed:
            # a new architecture after warmup: compile its bucket
            # programs HERE (charged to onboard latency) so the
            # training loop keeps the zero-recompile guarantee
            self._warm_cohort(cohort)
            telemetry_events.instant("fleet.cohort_warm_on_onboard",
                                     cohort=cohort.key)
        self._cohort_key_vecs(cohort)  # rebuild eagerly: part of latency
        self.router.add_tenant(spec.tenant_id)
        ms = (time.perf_counter() - t0) * 1e3
        self._onboard_ms.append(ms)
        self.onboarded_total += 1
        telemetry_events.instant(
            "fleet.onboard", tenant=spec.tenant_id, cohort=cohort.key,
            slot=cohort.slot_of(spec.tenant_id), latency_ms=ms,
            restored=from_checkpoint is not None)
        if self.registry is not None:
            self.registry.inc("gan4j_fleet_tenant_onboarded_total")
        return ms

    def offboard(self, tenant: int) -> Optional[str]:
        """Offboard ``tenant``: vacate its slot (ghost again), stop
        routing its segment, and write its final per-tenant checkpoint
        (a 1-tenant fleet save with the identity map — re-onboard with
        ``onboard(spec, from_checkpoint=...)``).  Returns the
        checkpoint path."""
        cohort = self.cohort_of(tenant)
        final = cohort.vacate(tenant)
        # quarantine already stopped routing this tenant — offboarding
        # a quarantined tenant must not raise through the fleet loop
        if tenant in self.router.tenants:
            self.router.remove_tenant(tenant)
        self.specs.pop(tenant, None)
        self._key_vecs.pop(cohort.key, None)
        self.sentinel.forget(tenant)
        # the tenant leaves quarantine with its slot: report()/healthz
        # stop naming it, and a later re-onboard is quarantinable again
        self.quarantined.pop(tenant, None)
        path = None
        ck = fleet_lib.FleetCheckpointer(
            os.path.join(self.c.res_path, "offboarded",
                         f"tenant{tenant}"),
            keep=self.c.keep_checkpoints)
        state1 = _stack_rows([final])
        path = ck.save(self.step_count, state1,
                       tenant_map={"slots": [tenant],
                                   "cohorts": {str(tenant): cohort.key}})
        self.offboarded_total += 1
        telemetry_events.instant("fleet.offboard", tenant=tenant,
                                 cohort=cohort.key, checkpoint=path)
        if self.registry is not None:
            self.registry.inc("gan4j_fleet_tenant_offboarded_total")
        return path

    def quarantine(self, tenant: int, reason: str) -> None:
        """Freeze + mask ONE sick tenant; cohort-mates keep stepping
        (never a fleet rollback).  The slot stays attached to the id
        (forensics: its frozen state still lands in cohort checkpoints
        under its own name)."""
        if tenant in self.quarantined or tenant not in self.specs:
            return
        cohort = self.cohort_of(tenant)
        cohort.freeze(tenant)
        if tenant in self.router.tenants:
            self.router.remove_tenant(tenant)
        self.quarantined[tenant] = reason
        with open(os.path.join(self.c.res_path,
                               "quarantine_fleet.jsonl"), "a") as f:
            f.write(json.dumps({"tenant": tenant, "reason": reason,
                                "step": self.step_count}) + "\n")
        telemetry_events.instant("fleet.quarantine", tenant=tenant,
                                 cohort=cohort.key, reason=reason,
                                 step=self.step_count)
        if self.registry is not None:
            self.registry.inc("gan4j_fleet_tenant_quarantined_total")

    def poison_params(self, tenant: int) -> None:
        """Chaos seam (testing/chaos.py): overwrite ``tenant``'s
        generator/discriminator params with NaN in place — the
        param-poison fault the per-tenant health sentinel must catch
        WITHOUT disturbing cohort-mates."""
        cohort = self.cohort_of(tenant)
        slot = cohort.slot_of(tenant)
        host = _np_state(cohort.state)

        def _poison(x):
            x = np.array(x)
            x[slot] = np.nan
            return x

        fields = {f: getattr(host, f)
                  for f in ("dis_params", "dis_opt", "gan_params",
                            "gan_opt", "clf_params", "clf_opt",
                            "gen_params")}
        for f in ("dis_params", "gen_params"):
            fields[f] = jax.tree.map(_poison, fields[f])
        cohort.state = jax.device_put(ProtocolState(
            *(fields[f] for f in ("dis_params", "dis_opt",
                                  "gan_params", "gan_opt",
                                  "clf_params", "clf_opt",
                                  "gen_params")),
            host.it, host.ema_gen))
        telemetry_events.instant("chaos.poison_params", tenant=tenant,
                                 cohort=cohort.key)

    def checkpoint_fleet(self) -> Dict[str, str]:
        """One verified save per cohort, each carrying its tenant map
        — restore any tenant BY ID, bit-equal, mapping enforced."""
        out = {}
        for key, cohort in self.cohorts.items():
            if cohort.state is None:
                continue
            ck = self.checkpointer_for(key)
            out[key] = ck.save(self.step_count, cohort.state,
                               tenant_map=cohort.tenant_map())
        return out

    # -- the window loop -----------------------------------------------------

    def step_window(self, features, labels, steps: int) -> Dict:
        """Drain boundary ops, route one window of data, advance every
        cohort ``steps`` fused dispatches, then run per-tenant health.
        Returns the window report (losses are per ACTIVE tenant; ghost
        and quarantined lanes are masked out)."""
        self.drain_pending()
        c = self.c
        B = c.batch_size
        # per-window source tag: quarantine charges are idempotent per
        # (source, row) — each window is a NEW stream, so the same row
        # index going bad in consecutive windows must burn budget each
        # time (a re-read of one window's rows still charges once)
        f_all, l_all, info = self.router.route_tables(
            features, labels, B,
            source=f"<window@{self.step_count}>")
        # table row order is the router's tenant list AS ROUTED —
        # capture it BEFORE quarantining tripped tenants (quarantine
        # removes them from the router, which would shift every later
        # tenant onto a neighbour's rows)
        order = {t: i for i, t in enumerate(self.router.tenants)}
        for t in info.tripped:
            self.quarantine(t, "data-quarantine-budget")
        self.throttled_total += sum(info.throttled.values())
        if self.registry is not None and info.throttled:
            self.registry.inc("gan4j_fleet_tenant_throttled_total",
                              sum(info.throttled.values()))
        starved = set(info.starved) - set(self.quarantined)
        t0 = time.perf_counter()
        window_losses: Dict[str, list] = {}
        for key, cohort in self.cohorts.items():
            cohort.ensure_state()
            cap = cohort.capacity
            data = np.zeros((cap, B, f_all.shape[2]), np.float32)
            labs = np.zeros((cap, B, l_all.shape[2]), np.float32)
            mask = cohort.mask.copy()
            for slot, t in enumerate(cohort.slots):
                if t is None or not cohort.mask[slot]:
                    continue
                if t in starved or t not in order:
                    mask[slot] = False  # frozen for THIS window only
                    continue
                data[slot] = f_all[order[t]]
                labs[slot] = l_all[order[t]]
            zks, rks = self._cohort_key_vecs(cohort)
            d_dev = jnp.asarray(data)
            l_dev = jnp.asarray(labs)
            m_dev = jnp.asarray(mask)
            per_step = []
            state = cohort.state
            for _ in range(steps):
                state, losses = cohort.step(
                    state, d_dev, l_dev, zks, rks, m_dev,
                    self._y_real, self._y_fake, self._ones)
                per_step.append(losses)
            cohort.state = state
            window_losses[key] = per_step
        # ONE deliberate readback per window (the fleet-loop cadence
        # discipline), then host-side health over the loss vectors
        for key, per_step in window_losses.items():
            device_fence(per_step)
        dt = time.perf_counter() - t0
        self.step_count += steps
        if dt > 0:
            self._steps_per_sec = steps / dt
            self._dispatch_ms = (dt / steps) * 1e3
        report: Dict[int, Dict[str, np.ndarray]] = {}
        trips: List[Tuple[int, str]] = []
        for key, cohort in self.cohorts.items():
            per_step = [jax.tree.map(np.asarray, x)
                        for x in window_losses[key]]
            for slot, t in enumerate(cohort.slots):
                if t is None or not cohort.mask[slot]:
                    continue
                if t in starved:
                    continue
                d = np.array([s[0][slot] for s in per_step])
                g = np.array([s[1][slot] for s in per_step])
                cl = np.array([s[2][slot] for s in per_step])
                report[t] = {"d": d, "g": g, "clf": cl}
                if c.record_timelines:
                    hist = self.loss_history.setdefault(
                        t, {"d": [], "g": [], "clf": []})
                    hist["d"].extend(d.tolist())
                    hist["g"].extend(g.tolist())
                    hist["clf"].extend(cl.tolist())
                reason = self.sentinel.observe(t, d, g)
                if reason is not None:
                    trips.append((t, reason))
        for t, reason in trips:
            self.quarantine(t, reason)
        if self.registry is not None:
            self.registry.inc("gan4j_steps_total", steps)
            self.registry.set("gan4j_step", self.step_count)
        if (c.checkpoint_every
                and self.step_count % c.checkpoint_every == 0):
            self.checkpoint_fleet()
        return {"step": self.step_count, "losses": report,
                "starved": sorted(starved),
                "quarantined_now": [t for t, _ in trips],
                "info": info}

    # -- observability -------------------------------------------------------

    @property
    def onboard_latency_ms(self) -> float:
        if not self._onboard_ms:
            return 0.0
        return float(np.median(self._onboard_ms))

    def report(self) -> Dict:
        """The ``observe_fleet`` feed: the PR-12 fleet block plus the
        lifecycle ``tenants`` detail (exporter ->
        ``gan4j_fleet_tenant_*`` series + healthz ``fleet.tenants``)."""
        active = self.active_ids()
        return {
            "tenants": len(active),
            "steps_per_sec": self._steps_per_sec,
            "dispatch_ms": self._dispatch_ms,
            "ok": self.health.report().get("ok", True),
            "tenants_detail": {
                "active": len(active),
                "cohorts": len(self.cohorts),
                "quarantined": sorted(self.quarantined),
                "quarantine_reasons": dict(sorted(
                    self.quarantined.items())),
                "onboarded_total": self.onboarded_total,
                "offboarded_total": self.offboarded_total,
                "throttled_total": self.throttled_total,
                "onboard_latency_ms": self.onboard_latency_ms,
            },
        }


class LifecycleFleetTrainer:
    """The heterogeneous lifecycle fleet as ONE payload behind the one
    ``SupervisionShell`` — every cohort's dispatches, the health
    sentinel, and all lifecycle boundary ops run inside a single
    install/teardown bracket (recorder -> watchdog -> sentinel ->
    exporter), exactly like ``GANTrainer`` and ``FleetTrainer``.

    ``feed(window) -> (features, labels)`` supplies each window's raw
    row stream (the chaos harness poisons a tenant by poisoning its
    segment's rows here).  ``on_warm(manager)`` fires after
    :meth:`FleetManager.warmup` — the hook the e2e uses to ARM its
    RecompileSentinel for the zero-recompile proof."""

    def __init__(self, specs: Sequence[TenantSpec],
                 config: LifecycleConfig,
                 metrics_port: Optional[int] = None,
                 events_enabled: bool = True):
        from gan_deeplearning4j_tpu.telemetry.exporter import (
            MetricsRegistry,
        )

        self.c = config
        self.registry = MetricsRegistry()
        self.health = resilient.DataHealth()
        self.registry.observe_data(self.health.report)
        self.manager = FleetManager(specs, config,
                                    registry=self.registry,
                                    health=self.health)
        self.registry.observe_fleet(self.manager.report)
        self._metrics_port = metrics_port
        self._events = events_enabled
        self.metrics_port: Optional[int] = None

    def train(self, feed: Callable[[int], Tuple], windows: int,
              steps_per_window: int,
              on_warm: Optional[Callable] = None,
              stop: Optional[Callable[[int], bool]] = None,
              log: Callable[[str], None] = print) -> Dict:
        from gan_deeplearning4j_tpu.train.shell import SupervisionShell

        m = self.manager
        shell = SupervisionShell(
            self.registry, self.c.res_path,
            events_enabled=self._events,
            step_fn=lambda: m.step_count,
            metrics_port=self._metrics_port, log=log)

        def _payload():
            self.metrics_port = shell.metrics_port
            m.warmup()
            if on_warm is not None:
                on_warm(m)
            w = 0
            while w < windows:
                feats, labs = feed(w)
                m.step_window(feats, labs, steps_per_window)
                w += 1
                if stop is not None and stop(w):
                    break
            m.checkpoint_fleet()
            r = m.report()
            r["windows"] = w
            r["steps"] = m.step_count
            r["timelines"] = {
                t: {k: np.asarray(v, np.float32)
                    for k, v in h.items()}
                for t, h in m.loss_history.items()}
            return r

        return shell.run(_payload)
