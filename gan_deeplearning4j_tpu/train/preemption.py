"""Preemption handling — turn SIGTERM into a checkpoint, not a lost epoch.

Preemptible TPU fleets deliver an eviction warning as a signal (SIGTERM
on GCE/GKE; some schedulers use SIGUSR1) with a grace window measured in
seconds.  The reference's answer was Spark task retries — the whole epoch
replays.  Here a ``PreemptionGuard`` converts the signal into a latched
flag; the training loop polls it at step/chunk boundaries (i.e. after
the in-flight fused call has been dispatched and its state captured),
takes an EMERGENCY checkpoint through the one shared save mechanism
(``GANTrainer._emergency_checkpoint``), writes a resumable
``PREEMPTED.json`` marker, and raises ``PreemptionError`` — which the
recovery wrapper deliberately re-raises (the host is going away;
restarting in-process would just be killed harder) and the mains turn
into exit code 75 (EX_TEMPFAIL: "try again", the conventional
requeue-me status).

The handler itself only sets the flag: no I/O, no locks, nothing
async-signal-unsafe.  Multi-host jobs run the consensus poll
(``parallel/multihost.agree_preemption``) on EVERY host at each armed
boundary — any one signaled host preempts the whole fleet together, and
the markers record the fleet-agreed (min) step alongside each host's
local one.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Dict, Iterable, Optional, Union

# the conventional "temporary failure, requeue me" exit status
EXIT_PREEMPTED = 75

MARKER_NAME = "PREEMPTED.json"


class PreemptionError(RuntimeError):
    """Training was interrupted by a preemption signal AFTER an emergency
    checkpoint was committed; the run is resumable (``--resume`` /
    ``train_with_recovery`` restart) on a replacement host."""

    def __init__(self, msg: str, step: Optional[int] = None,
                 checkpoint: Optional[str] = None):
        super().__init__(msg)
        self.step = step
        self.checkpoint = checkpoint


def _resolve(sig: Union[int, str]) -> int:
    if isinstance(sig, int):
        return sig
    name = sig.strip().upper()
    if not name.startswith("SIG"):
        name = "SIG" + name
    try:
        return getattr(signal, name)
    except AttributeError:
        raise ValueError(
            f"unknown signal {sig!r} (expected e.g. 'SIGTERM', 'SIGUSR1')"
        ) from None


def parse_signals(spec: Union[str, Iterable[Union[int, str]]]) -> tuple:
    """``"SIGTERM,SIGUSR1"`` / ``["TERM", signal.SIGUSR1]`` -> signal
    numbers, validated eagerly (an unknown or uncatchable name must
    fail at config time, not inside the grace window)."""
    if isinstance(spec, str):
        spec = [s for s in spec.split(",") if s.strip()]
    nums = tuple(_resolve(s) for s in spec)
    uncatchable = {getattr(signal, n) for n in ("SIGKILL", "SIGSTOP")
                   if hasattr(signal, n)}
    for n in nums:
        if n in uncatchable:
            raise ValueError(
                f"unknown signal (uncatchable): "
                f"{signal.Signals(n).name} cannot have a handler — "
                "a hard kill is what the checkpoint write protocol "
                "survives, not what a guard can intercept")
    return nums


def preempt_exit(res_path: str, guard: "PreemptionGuard", *,
                 local_step: int, fleet_min_step: int,
                 checkpoint: Optional[str], run_id: Optional[str] = None):
    """The one exit protocol every preempted trainer shares: write the
    resumable ``PREEMPTED.json`` marker (fsynced) and raise
    ``PreemptionError``.  ``step`` in both is the LOCAL step — the step
    this host's emergency checkpoint actually holds; ``fleet_min_step``
    records the allreduce consensus (equal under SPMD lockstep), so a
    straggler mismatch is observable in the marker instead of silently
    mislabeling the checkpoint."""
    from gan_deeplearning4j_tpu.telemetry import events

    marker = {
        "step": local_step,
        "fleet_min_step": fleet_min_step,
        "signal": guard.signal_name(),
        "received_at": guard.received_at,
        "checkpoint": checkpoint,
        "run_id": run_id,
    }
    mpath = os.path.join(res_path, MARKER_NAME)
    with open(mpath, "w") as f:
        json.dump(marker, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    # the timeline: the signal's true arrival (the handler only latched
    # a flag — recording here keeps the handler async-signal-safe), the
    # exit itself, then the flight record rides next to PREEMPTED.json
    events.instant("preempt.signal", signal=guard.signal_name(),
                   received_at=guard.received_at)
    events.instant("preempt.exit", step=local_step,
                   fleet_min_step=fleet_min_step, checkpoint=checkpoint)
    events.dump_flight_record(res_path, "preemption",
                              extra={"step": local_step,
                                     "signal": guard.signal_name()})
    raise PreemptionError(
        f"preempted by {guard.signal_name()} at step {local_step}; "
        f"emergency checkpoint at {checkpoint} (resume with --resume / "
        "the scheduler's requeue)",
        step=local_step, checkpoint=checkpoint)


class PreemptionGuard:
    """Latched signal flag with handler install/uninstall.

    ``install()`` replaces the handlers (main thread only — a worker
    thread cannot install handlers, and ``install`` says so rather than
    silently not arming).  The previous handlers are restored by
    ``uninstall()``/context exit; they are NOT chained on delivery —
    for SIGTERM the inherited handler is usually "terminate", which is
    exactly what the guard exists to prevent.
    """

    def __init__(self, signals: Union[str, Iterable] = ("SIGTERM",)):
        self.signals = parse_signals(signals)
        self._event = threading.Event()
        self._prev: Dict[int, object] = {}
        self.signum: Optional[int] = None
        self.received_at: Optional[float] = None

    # -- the handler (async-signal-safe: flag only) ---------------------------

    def _handler(self, signum, frame) -> None:
        if self.signum is None:
            self.signum = signum
            self.received_at = time.time()
        self._event.set()

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def signal_name(self) -> Optional[str]:
        if self.signum is None:
            return None
        try:
            return signal.Signals(self.signum).name
        except ValueError:
            return str(self.signum)

    # -- lifecycle ------------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        """Install handlers for every configured signal.  Exception-safe:
        a failure part-way (e.g. not on the main thread) restores the
        handlers already swapped before re-raising — a guard that nobody
        will ever poll must not keep eating SIGTERM."""
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
        except BaseException:
            self.uninstall()
            raise
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError, OSError):  # gan4j-lint: disable=swallowed-exception — interpreter teardown / non-main thread: handlers are already gone
                pass
        self._prev.clear()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
