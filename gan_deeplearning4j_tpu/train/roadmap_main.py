"""Roadmap model-family trainer — BASELINE.json configs 3-5 as a CLI.

The reference ships only the two DL4J workloads; BASELINE.json's roadmap
names three more families this framework must carry: conditional GAN on
CIFAR-10, WGAN-GP (the second-order stress test DL4J/SameDiff could not
express), and CelebA-64 DCGAN multi-replica.  This main trains any of
them end-to-end on the idiomatic two-pytree ``GANPair`` engine (no
stacked graph, no weight copies — train/gan_pair.py) over deterministic
synthetic surrogates (data/datasets.py; no network egress), dumping
per-cadence sample-grid PNGs and JSONL metrics.

Run: ``python -m gan_deeplearning4j_tpu.train.roadmap_main --family
cgan-cifar10 --iterations 2000``
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.graph import serialization
from gan_deeplearning4j_tpu.runtime import prng
from gan_deeplearning4j_tpu.telemetry import MetricsRegistry, events
from gan_deeplearning4j_tpu.train.gan_pair import GANPair
from gan_deeplearning4j_tpu.utils import (
    MetricsLogger,
    device_fence,
    overlap_device_get,
    start_host_copy,
)
from gan_deeplearning4j_tpu.utils.async_dump import AsyncArtifactWriter

FAMILIES = ("cgan-cifar10", "wgan-gp", "celeba")
# the default --batch-size: a named constant because it is part of the
# gan4j-prove bucket-coverage contract (analysis/program.py
# reachable_pair_batches) — changing it requires a contract diff
DEFAULT_BATCH_SIZE = 128


SAMPLE_SHAPES = {
    "cgan-cifar10": (3, 32, 32),
    "wgan-gp": (1, 28, 28),
    "celeba": (3, 64, 64),
}


def _build(family: str, mesh, num_classes: int = None,
           lr_decay_steps: int = None, ms_weight: float = 0.0):
    if lr_decay_steps is not None and lr_decay_steps <= 0:
        raise ValueError(f"--lr-decay-steps must be positive, "
                         f"got {lr_decay_steps}")
    if lr_decay_steps and family not in ("cgan-cifar10", "celeba"):
        raise ValueError("--lr-decay-steps is currently wired for "
                         "cgan-cifar10 and celeba only")
    if ms_weight and family not in ("cgan-cifar10", "celeba"):
        raise ValueError("--ms-weight is currently wired for "
                         "cgan-cifar10 and celeba only")
    if family == "cgan-cifar10":
        import dataclasses

        from gan_deeplearning4j_tpu.models import cgan_cifar10 as M

        cfg = M.CGANConfig()
        if num_classes is not None and num_classes != cfg.num_classes:
            # the label input's width must match the dataset's class count
            # (a real --data-dir tree can have any number of class dirs)
            cfg = dataclasses.replace(cfg, num_classes=num_classes)
        if lr_decay_steps:
            cfg = dataclasses.replace(cfg, decay_steps=lr_decay_steps)
        if ms_weight:
            cfg = dataclasses.replace(cfg, ms_weight=ms_weight)
        pair = GANPair(M.build_generator(cfg), M.build_discriminator(cfg),
                       mesh=mesh, ms_weight=cfg.ms_weight)
        return pair, cfg, (cfg.channels, cfg.height, cfg.width)
    if family == "wgan-gp":
        from gan_deeplearning4j_tpu.models import wgan_gp as M

        cfg = M.WGANGPConfig()
        pair = GANPair(M.build_generator(cfg), M.build_critic(cfg),
                       mode="wgan-gp", gp_weight=cfg.gp_weight, mesh=mesh)
        return pair, cfg, (cfg.channels, cfg.height, cfg.width)
    if family == "celeba":
        import dataclasses

        from gan_deeplearning4j_tpu.models import dcgan_celeba as M

        cfg = M.CelebAConfig()
        if lr_decay_steps:
            cfg = dataclasses.replace(cfg, decay_steps=lr_decay_steps)
        if ms_weight:
            cfg = dataclasses.replace(cfg, ms_weight=ms_weight)
        pair = GANPair(M.build_generator(cfg), M.build_discriminator(cfg),
                       mesh=mesh, ms_weight=cfg.ms_weight)
        return pair, cfg, (cfg.channels, cfg.height, cfg.width)
    raise ValueError(f"unknown family {family!r}; choose from {FAMILIES}")


def _data(family: str, n: int, seed: int, sample_shape=None,
          data_dir: str = None):
    """(features[n, C*H*W], onehot_labels[n, 10] or None), tanh range
    except wgan-gp (sigmoid generator head -> [0, 1] data).

    ``data_dir``: directory of real images (DataVec-style
    ``dir/<class>/img.png`` for the conditional family, flat images
    otherwise) read via data/images.py; default = the synthetic
    surrogates (no network egress in this environment)."""
    from gan_deeplearning4j_tpu.data import datasets

    if data_dir:
        from gan_deeplearning4j_tpu.data.images import ImageRecordReader

        c, h, w = sample_shape
        reader = ImageRecordReader(h, w, c, tanh_range=(family != "wgan-gp"))
        x, labels, classes = reader.read_folder(data_dir, limit=n)
        if family == "cgan-cifar10":
            if labels is None:
                raise ValueError(
                    "cgan-cifar10 needs class subdirectories in --data-dir")
            return x, np.eye(len(classes), dtype=np.float32)[labels]
        return x, None
    if family == "cgan-cifar10":
        # calibrated tier (r5): label-preserving ambiguous tail puts the
        # probe's Bayes ceiling at ~0.96, so conditional_fidelity cannot
        # saturate at 1.000 (VERDICT r4 #4)
        x, y = datasets.synthetic_cifar10(n, seed=seed,
                                          difficulty="calibrated")
        return x, np.eye(10, dtype=np.float32)[y]
    if family == "wgan-gp":
        x, _ = datasets.synthetic_mnist(n, seed=seed)
        return x.astype(np.float32), None
    return datasets.synthetic_celeba(n, seed=seed), None


def train(family: str, iterations: int, batch_size: int, res_path: str,
          n_train: int, print_every: int, n_devices=None,
          data_dir: str = None, ema_decay: float = 0.0,
          checkpoint_every: int = 0, checkpoint_keep: int = 3,
          resume: bool = False,
          steps_per_call: int = None, lr_decay_steps: int = None,
          ms_weight: float = 0.0, fidelity_steps: int = 400,
          async_checkpoint: bool = False, preempt_signals: str = None,
          metrics_port: int = None, log=print) -> Dict[str, float]:
    """Train one roadmap family end to end.  ``async_checkpoint`` /
    ``preempt_signals`` carry the protocol trainer's fault-tolerance
    semantics (docs/FAULT_TOLERANCE.md): background-serialized
    manifest-verified checkpoints, and signal-triggered emergency save +
    resumable marker + ``PreemptionError``.  The run records its event
    timeline to ``res_path/events.jsonl`` (telemetry/events.py) and,
    with ``metrics_port`` (0 = ephemeral), serves /metrics + /healthz
    for the duration (telemetry/exporter.py) — the same observability
    contract as the protocol trainer."""
    guard = None
    if preempt_signals:
        from gan_deeplearning4j_tpu.train.preemption import PreemptionGuard

        guard = PreemptionGuard(preempt_signals)
        try:
            guard.install()
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "preempt_signals configured but not on the main thread; "
                "preemption guard NOT armed")
            guard = None
    os.makedirs(res_path, exist_ok=True)
    registry = MetricsRegistry()
    # setup failures (EADDRINUSE, unwritable events file) must still
    # tear down whatever was already installed — everything after the
    # guard lives in the try
    recorder = None
    prev_recorder = None
    stop_exporter = None
    try:
        # a resumed run APPENDS to its event history, same discipline
        # as the metrics JSONL
        recorder = events.EventRecorder(
            path=os.path.join(res_path, events.EVENTS_NAME),
            append=resume)
        prev_recorder = events.install(recorder)
        if metrics_port is not None:
            from gan_deeplearning4j_tpu.telemetry import serve_exporter

            stop_exporter = serve_exporter(registry, metrics_port)
            log(f"[metrics] serving /metrics + /healthz on "
                f"http://127.0.0.1:{stop_exporter.port}")
        return _train_impl(
            family, iterations, batch_size, res_path, n_train, print_every,
            n_devices, data_dir, ema_decay, checkpoint_every,
            checkpoint_keep, resume, steps_per_call, lr_decay_steps,
            ms_weight, fidelity_steps, async_checkpoint, guard, registry,
            log)
    finally:
        if stop_exporter is not None:
            stop_exporter()
        if prev_recorder is not None:
            events.install(prev_recorder)
        if recorder is not None:
            recorder.close()
        if guard is not None:
            guard.uninstall()


def _train_impl(family, iterations, batch_size, res_path, n_train,
                print_every, n_devices, data_dir, ema_decay,
                checkpoint_every, checkpoint_keep, resume, steps_per_call,
                lr_decay_steps, ms_weight, fidelity_steps,
                async_checkpoint, guard, registry, log) -> Dict[str, float]:
    from gan_deeplearning4j_tpu.telemetry import (
        GoodputTimer,
        write_run_manifest,
    )

    os.makedirs(res_path, exist_ok=True)
    mesh = None
    if n_devices and n_devices > 1:
        from gan_deeplearning4j_tpu.parallel import data_mesh

        mesh = data_mesh(n_devices)
    # goodput + manifest: same run-attribution ledger as the protocol
    # trainer (telemetry/goodput.py) — the GANPair loop's wall seconds
    # land in the same phase vocabulary
    goodput = GoodputTimer()
    manifest = write_run_manifest(
        res_path, config={"family": family, "iterations": iterations,
                          "batch_size": batch_size, "n_train": n_train,
                          "ema_decay": ema_decay,
                          "steps_per_call": steps_per_call},
        mesh=mesh, extra={"workload": family})
    events.current().run_id = manifest["run_id"]
    registry.run_id = manifest["run_id"]
    registry.observe_goodput(goodput.report)
    events.instant("train.start", workload=family)
    # data first: a real --data-dir can dictate the class count the
    # conditional model's label input must match
    with goodput.phase("data_wait"):
        x, y = _data(family, n_train, prng.NUMBER_OF_THE_BEAST,
                     SAMPLE_SHAPES[family], data_dir)
    n_train = x.shape[0]
    pair, cfg, sample_shape = _build(
        family, mesh, num_classes=None if y is None else y.shape[1],
        lr_decay_steps=lr_decay_steps, ms_weight=ms_weight)
    n_critic = getattr(cfg, "n_critic", 1)

    root = prng.root_key(cfg.seed)
    z_key = prng.stream(root, "roadmap-z")
    # fixed evaluation grid (8x8) like the reference's latent-grid dumps;
    # drawn from the TRAINING latent law U[-1,1] (a normal draw would put
    # ~1/3 of components outside the trained support and misrepresent
    # sample quality)
    z_eval = jax.random.uniform(prng.stream(root, "eval-z"),
                                (64, cfg.z_size), dtype=jnp.float32,
                                minval=-1.0, maxval=1.0)
    eval_cond = None
    if y is not None:
        k = y.shape[1]
        eval_cond = jnp.asarray(
            np.eye(k, dtype=np.float32)[np.arange(64) % k])

    real_label = (getattr(cfg, "real_label", 1.0)
                  if pair.mode == "gan" else 1.0)

    # the with-block guarantees queued sample PNGs land on disk (or
    # their error surfaces) even when training raises mid-run
    with AsyncArtifactWriter() as dumper:

        def dump_samples(it: int) -> None:
            from gan_deeplearning4j_tpu.eval.plots import save_rgb_grid_png

            eval_in = {"z": z_eval}
            if eval_cond is not None:
                eval_in["label"] = eval_cond
            # dispatch on the training thread (step-it snapshot); readback +
            # PNG encode run on the artifact-writer thread
            samples = pair.gen.output(
                *[eval_in[k] for k in pair.gen.input_names])[0]
            vrange = (0.0, 1.0) if family == "wgan-gp" else (-1.0, 1.0)
            path = os.path.join(res_path, f"{family}_samples_{it}.png")
            start_host_copy(samples)

            def write(samples=samples, path=path):
                save_rgb_grid_png(path, np.asarray(samples).reshape(64, -1),
                                  sample_shape, value_range=vrange)

            dumper.submit(write)

        steady_t0 = None
        steady_start = 0
        d_loss = g_loss = jnp.zeros(())
        # fused multi-iteration fast path: ONE dispatch per K iterations
        # (dispatch latency otherwise bounds the loop — same rationale
        # as the protocol trainer's steps_per_call); under a mesh the
        # scan is one shard_map SPMD program (GANPair.make_multistep)
        import math

        from gan_deeplearning4j_tpu.train.fused_step import (
            MAX_STEPS_PER_CALL,
        )

        ckpt = None
        start_it = 0
        if checkpoint_every or resume or guard is not None:
            from gan_deeplearning4j_tpu.checkpoint import (
                AsyncCheckpointer,
                NoVerifiedCheckpointError,
                TrainCheckpointer,
            )

            ckpt = TrainCheckpointer(os.path.join(res_path,
                                                  f"{family}_ckpt"),
                                     keep=checkpoint_keep)
            if async_checkpoint:
                ckpt = AsyncCheckpointer(ckpt)
            if resume:
                from gan_deeplearning4j_tpu.train.preemption import (
                    MARKER_NAME,
                )

                marker = os.path.join(res_path, MARKER_NAME)
                if os.path.exists(marker):
                    log(f"[{family}] resuming a preempted run "
                        f"(consuming {marker})")
                    os.remove(marker)
                try:
                    start_it, extra = ckpt.restore(
                        {"gen": pair.gen, "dis": pair.dis})
                except NoVerifiedCheckpointError:
                    start_it, extra = 0, {}
                    log(f"[{family}] resume requested but no verified "
                        "checkpoint; starting from iteration 0")
                if "ema" in extra:
                    if not ema_decay:
                        raise ValueError(
                            "checkpoint carries a generator EMA but "
                            "--ema-decay is 0: pass the original decay "
                            "(resuming without it would freeze the EMA "
                            "and mislabel the final gen_ema artifacts)")
                    pair.gen.ema_params = extra["ema"]
                if start_it:
                    log(f"[{family}] resumed from checkpoint at "
                        f"iteration {start_it}")

        # the resumed run APPENDS to its own metrics history rather than
        # truncating the pre-crash records; every materialized record
        # also feeds the scrape registry (on the logger's worker thread)
        metrics = MetricsLogger(
            os.path.join(res_path, f"{family}_metrics.jsonl"),
            append=start_it > 0, on_record=registry.observe_record)

        g = math.gcd(math.gcd(iterations, print_every), 100)
        if checkpoint_every:
            g = math.gcd(g, checkpoint_every)  # chunks end on ckpt points
        if start_it:
            # chunks must also tile [start_it, iterations] exactly, even
            # when this run's flags differ from the pre-crash run's
            g = math.gcd(g, start_it)
        cap = min(MAX_STEPS_PER_CALL, steps_per_call or MAX_STEPS_PER_CALL)
        K = max(d for d in range(1, min(cap, g) + 1) if g % d == 0)

        def save_ckpt(it: int) -> str:
            # EMA rides as a pytree extra (write_model only carries
            # params+updater); the counter-based z stream makes saved-RNG
            # state unnecessary (start_step seeds the draws)
            extra = {}
            ema = getattr(pair.gen, "ema_params", None)
            if ema is not None:
                extra["ema"] = ema
            with events.span("checkpoint.save", step=it):
                return ckpt.save(it, {"gen": pair.gen, "dis": pair.dis},
                                 extra=extra)

        step_fn, state = pair.make_multistep(
            jnp.asarray(x), None if y is None else jnp.asarray(y),
            batch_size=batch_size, steps_per_call=K, n_critic=n_critic,
            real_label=real_label, z_size=cfg.z_size,
            seed_key=z_key, ema_decay=ema_decay, start_step=start_it)
        it = start_it
        while it < iterations:
            with goodput.phase("dispatch"), \
                    events.span("train.chunk", step=it, n=K):
                state, (dl, gl) = step_fn(state)
            if steady_t0 is None:
                with goodput.phase("readback"):
                    device_fence((dl, gl))
                steady_t0 = time.perf_counter()
                steady_start = it + K
            # per-step LOSSES are real; per-step wall-clock is not (K
            # steps land in one dispatch), so omit examples — the
            # run-level examples_per_sec in the result is the throughput
            # record.  ONE chunk record keeps the (K,) loss arrays
            # stacked on device (per-step slicing is host work that
            # scales with steps — see MetricsLogger.log_chunk).
            metrics.log_chunk(it + 1, K, 0, {"d_loss": dl, "g_loss": gl})
            it += K
            d_loss, g_loss = dl[-1], gl[-1]
            if it % 100 == 0:
                # print-cadence readback: overlapped (one tunnel round
                # trip for both scalars), never per-iteration
                d_host, g_host = overlap_device_get((d_loss, g_loss))
                log(f"[{family}] iteration {it}: d={d_host:.4f} "
                    f"g={g_host:.4f}")
            if it % print_every == 0 or it >= iterations:
                pair.adopt_state(state)
                with goodput.phase("eval"), \
                        events.span("eval.samples", step=it):
                    dump_samples(it)
            if ckpt is not None and checkpoint_every \
                    and it % checkpoint_every == 0:
                pair.adopt_state(state)
                with goodput.phase("checkpoint"):
                    dumper.flush()  # pending artifacts land first
                    save_ckpt(it)
            if guard is not None:
                # chunk finished: the consensus poll runs on EVERY host
                # each chunk while armed (a conditionally-entered
                # collective would strand a partially-signaled fleet);
                # any triggered host preempts the whole fleet through
                # the shared exit protocol (train/preemption.py)
                if jax.process_count() > 1:
                    from gan_deeplearning4j_tpu.parallel import multihost

                    any_trig, agreed = multihost.agree_preemption(
                        guard.triggered, it)
                else:
                    any_trig, agreed = guard.triggered, it
                if any_trig:
                    from gan_deeplearning4j_tpu.train.preemption import (
                        preempt_exit,
                    )

                    pair.adopt_state(state)
                    with goodput.phase("checkpoint"):
                        dumper.flush()
                        path = save_ckpt(it)
                        w = getattr(ckpt, "wait", None)
                        if w is not None:
                            w()  # emergency saves must be durable
                    preempt_exit(res_path, guard, local_step=it,
                                 fleet_min_step=agreed, checkpoint=path,
                                 run_id=manifest["run_id"])
        pair.adopt_state(state)
        iterations = it
        if getattr(pair.gen, "ema_params", None) is not None:
            # final grid from the trajectory-averaged weights too
            orig = pair.gen.params
            pair.gen.params = pair.gen.ema_params
            try:
                dump_samples("ema")
            finally:
                pair.gen.params = orig

    with goodput.phase("readback"):
        device_fence((d_loss, g_loss))
    steps_timed = iterations - steady_start if steady_t0 is not None else 0
    wall = (time.perf_counter() - steady_t0) if steady_t0 is not None else 0.0
    # drain the logger before closing the ledger (the final flush's
    # readback belongs in the breakdown); the closed logger then writes
    # the goodput record synchronously
    with goodput.phase("readback"):
        metrics.flush(wait=True)
        metrics.close()
    if ckpt is not None:
        ckpt_wait = getattr(ckpt, "wait", None)
        if ckpt_wait is not None:
            # exit barrier: queued async saves become durable before the
            # run reports success
            with goodput.phase("checkpoint"):
                ckpt_wait()
    gp = goodput.report()
    metrics.log_record({"goodput": gp, "run_id": manifest["run_id"]})
    metrics.flush()
    events.instant("train.end", step=iterations)
    for name, graph in (("gen", pair.gen), ("dis", pair.dis)):
        serialization.write_model(
            graph, os.path.join(res_path, f"{family}_{name}_model.zip"))
    if getattr(pair.gen, "ema_params", None) is not None:
        orig = pair.gen.params
        pair.gen.params = pair.gen.ema_params
        try:
            # inference-only artifact: the live Adam moments don't belong
            # to the averaged weights
            serialization.write_model(pair.gen, os.path.join(
                res_path, f"{family}_gen_ema_model.zip"),
                save_updater=False)
        finally:
            pair.gen.params = orig
    result = {
        "family": family,
        "steps": iterations,
        "d_loss": float(d_loss),
        "g_loss": float(g_loss),
        "examples_per_sec": (
            steps_timed * batch_size * (n_critic + 1) / wall
            if steps_timed > 0 else 0.0),
        "run_id": manifest["run_id"],
        "goodput": gp,
    }
    if y is not None and fidelity_steps > 0:
        # conditional fidelity (VERDICT r3 weak-#3's falsifiable gate):
        # probe-classifier label agreement of conditioned samples — a
        # class-collapsed generator scores ~1/K regardless of how sharp
        # its surviving modes look
        from gan_deeplearning4j_tpu.eval.conditional import (
            conditional_fidelity,
        )

        fid = conditional_fidelity(
            pair.gen, x, y, sample_shape=sample_shape, z_size=cfg.z_size,
            probe_steps=fidelity_steps)
        result["conditional_fidelity"] = fid["fidelity"]
        result["fidelity_per_class"] = fid["per_class"]
        result["probe_train_acc"] = fid["probe_train_acc"]
        log(f"[{family}] conditional fidelity {fid['fidelity']:.3f} "
            f"(probe train acc {fid['probe_train_acc']:.3f}); per-class "
            + " ".join(f"{v:.2f}" for v in fid["per_class"]))
        if getattr(pair.gen, "ema_params", None) is not None:
            # same (x, y, seed) -> reuse the trained probe, don't retrain
            fid_ema = conditional_fidelity(
                pair.gen, x, y, sample_shape=sample_shape,
                z_size=cfg.z_size, probe_steps=fidelity_steps,
                use_ema=True, probe=fid["probe"])
            result["conditional_fidelity_ema"] = fid_ema["fidelity"]
        if family == "cgan-cifar10" and int(np.bincount(
                np.argmax(y, axis=1), minlength=y.shape[1]).min()) >= 50:
            # the non-saturating companions (frozen 32x32 space): per-
            # class FID + intra-class diversity keep discriminating when
            # agreement hits the probe ceiling.  Skipped for toy runs
            # (< 50 real rows in some class): a covariance over a
            # handful of samples is degenerate, not a metric.
            from gan_deeplearning4j_tpu.eval.conditional import (
                conditional_class_metrics,
            )

            cm = conditional_class_metrics(
                pair.gen, x, y, sample_shape=sample_shape,
                z_size=cfg.z_size)
            result["per_class_fid"] = cm["per_class_fid"]
            result["mean_class_fid"] = cm["mean_class_fid"]
            result["diversity_ratio"] = cm["mean_diversity_ratio"]
            log(f"[{family}] per-class frozen FID mean "
                f"{cm['mean_class_fid']:.2f} "
                + " ".join(f"{v:.1f}" for v in cm["per_class_fid"])
                + f"; diversity ratio {cm['mean_diversity_ratio']:.3f}")
            if getattr(pair.gen, "ema_params", None) is not None:
                cme = conditional_class_metrics(
                    pair.gen, x, y, sample_shape=sample_shape,
                    z_size=cfg.z_size, use_ema=True,
                    real_features=cm["_real_features"])
                result["mean_class_fid_ema"] = cme["mean_class_fid"]
                result["diversity_ratio_ema"] = \
                    cme["mean_diversity_ratio"]
    return result


def main(argv=None) -> Dict[str, float]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", choices=FAMILIES, required=True)
    p.add_argument("--iterations", type=int, default=2000)
    p.add_argument("--batch-size", type=int, default=DEFAULT_BATCH_SIZE)
    p.add_argument("--res-path", default=None)
    p.add_argument("--n-train", type=int, default=10000)
    p.add_argument("--print-every", type=int, default=500)
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument("--data-dir", default=None,
                   help="directory of real images (class subdirs for the "
                        "conditional family) instead of the synthetic "
                        "surrogate")
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="cap on lax.scan iterations per XLA dispatch "
                        "(None = auto, up to 100; use a small value on "
                        "CPU hosts where big scanned chunks stall)")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="periodic atomic checkpoints every N iterations "
                        "(aligned to scan chunks)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest VERIFIED checkpoint in "
                        "res-path (torn/corrupt ones are skipped)")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="serialize/fsync checkpoints on a background "
                        "worker — the training thread pays only the host "
                        "snapshot; identical on-disk bytes")
    p.add_argument("--preempt-signal", action="append", default=None,
                   metavar="SIG",
                   help="signal name (e.g. SIGTERM; repeatable) that "
                        "triggers an emergency checkpoint + resumable "
                        "PREEMPTED.json marker, then exit code 75 "
                        "(EX_TEMPFAIL) — requeue and resume with --resume")
    p.add_argument("--lr-decay-steps", type=int, default=None,
                   help="hold-then-decay LR horizon for both networks "
                        "(cgan-cifar10; mitigates but does not fix the "
                        "measured 5k conditional collapse — RESULTS §6)")
    p.add_argument("--ms-weight", type=float, default=0.0,
                   help="mode-seeking regularizer weight (MSGAN) for the "
                        "conditional family; counters within-class mode "
                        "shrinkage (RESULTS r5)")
    p.add_argument("--fidelity-steps", type=int, default=400,
                   help="probe-classifier training steps for the "
                        "conditional-fidelity metric (conditional "
                        "families; 0 disables)")
    p.add_argument("--ema-decay", type=float, default=0.0,
                   help="generator weight EMA decay (e.g. 0.999): the "
                        "final sample grid is also rendered from the "
                        "trajectory-averaged weights")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into "
                        "DIR and print its top time sinks at exit "
                        "(same contract as the protocol mains)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve /metrics (Prometheus text: step/loss/"
                        "goodput series) + /healthz on this port for "
                        "the duration of training (0 = ephemeral; "
                        "docs/OBSERVABILITY.md)")
    from gan_deeplearning4j_tpu.runtime import backend

    backend.add_bf16_flag(p)
    backend.add_mp_flag(p)
    args = p.parse_args(argv)
    if args.bf16:
        backend.configure(matmul_bf16=True)
    if args.mp:
        backend.configure(compute_bf16=True)
    res = args.res_path or os.path.join("outputs", args.family)
    from gan_deeplearning4j_tpu.train.preemption import PreemptionError
    from gan_deeplearning4j_tpu.utils import maybe_trace, print_trace_summary

    try:
        with maybe_trace(args.profile):
            result = train(
                args.family, args.iterations, args.batch_size, res,
                args.n_train, args.print_every, args.n_devices,
                data_dir=args.data_dir, ema_decay=args.ema_decay,
                checkpoint_every=args.checkpoint_every,
                resume=args.resume,
                steps_per_call=args.steps_per_call,
                lr_decay_steps=args.lr_decay_steps,
                ms_weight=args.ms_weight,
                fidelity_steps=args.fidelity_steps,
                async_checkpoint=args.async_checkpoint,
                preempt_signals=(",".join(args.preempt_signal)
                                 if args.preempt_signal else None),
                metrics_port=args.metrics_port)
        if args.profile:
            # where the step time went, without leaving the terminal
            # (matching cv_main / insurance_main)
            print_trace_summary(args.profile)
    except PreemptionError as e:
        # the emergency checkpoint is durable; report the resumable state
        # instead of a traceback (cli() exits 75 so the scheduler requeues)
        result = {"family": args.family, "preempted": True,
                  "step": e.step, "checkpoint": e.checkpoint,
                  "res_path": res}
    import json

    # one JSON line (numpy scalars coerced) — machine-consumable, cf.
    # bench.py and benchmarks/acceptance.py
    print(json.dumps(result, default=float))
    return result


def cli(argv=None) -> None:
    """Console-script / python -m entry: honor JAX_PLATFORMS — a fresh
    process by definition, so this cannot clobber an in-process override
    (unlike main(), which tests import and call under a conftest-forced
    CPU platform).  A preempted run exits 75 (EX_TEMPFAIL)."""
    import sys

    from gan_deeplearning4j_tpu.runtime import backend as _backend
    from gan_deeplearning4j_tpu.train.preemption import EXIT_PREEMPTED

    _backend.apply_env_platform()
    result = main(argv)
    if result.get("preempted"):
        sys.exit(EXIT_PREEMPTED)


if __name__ == "__main__":
    cli()
