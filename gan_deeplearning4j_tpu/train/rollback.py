"""Rollback-with-perturbation — heal a diverging run instead of dying.

``--nan-alarm abort`` is deliberately FATAL in the recovery wrapper:
the data order and the training z-stream are counter-based functions of
the seed and the step index, so a deterministic replay from the last
checkpoint marches straight back into the same NaN (train/
gan_trainer.py:~230).  That logic also shows the way out — make the
replay NOT deterministic.  ``--nan-alarm rollback`` (shared by the
divergence sentinel, train/divergence.py) does three things instead of
raising a fatal error:

1. **restore** the last verified checkpoint from BEFORE the bad step,
   in-process (the trainer raises ``RollbackRequested``; the recovery
   wrapper rebuilds the trainer with ``resume=True`` — no process exit,
   no scheduler round trip — and the resume path restores with
   ``max_step`` excluding the poisoned suffix, then prunes it);
2. **cut the learning rate** by ``lr_factor`` (compounding per
   rollback) — the classic divergence remedy the reference hand-tuned
   around;
3. **advance the noise RNG stream**: the training z-key and the fused
   dropout key are folded with a per-rollback salt, so the replayed
   window draws DIFFERENT latents and the run explores a different
   trajectory out of the basin that produced the blowup.

The budget is progress-aware like the restart budget: a rollback at a
LATER step than the previous one resets the attempt counter (the run is
getting somewhere; each incident taxes it once), while repeated
rollbacks at the same step exhaust ``max_rollbacks`` and escalate to
``RollbackError`` — which the recovery wrapper classifies FATAL, the
same end state ``abort`` reaches immediately.

One ``RollbackManager`` must be shared across every trainer incarnation
of a run (``run_with_recovery`` owns it): the LR scale, the RNG epoch
and the budget all live on it, and a per-incarnation manager would
reset them on every restart — an infinite rollback loop.  Multi-host
fleets agree through ``parallel/multihost.agree_rollback`` (mirrors
``agree_preemption``): every host polls the consensus at each armed
boundary, so one host's alarm rolls the whole fleet back together.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax

_log = logging.getLogger(__name__)

# fold_in salt namespacing the rollback perturbation away from every
# other derived stream (runtime/prng.py folds small indices; step
# folding uses 2*i(+1)) — any large constant works, it just must be
# reserved for this purpose
PERTURB_SALT = 0x5EED_BACC


class RollbackRequested(RuntimeError):
    """The trainer wants an in-process rollback: restore the last
    verified pre-failure checkpoint, apply the manager's perturbation,
    and continue.  ``train_with_recovery`` handles it WITHOUT burning
    the restart budget (the rollback budget is the manager's own)."""

    def __init__(self, msg: str, step: Optional[int] = None,
                 rollbacks: int = 0):
        super().__init__(msg)
        self.step = step
        self.rollbacks = rollbacks


class RollbackError(RuntimeError):
    """The rollback budget is exhausted (same step keeps failing even
    with the LR cut and perturbed noise): escalate to fatal — the same
    end state ``--nan-alarm abort`` reaches immediately, after
    ``max_rollbacks`` genuine healing attempts."""


class RollbackManager:
    """Cross-incarnation rollback state: budget, LR scale, RNG epoch.

    ``request(step, reason, bad_step=...)`` charges the (progress-aware)
    budget and records where the poison starts; ``apply(trainer)`` is
    called by every new trainer incarnation and installs the current
    perturbation — LR scale on all four graphs' updaters, fold-in epoch
    on the z/dropout streams, and the resume bound that keeps the
    restore strictly before the bad step."""

    def __init__(self, max_rollbacks: int = 3, lr_factor: float = 0.5):
        if not 0.0 < lr_factor <= 1.0:
            raise ValueError(
                f"lr_factor must be in (0, 1], got {lr_factor} "
                "(a factor > 1 would amplify the divergence being "
                "healed)")
        if max_rollbacks < 1:
            raise ValueError("max_rollbacks must be >= 1")
        self.max_rollbacks = int(max_rollbacks)
        self.lr_factor = float(lr_factor)
        self.total = 0              # lifetime count: LR compounding + metrics
        self.attempts = 0           # progress-aware budget window
        self.last_step: Optional[int] = None
        self.restore_before: Optional[int] = None
        self.last_reason: Optional[str] = None

    @property
    def lr_scale(self) -> float:
        return self.lr_factor ** self.total

    @property
    def exhausted(self) -> bool:
        return self.attempts > self.max_rollbacks

    def request(self, step: int, reason: str,
                bad_step: Optional[int] = None) -> bool:
        """Charge one rollback at ``step``.  ``bad_step``: the first
        step whose state is known-poisoned (the alarm step); the resume
        restores strictly before it.  Returns False when the budget is
        exhausted (the caller escalates to ``RollbackError``)."""
        if self.last_step is not None and step > self.last_step:
            self.attempts = 0  # progress since the last incident
        self.last_step = step
        self.attempts += 1
        self.total += 1
        self.restore_before = bad_step if bad_step is not None else step
        self.last_reason = reason
        return not self.exhausted

    # -- applying the perturbation --------------------------------------------

    def apply(self, trainer) -> None:
        """Install the current perturbation on a fresh trainer
        incarnation (called from ``GANTrainer.__init__``, before
        anything traces the updaters' LR constants into a program).
        A manager that has never rolled back is a no-op."""
        if not self.total:
            return
        scale = self.lr_scale
        scaled = 0
        for graph in trainer._graphs().values():
            scaled += scale_graph_lr(graph, scale)
        trainer._z_base = perturb_key(trainer._z_base, self.total)
        trainer._fused_rng = perturb_key(trainer._fused_rng, self.total)
        # keep the restore strictly before the known-bad step and let
        # the resume path prune the poisoned suffix once restored
        trainer._resume_max_step = (
            None if self.restore_before is None
            else self.restore_before - 1)
        _log.warning(
            "rollback #%d applied: lr x%.4g on %d layer updaters, noise "
            "stream advanced (epoch %d), resuming before step %s",
            self.total, scale, scaled, self.total, self.restore_before)


def perturb_key(key, epoch: int):
    """Advance a PRNG stream to the ``epoch``-th rollback lineage: the
    replayed window must NOT redraw the latents that produced the
    blowup.  fold_in keeps it a pure function of (seed, epoch) — two
    hosts of a fleet at the same epoch still derive identical streams,
    which the SPMD step requires."""
    return jax.random.fold_in(key, PERTURB_SALT + epoch)


def _scaled_updater(up, scale: float):
    """One layer updater scaled by ``scale``, or None when there is
    nothing to scale (frozen lr-0 layers, unknown kinds).  Handles the
    three updater shapes the stack carries: plain frozen dataclasses
    with a ``learning_rate`` field (RmsProp/Adam/...), ``Scheduled``
    wrappers (``learning_rate`` is a read-only property — the scale
    goes onto the schedule's ``initial_lr``, a pure multiplier in every
    schedule kind, so the WHOLE trajectory scales), and mutable custom
    updaters (setattr)."""
    sched = getattr(up, "schedule", None)
    if sched is not None and getattr(sched, "initial_lr", None):
        return dataclasses.replace(
            up, schedule=dataclasses.replace(
                sched, initial_lr=sched.initial_lr * scale))
    lr = getattr(up, "learning_rate", None)
    if not lr:  # absent or 0.0 (frozen)
        return None
    try:
        return dataclasses.replace(up, learning_rate=lr * scale)
    except (TypeError, ValueError):  # gan4j-lint: disable=swallowed-exception — not a dataclass, or learning_rate not an init field: the mutable-updater path below handles it
        pass
    up.learning_rate = lr * scale  # mutable custom updater
    return up


def scale_graph_lr(graph, scale: float) -> int:
    """Multiply every trainable layer updater's learning rate by
    ``scale`` (frozen lr-0 layers stay frozen).  The updaters are
    frozen dataclasses shared by reference between graphs, so each is
    REPLACED, never mutated.  Returns the number of layer updaters
    rescaled; an updater whose shape defeats scaling is SKIPPED with a
    loud warning — the rollback is the healing path, and crashing it
    over one exotic layer would be worse than a partial LR cut.  Must
    run before the graph's update rule is traced (fresh graphs only):
    the LRs are compile-time constants of the fused program."""
    updater = getattr(graph, "updater", None)
    if updater is None:
        return 0
    ups = updater.layer_updaters
    n = 0
    for name, up in list(ups.items()):
        try:
            scaled = _scaled_updater(up, scale)
        except Exception as e:
            _log.warning(
                "rollback LR cut skipped layer %r (updater %r: %r)",
                name, type(up).__name__, e)
            continue
        if scaled is None:
            continue
        ups[name] = scaled
        n += 1
    return n
