"""The supervision/ops shell, split from the stepped payload.

``GANTrainer.train()`` accreted a careful install/teardown bracket
around its loop — preemption guard, run-scoped event recorder
(installed process-wide), heartbeat watchdog, recompile sentinel,
/metrics + /healthz exporter — with ordering that matters (the
recorder installs FIRST so watchdog timeouts and recompile events land
in this run's timeline; the watchdog disarms FIRST on the way out so
no async raise lands mid-teardown).  The fleet work (ROADMAP item 3)
needs the same shell around a different payload, and duplicating a
correctness-ordered bracket is how duplicates drift — so the bracket
lives here once.

:class:`SupervisionShell` is payload-agnostic: ``GANTrainer`` runs
``_train_impl`` behind it, ``train/fleet_trainer.FleetTrainer`` runs
the fleet loop behind it.  A payload is any zero-arg callable; the
shell guarantees full teardown on every exit path, including setup
failures (EADDRINUSE on the exporter port, an unwritable events file).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional, Sequence, TypeVar

from gan_deeplearning4j_tpu.telemetry import events

T = TypeVar("T")

_log = logging.getLogger(__name__)


class SupervisionShell:
    """Install order (teardown is the exact reverse, watchdog first):

    1. preemption guard (``preempt_signal_nums``) — main-thread only; a
       worker-thread trainer runs unguarded, loudly;
    2. event recorder → ``events.install`` (process-wide current
       recorder for the run: checkpoint workers, prefetch threads and
       collectives land their events in this run's file);
    3. heartbeat watchdog (+ its ``/healthz`` registry feed);
    4. recompile sentinel;
    5. /metrics exporter (resolved port on ``self.metrics_port``).

    After :meth:`run` installs everything it calls ``payload()`` and
    returns its result.  The live handles (``recorder``, ``watchdog``,
    ``sanitizer``, ``guard``, ``metrics_port``) stay readable on the
    shell while the payload runs — and ``recorder`` stays readable
    after exit too, so a recovery wrapper can still dump the flight
    record of a failed run (only the file sink is closed)."""

    def __init__(
        self,
        registry,
        res_path: str,
        *,
        events_enabled: bool = True,
        events_append: bool = False,
        watchdog: bool = False,
        watchdog_deadline_s: Optional[float] = None,
        watchdog_warmup_s: float = 300.0,
        watchdog_scale: float = 20.0,
        watchdog_min_deadline_s: float = 5.0,
        watchdog_on_timeout: Optional[Callable] = None,
        sanitize: bool = False,
        step_fn: Callable[[], int] = lambda: 0,
        metrics_port: Optional[int] = None,
        preempt_signal_nums: Sequence[int] = (),
        log: Callable[[str], None] = print,
    ):
        self._registry = registry
        self._res_path = res_path
        self._events_enabled = events_enabled
        self._events_append = events_append
        self._watchdog_cfg = dict(
            enabled=watchdog, deadline_s=watchdog_deadline_s,
            warmup_s=watchdog_warmup_s, scale=watchdog_scale,
            min_deadline_s=watchdog_min_deadline_s,
            on_timeout=watchdog_on_timeout)
        self._sanitize = sanitize
        self._step_fn = step_fn
        self._metrics_port_cfg = metrics_port
        self._preempt_signal_nums = tuple(preempt_signal_nums or ())
        self._log = log
        # live handles, populated by run() for the payload's duration
        self.recorder: Optional[events.EventRecorder] = None
        self.watchdog = None
        self.sanitizer = None
        self.guard = None
        self.metrics_port: Optional[int] = None

    def run(self, payload: Callable[[], T],
            on_recorder: Optional[Callable] = None) -> T:
        """Bracket ``payload()`` with the full install/teardown.

        ``on_recorder(recorder)`` fires right after the recorder is
        installed (before the watchdog arms) — the hook a trainer uses
        to expose the recorder for post-mortem flight-record dumps even
        when a LATER setup stage (exporter port, watchdog) fails."""
        guard = None
        if self._preempt_signal_nums:
            from gan_deeplearning4j_tpu.train.preemption import (
                PreemptionGuard,
            )

            guard = PreemptionGuard(self._preempt_signal_nums)
            try:
                guard.install()
            except ValueError:
                # signal handlers are a main-thread privilege; a run
                # driven from a worker thread trains unguarded, loudly
                _log.warning(
                    "preempt_signals configured but not on the main "
                    "thread; preemption guard NOT armed")
                guard = None
        self.guard = guard
        prev_recorder = None
        stop_exporter = None
        try:
            # a resumed run APPENDS to its own event history (same
            # discipline as the metrics JSONL): the pre-crash timeline
            # is exactly what a post-mortem overlay wants to keep
            self.recorder = events.EventRecorder(
                path=(os.path.join(self._res_path, events.EVENTS_NAME)
                      if self._events_enabled else None),
                enabled=self._events_enabled, append=self._events_append)
            prev_recorder = events.install(self.recorder)
            if on_recorder is not None:
                on_recorder(self.recorder)
            wd = self._watchdog_cfg
            if wd["enabled"]:
                # armed AFTER the recorder install so the timeout event
                # and flight record land in this run's timeline
                from gan_deeplearning4j_tpu.train.watchdog import (
                    HeartbeatWatchdog,
                )

                self.watchdog = HeartbeatWatchdog(
                    deadline_s=wd["deadline_s"],
                    warmup_s=wd["warmup_s"],
                    scale=wd["scale"],
                    min_deadline_s=wd["min_deadline_s"],
                    on_timeout=wd["on_timeout"],
                    res_path=self._res_path)
                self.watchdog.start()
                self._registry.observe_watchdog(self.watchdog.report)
            if self._sanitize:
                # armed AFTER the recorder install (compile.recompile
                # events must land in this run's timeline); passive
                # until the payload marks steady state
                from gan_deeplearning4j_tpu.analysis.sanitizers import (
                    RecompileSentinel,
                )

                step_fn = self._step_fn
                self.sanitizer = RecompileSentinel(
                    registry=self._registry,
                    step_fn=step_fn,
                    on_recompile=lambda name: _log.warning(
                        "sanitizer: post-warmup XLA recompile of %r at "
                        "step %d — the hot path lost its cached program "
                        "(see docs/STATIC_ANALYSIS.md)",
                        name, step_fn()))
                self.sanitizer.start()
            if self._metrics_port_cfg is not None:
                from gan_deeplearning4j_tpu.telemetry import serve_exporter

                stop_exporter = serve_exporter(self._registry,
                                               self._metrics_port_cfg)
                self.metrics_port = stop_exporter.port
                self._log(f"[metrics] serving /metrics + /healthz on "
                          f"http://127.0.0.1:{stop_exporter.port}")
            return payload()
        finally:
            if self.watchdog is not None:
                # disarm FIRST: no async raise may land while the
                # teardown below runs (stop() joins the poll thread)
                self.watchdog.stop()
                self.watchdog = None
            if self.sanitizer is not None:
                self.sanitizer.stop()
                self.sanitizer = None
            if stop_exporter is not None:
                stop_exporter()
            if prev_recorder is not None:
                events.install(prev_recorder)
            if self.recorder is not None:
                # close the file sink only — the ring stays readable for
                # post-mortem flight-record dumps
                self.recorder.close()
            if guard is not None:
                guard.uninstall()
            self.guard = None
