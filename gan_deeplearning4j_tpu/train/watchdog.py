"""Hang watchdog — turn a silent stall into a retryable failure.

The recovery loop (train/gan_trainer.py ``train_with_recovery``) only
fires on EXCEPTIONS.  A wedged collective, a data source that never
returns from ``next()``, or a device readback that never completes
raises nothing — the run just stops making progress forever, which at
fleet scale is worse than a crash (a crash at least frees the
accelerator).  ``HeartbeatWatchdog`` closes that gap:

* the training thread **beats** at every step/chunk boundary and at the
  entry/exit of every blocking region (the trainer routes its goodput
  phases — data wait, dispatch, readback, checkpoint, eval — through
  ``region()``, so the region name in flight is always known);
* a daemon thread checks the age of the last beat against a deadline
  **auto-scaled from the measured steady-state inter-beat interval**
  (``scale`` x a robust EWMA, floored at ``min_deadline_s``) — a run
  whose chunks legitimately take 30s gets a proportionally longer leash
  than one stepping every 10ms.  Until enough intervals are measured
  (XLA compile pays its one-off cost here) the generous ``warmup_s``
  deadline applies.  An explicit ``deadline_s`` overrides auto-scaling.

On expiry the watchdog, in order: records a ``watchdog.timeout``
instant and dumps the flight-recorder ring (telemetry/events.py) while
the stalled state is still in it; runs the ``on_timeout`` callback (the
trainer passes its best-effort emergency checkpoint) on a SACRIFICIAL
thread with a bounded join — if the device is the thing that hung, the
save hangs with it and is abandoned, never the watchdog; then raises
``WatchdogTimeout`` **on the monitored thread** via
``PyThreadState_SetAsyncExc``, so the hang unwinds like any other
retryable failure and ``train_with_recovery`` restarts from the latest
checkpoint.

Async-raise reaches the target thread at its next bytecode boundary —
a thread blocked inside a C call does not see it until that call
returns.  The stack's own blocking waits are therefore written as
bounded polls (``data/prefetch.py`` ``__next__`` re-arms a 0.25s
``queue.get`` in a loop), which converts "blocked in C forever" into
"interruptible within a poll tick".  The raise is re-attempted a few
times (``max_raises``) in case the first lands while the thread is
briefly inside such a call.

The exporter integration (``MetricsRegistry.observe_watchdog``) serves
the same signal outward: ``/healthz`` flips to 503 + ``"stalled": true``
as soon as the heartbeat goes quiet past the deadline, and the
``gan4j_watchdog_*`` series carry the beat age / deadline / timeout
count (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import ctypes
import logging
import threading
import time
from typing import Callable, Dict, Optional

_log = logging.getLogger(__name__)


class WatchdogTimeout(RuntimeError):
    """No heartbeat landed within the watchdog deadline: the run is
    hung (data source, readback, collective, ...).  Raised ON the
    training thread by the watchdog; ``train_with_recovery`` classifies
    it RETRYABLE — a hang becomes a restart-from-checkpoint, not a
    forever-wedged process.  Diagnostics (region in flight, beat age,
    deadline) are in the ``watchdog.timeout`` event and the
    ``flight_record_watchdog_timeout.json`` dump, not on this
    exception: async-raise delivers a bare exception CLASS."""


def _async_raise(thread_ident: int, exc_type) -> bool:
    """Schedule ``exc_type`` on the thread with ``thread_ident``
    (delivered at its next bytecode boundary).  Returns True when
    exactly one thread state was modified."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), ctypes.py_object(exc_type))
    if res > 1:  # "should never happen" per CPython docs: undo
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident), None)
        return False
    return res == 1


class HeartbeatWatchdog:
    """Deadline supervisor over one monitored (training) thread.

    ``deadline_s``: explicit fixed deadline; None = auto-scale
    (``scale`` x EWMA of inter-beat intervals, floored at
    ``min_deadline_s``; ``warmup_s`` until ``min_intervals`` beats have
    been measured — the XLA-compile allowance).  ``on_timeout``: called
    once on expiry (bounded by ``emergency_timeout_s`` on a sacrificial
    thread).  ``res_path``: where the flight record lands.

    Thread-discipline: only beats from the MONITORED thread count (a
    checkpoint worker or the emergency-save thread reporting progress
    must not mask a hung training thread)."""

    # regions that legitimately block for much longer than a steady
    # step the FIRST time they run (a synchronous checkpoint's
    # zip+fsync, a dispatch that pays an XLA compile mid-run): the
    # effective deadline while such a region is open is floored at the
    # region's value — the hang is still detected, just on a leash
    # sized to the region's honest worst case.  data_wait / readback /
    # collective regions (the common hang sites) keep the tight
    # auto-scaled deadline.
    DEFAULT_REGION_FLOORS = {"checkpoint": 120.0, "dispatch": 60.0,
                             "eval": 60.0}

    def __init__(self, deadline_s: Optional[float] = None,
                 warmup_s: float = 300.0, scale: float = 20.0,
                 min_deadline_s: float = 5.0, poll_s: float = 0.25,
                 min_intervals: int = 3,
                 on_timeout: Optional[Callable[[], None]] = None,
                 emergency_timeout_s: float = 30.0,
                 res_path: Optional[str] = None,
                 max_raises: int = 3,
                 region_floors: Optional[Dict[str, float]] = None):
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("watchdog deadline_s must be > 0")
        self.deadline_s = deadline_s
        self.warmup_s = float(warmup_s)
        self.scale = float(scale)
        self.min_deadline_s = float(min_deadline_s)
        self.poll_s = float(poll_s)
        self.min_intervals = int(min_intervals)
        self.on_timeout = on_timeout
        self.emergency_timeout_s = float(emergency_timeout_s)
        self.res_path = res_path
        self.max_raises = int(max_raises)
        self.region_floors = (dict(self.DEFAULT_REGION_FLOORS)
                              if region_floors is None
                              else dict(region_floors))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._monitored_ident: Optional[int] = None
        self._monitored_thread: Optional[threading.Thread] = None
        self._last_beat: Optional[float] = None
        self._last_step: Optional[int] = None
        from collections import deque

        self._samples: "deque" = deque(maxlen=64)
        self._intervals = 0
        self._saw_step_beat = False
        self._region: Optional[str] = None
        self.fired = False
        self.timeouts = 0

    # -- heartbeat (monitored thread) -----------------------------------------

    def beat(self, step: Optional[int] = None) -> None:
        """Record a heartbeat.  Beats from any OTHER thread are ignored
        — progress elsewhere is not progress of the training thread."""
        if self._monitored_ident is not None \
                and threading.get_ident() != self._monitored_ident:
            return
        now = time.perf_counter()
        with self._lock:
            if self._last_beat is not None:
                # rolling MEDIAN of recent inter-beat intervals: robust
                # to the occasional slow outlier (a compile, a sync
                # save) in both directions — the deadline tracks the
                # TYPICAL cadence, `scale` buys the variance
                self._samples.append(now - self._last_beat)
                self._intervals += 1
            self._last_beat = now
            if step is not None:
                # a step-carrying beat means a full protocol step (and
                # therefore the XLA compile the first one pays)
                # completed — the signal that ends the warmup deadline
                self._last_step = step
                self._saw_step_beat = True

    def region(self, name: str):
        """Context manager around a blocking region: beat on entry and
        exit, and remember the region name so a timeout names what was
        in flight."""
        return _Region(self, name)

    # -- deadline math ---------------------------------------------------------

    def effective_deadline(self) -> float:
        with self._lock:
            return self._deadline_locked()

    def _deadline_locked(self) -> float:
        if self.deadline_s is not None:
            # an EXPLICIT deadline is exactly that — the operator's
            # number, not raised by region floors (the config and docs
            # promise "a fixed deadline in seconds"; floors exist to
            # protect the AUTO deadline from legitimately slow regions)
            return self.deadline_s
        floor = 0.0
        if self._region is not None:
            floor = self.region_floors.get(self._region, 0.0)
        # warmup holds until steady state is OBSERVABLE: enough
        # intervals measured AND at least one completed step (the first
        # dispatch pays the XLA compile before any step beat can land —
        # a tight deadline armed from the fast pre-compile beats would
        # false-fire on the compile itself)
        if (self._intervals < self.min_intervals
                or not self._saw_step_beat):
            return max(self.warmup_s, self.min_deadline_s, floor)
        s = sorted(self._samples)
        mid = len(s) // 2
        median = s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])
        return max(self.min_deadline_s, self.scale * median, floor)

    def last_beat_age(self) -> Optional[float]:
        with self._lock:
            if self._last_beat is None:
                return None
            return time.perf_counter() - self._last_beat

    @property
    def stalled(self) -> bool:
        """True once the heartbeat is quiet past the deadline (the
        /healthz 503 signal) — set the instant the deadline passes,
        whether or not the raise has taken effect yet."""
        if self._stop.is_set():
            return False
        with self._lock:
            if self._last_beat is None:
                return False
            age = time.perf_counter() - self._last_beat
            return age > self._deadline_locked()

    def report(self) -> Dict:
        """Scrape feed for ``MetricsRegistry.observe_watchdog``."""
        with self._lock:
            age = (None if self._last_beat is None
                   else time.perf_counter() - self._last_beat)
            deadline = self._deadline_locked()
        return {"last_beat_age_s": age, "deadline_s": deadline,
                "timeouts_total": self.timeouts,
                "stalled": self.stalled, "step": self._last_step}

    # -- lifecycle -------------------------------------------------------------

    def start(self, thread: Optional[threading.Thread] = None
              ) -> "HeartbeatWatchdog":
        """Arm over ``thread`` (default: the calling thread) and start
        the poll loop.  The first beat is implicit — the warmup clock
        starts now, not at the first explicit beat."""
        with self._lock:
            self._monitored_thread = thread or threading.current_thread()
            self._monitored_ident = self._monitored_thread.ident
            self._last_beat = time.perf_counter()
            self._thread = threading.Thread(
                target=self._poll_loop, name="gan4j-watchdog", daemon=True)
            poll_thread = self._thread
        poll_thread.start()
        return self

    def stop(self) -> None:
        """Disarm; no raise is attempted after this returns (the poll
        loop checks the flag immediately before every raise)."""
        self._stop.set()
        with self._lock:
            poll_thread, self._thread = self._thread, None
        if poll_thread is not None:
            # join OUTSIDE the lock: the poll loop takes it every cycle
            poll_thread.join(timeout=self.poll_s * 8 + 1.0)

    def __enter__(self) -> "HeartbeatWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- expiry ----------------------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                if self._last_beat is None:
                    continue
                age = time.perf_counter() - self._last_beat
                deadline = self._deadline_locked()
                region, step = self._region, self._last_step
            if age <= deadline:
                continue
            if not self._monitored_alive():
                return  # the run is already unwinding
            self._fire(age, deadline, region, step)
            return

    def _monitored_alive(self) -> bool:
        t = self._monitored_thread
        return t is not None and t.is_alive()

    def _fire(self, age: float, deadline: float,
              region: Optional[str], step: Optional[int]) -> None:
        from gan_deeplearning4j_tpu.telemetry import events

        with self._lock:
            self.fired = True
            self.timeouts += 1
        _log.error(
            "watchdog: no heartbeat for %.1fs (deadline %.1fs, region "
            "%s, step %s) — dumping flight record and raising "
            "WatchdogTimeout on the training thread",
            age, deadline, region or "?", step)
        try:
            events.instant("watchdog.timeout", step=step, region=region,
                           age_s=round(age, 3),
                           deadline_s=round(deadline, 3))
            if self.res_path:
                events.dump_flight_record(
                    self.res_path, "watchdog_timeout",
                    extra={"step": step, "region": region,
                           "age_s": round(age, 3),
                           "deadline_s": round(deadline, 3)})
        except Exception:  # gan4j-lint: disable=swallowed-exception — diagnostics must never block the raise
            pass
        if self.on_timeout is not None:
            # sacrificial thread: if the DEVICE is what hung, the
            # emergency save hangs on it too — bound it and move on
            done = threading.Event()

            def run() -> None:
                try:
                    self.on_timeout()
                except Exception as e:
                    _log.warning(
                        "watchdog emergency action failed (%r); the "
                        "restart falls back to the last periodic "
                        "checkpoint", e)
                finally:
                    done.set()

            t = threading.Thread(target=run, daemon=True,
                                 name="gan4j-watchdog-emergency")
            t.start()
            if not done.wait(self.emergency_timeout_s):
                _log.warning(
                    "watchdog emergency action still blocked after "
                    "%.0fs — abandoned (the device hang it was racing "
                    "got it too)", self.emergency_timeout_s)
        # raise, then re-raise on a grace cadence in case the first
        # delivery landed while the thread sat inside a C call; a beat
        # (the thread came back to life) or stop() cancels the rest
        for attempt in range(self.max_raises):
            if self._stop.is_set() or not self._monitored_alive():
                return
            with self._lock:
                revived = (self._last_beat is not None
                           and time.perf_counter() - self._last_beat
                           <= deadline)
            if revived:
                return
            _async_raise(self._monitored_ident, WatchdogTimeout)
            if self._stop.wait(max(self.poll_s * 4, 1.0)):
                return


class _Region:
    def __init__(self, wd: HeartbeatWatchdog, name: str):
        self._wd = wd
        self._name = name
        self._prev: Optional[str] = None

    def __enter__(self) -> "_Region":
        wd = self._wd
        if threading.get_ident() == wd._monitored_ident \
                or wd._monitored_ident is None:
            with wd._lock:
                self._prev = wd._region
                wd._region = self._name
        wd.beat()
        return self

    def __exit__(self, *exc) -> None:
        wd = self._wd
        if threading.get_ident() == wd._monitored_ident \
                or wd._monitored_ident is None:
            with wd._lock:
                wd._region = self._prev
        wd.beat()
