"""Utilities: structured metrics/observability + tracing (SURVEY.md §5)."""

from gan_deeplearning4j_tpu.utils.device import (
    device_fence,
    overlap_device_get,
    start_host_copy,
)
from gan_deeplearning4j_tpu.utils.listeners import (
    CollectScoresListener,
    PerformanceListener,
    ScoreIterationListener,
    TrainingListener,
)
from gan_deeplearning4j_tpu.utils.metrics import MetricsLogger
from gan_deeplearning4j_tpu.utils.profiling import (
    maybe_trace,
    print_trace_summary,
    summarize_trace,
)

__all__ = ["MetricsLogger", "maybe_trace", "summarize_trace",
           "print_trace_summary",
           "device_fence", "overlap_device_get", "start_host_copy",
           "TrainingListener", "ScoreIterationListener",
           "PerformanceListener", "CollectScoresListener"]
