"""Utilities: structured metrics/observability (SURVEY.md §5)."""

from gan_deeplearning4j_tpu.utils.metrics import MetricsLogger

__all__ = ["MetricsLogger"]
