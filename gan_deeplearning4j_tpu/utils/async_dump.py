"""Asynchronous artifact writer — overlap readback/CSV IO with training.

The reference writes its periodic artifacts synchronously on the training
thread, element by element (dl4jGANComputerVision.java:479-522 — the §3.3
hot-loop inefficiency SURVEY.md flags).  Here the trainer dispatches the
device computation for an artifact on the main thread (so the values are an
exact snapshot of the params at that step) and hands the *materialization* —
device→host readback plus CSV formatting/writing — to a single background
worker.  On a tunneled PJRT link a readback is a ~70ms round trip; at the
reference's save cadence (every 100 of 10,000 iterations, two artifacts
each) that is seconds of wall clock the device spends idle, which this
thread reclaims.

Snapshot correctness: jax dispatch is async — the arrays enqueued here are
futures tied to the exact program the main thread dispatched before its
next training step, so a late readback still yields step-k values.  The
queue is bounded: each pending job pins its device buffers live, so
backpressure (a blocking ``submit``) caps HBM retention at
``max_pending`` artifacts rather than letting a slow disk grow it.

Failure semantics: a worker exception is captured and re-raised on the
training thread at the next ``submit``/``flush``/``close`` — artifact
failures are not silent (the recovery wrapper in train.gan_trainer then
sees them like any other training fault).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional


class AsyncArtifactWriter:
    """Run zero-arg write jobs on a background thread, in submit order.

    ``synchronous=True`` degrades to running each job inline at ``submit``
    (the reference's behavior, and the fallback for debugging or
    single-threaded environments); the API is identical either way.
    """

    def __init__(self, max_pending: int = 4, synchronous: bool = False):
        self._synchronous = synchronous
        self._error: Optional[BaseException] = None
        if synchronous:
            return
        self._closed = False
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue(
            maxsize=max_pending)
        self._thread = threading.Thread(
            target=self._worker, name="gan4j-artifact-writer", daemon=True)
        self._thread.start()

    # -- worker --------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            job = self._q.get()
            try:
                if job is None:
                    return
                if self._error is None:  # fail fast: skip jobs after error
                    job()
            except BaseException as e:  # noqa: BLE001 — reraised on main thread
                if self._error is None:
                    self._error = e
            finally:
                self._q.task_done()

    def _reraise(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    # -- API -----------------------------------------------------------------

    def submit(self, job: Callable[[], None],
               timeout: float = 600.0) -> None:
        """Enqueue a write job (blocking when ``max_pending`` jobs wait).

        Bounded: a worker wedged on a stalled disk/readback surfaces as
        the same 'artifact writer stalled' RuntimeError that flush()/
        close() raise, instead of deadlocking the training thread at the
        next submit."""
        self._reraise()
        if self._synchronous or self._closed:
            # after close() the worker is gone — run inline rather than
            # letting the job vanish into a dead queue
            job()
            return
        try:
            self._q.put(job, timeout=timeout)
        except queue.Full:
            raise RuntimeError(
                f"artifact writer stalled: queue full ({self._q.maxsize} "
                f"pending) after {timeout:.0f}s") from None

    def _drain(self, timeout: float) -> None:
        """queue.join with a deadline: a hung write job (stalled disk,
        wedged readback) surfaces as a RuntimeError on the training
        thread instead of deadlocking the run."""
        deadline = time.monotonic() + timeout
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"artifact writer stalled: {self._q.unfinished_tasks}"
                        f" job(s) still pending after {timeout:.0f}s")
                self._q.all_tasks_done.wait(remaining)

    def flush(self, timeout: float = 600.0) -> None:
        """Block until every submitted job has run (raising if the worker
        stalls past ``timeout``); surface worker errors."""
        if not self._synchronous:
            self._drain(timeout)
        self._reraise()

    def close(self, timeout: float = 600.0) -> None:
        """Flush, stop the worker, and surface any pending error."""
        if self._synchronous:
            self._reraise()
            return
        if not self._closed:
            # drain BEFORE marking closed: a drain timeout leaves the
            # writer open (the worker may still be wedged on a job), so a
            # retry of close() drains again instead of silently
            # succeeding while jobs are pending — and submit() keeps
            # queueing rather than racing the stuck worker inline
            self._drain(timeout)
            self._closed = True
            self._q.put(None)
            self._thread.join(timeout=10)
        self._reraise()

    def __enter__(self) -> "AsyncArtifactWriter":
        return self

    def __exit__(self, *exc) -> None:
        # on an exception in the with-body, still drain (artifacts already
        # snapshotted are valid) but let the body's exception win
        try:
            self.close()
        except BaseException:
            if exc == (None, None, None):
                raise
