"""Device↔host transfer helpers for high-latency (tunneled) PJRT links.

Two backend quirks this module centralizes (discovered on the axon TPU
tunnel, ~70ms round trip):

* ``jax.block_until_ready`` returns immediately with work still queued —
  the only reliable device fence is an actual readback (``device_fence``).
* A ``float()``/``np.asarray()`` per array serializes one full round trip
  each; starting every copy with ``copy_to_host_async`` first overlaps
  them into roughly one round trip total (``overlap_device_get``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

# fault-injection seam (testing/chaos.py hang_at_readback): called at
# the top of device_fence so a chaos test can simulate a device
# readback that never completes — the hang class the watchdog
# (train/watchdog.py) exists to catch.  None in production.
_chaos_readback_hook: Optional[Callable[[], None]] = None


def overlap_device_get(tree: Any) -> Any:
    """Materialize every jax.Array leaf of ``tree`` to numpy with
    overlapped transfers: async-start ALL host copies, then read.
    Non-array leaves pass through unchanged."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    start_host_copy(leaves)
    return jax.tree_util.tree_unflatten(
        treedef,
        [np.asarray(a) if hasattr(a, "dtype") else a for a in leaves])


def start_host_copy(tree: Any) -> Any:
    """Begin the device->host transfer of every array leaf WITHOUT
    waiting (returns ``tree`` unchanged).  Call right after dispatching
    an artifact's compute: the copy then overlaps subsequent device work
    and a later ``np.asarray``/``overlap_device_get`` (e.g. on the async
    artifact writer's thread) mostly finds the bytes already host-side."""
    for a in jax.tree_util.tree_leaves(tree):
        if hasattr(a, "copy_to_host_async"):
            try:
                a.copy_to_host_async()
            except Exception:  # gan4j-lint: disable=swallowed-exception — async copy is an overlap optimization; the eventual synchronous read still works
                pass
    return tree


def device_fence(tree: Any) -> None:
    """Wait for completion of every program producing a leaf of ``tree``
    (plus, by in-order execution, everything dispatched before them):
    overlapped readback of ALL array leaves — block_until_ready is NOT a
    fence on tunneled backends, and reading a single leaf would not fence
    later-dispatched programs producing the other leaves."""
    if _chaos_readback_hook is not None:
        _chaos_readback_hook()
    overlap_device_get([a for a in jax.tree_util.tree_leaves(tree)
                        if hasattr(a, "dtype")])
