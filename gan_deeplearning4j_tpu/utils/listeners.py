"""Training listeners — DL4J's ``TrainingListener`` attachment surface.

DL4J models expose ``setListeners(new ScoreIterationListener(100), ...)``
and call ``iterationDone(model, iteration, epoch)`` after every fit.  The
reference attaches none (SURVEY.md §5: "no DL4J listeners ... attached"),
so this is migration surface, not protocol parity.

DELIBERATE signature difference: the third ``iteration_done`` argument
is the step's SCORE, not DL4J's epoch — the GAN protocol is a single
pass over iterations (epoch would always be 0), and the score is what
every shipped DL4J listener immediately re-reads from the model anyway.
A ported listener that used the epoch argument must be adapted.

TPU-aware contract: ``iteration_done`` receives the SCORE AS A DEVICE
SCALAR.  Converting it (``float(score)``) forces a host readback and
serializes the dispatch pipeline, so the shipped listeners only
materialize the score at their reporting boundary (every
``print_every``/``frequency`` iterations) — attach-and-forget stays
cheap.  Listeners fire on the eager ``ComputationGraph.fit`` path; the
scan-fused multistep trainers report through `utils.metrics` chunk
records instead (one stacked array per dispatch), which is the same
information without a per-step host sync.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple


class TrainingListener:
    """Base: override ``iteration_done``.  ``model`` is the graph that
    just stepped, ``score`` its loss as a device scalar."""

    def iteration_done(self, model, iteration: int, score) -> None:
        raise NotImplementedError


class ScoreIterationListener(TrainingListener):
    """DL4J ScoreIterationListener: log the score every N iterations."""

    def __init__(self, print_every: int = 10,
                 log: Callable[[str], None] = print):
        self.print_every = max(1, print_every)
        self.log = log

    def iteration_done(self, model, iteration: int, score) -> None:
        if iteration % self.print_every == 0:
            self.log(f"Score at iteration {iteration} is {float(score)}")


class PerformanceListener(TrainingListener):
    """DL4J PerformanceListener: iterations/sec (and examples/sec when
    the listener can see a batch size) every N iterations."""

    def __init__(self, frequency: int = 10, batch_size: Optional[int] = None,
                 log: Callable[[str], None] = print):
        self.frequency = max(1, frequency)
        self.batch_size = batch_size
        self.log = log
        self._last: Optional[Tuple[int, float]] = None

    def iteration_done(self, model, iteration: int, score) -> None:
        now = time.perf_counter()
        if self._last is None:
            # baseline at the first OBSERVED step (not iteration 0):
            # attaching to an already-trained graph must not fold the
            # unobserved history into the first window's rate
            self._last = (iteration, now)
            return
        if iteration % self.frequency:
            return
        it0, t0 = self._last
        if iteration == it0:
            return
        dt = max(now - t0, 1e-9)
        rate = (iteration - it0) / dt
        msg = f"iteration {iteration}: {rate:.1f} it/s"
        if self.batch_size:
            msg += f", {rate * self.batch_size:.1f} examples/s"
        self.log(msg)
        self._last = (iteration, now)


class CollectScoresListener(TrainingListener):
    """DL4J CollectScoresIterationListener: record (iteration, score)
    pairs every N iterations (each record is a host readback)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration: int, score) -> None:
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))
