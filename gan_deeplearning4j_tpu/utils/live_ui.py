"""Live training UI — the Spark-web-UI analog (SURVEY.md §5).

The reference gets a free live dashboard from Spark's executor UI
(dl4jGANComputerVision.java:309 ``local[4]`` master); this framework's
structured metrics feed (utils/metrics.py JSONL) is richer but was
post-hoc only (utils/plot_metrics.py).  This module serves it live: a
stdlib ThreadingHTTPServer on a background daemon thread tails the
metrics JSONL and renders an auto-refreshing loss dashboard — zero
dependencies, zero training-thread work (the browser polls; the server
reads the file the trainer was writing anyway).

Use: ``--live-ui PORT`` on any main, or::

    from gan_deeplearning4j_tpu.utils.live_ui import serve_metrics
    stop = serve_metrics("outputs/run/mnist_metrics.jsonl", port=8080)
    ...
    stop()
"""

from __future__ import annotations

import json
import math
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

_PAGE = """<!doctype html>
<html><head><title>gan4j live metrics</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 24px; }
 #meta { color: #555; margin-bottom: 12px; }
 #alarm { display: none; background: #c62828; color: #fff;
          padding: 8px 12px; margin-bottom: 12px; border-radius: 4px; }
 canvas { border: 1px solid #ccc; width: 100%; height: 300px; }
 h3 { margin: 18px 0 4px; }
 .key { display: inline-block; margin-right: 16px; }
 .swatch { display: inline-block; width: 12px; height: 12px;
           margin-right: 4px; vertical-align: middle; }
</style></head>
<body>
<h2>gan4j live metrics</h2>
<div id="alarm"></div>
<div id="meta">waiting for data&hellip;</div>
<h3>losses</h3>
<div id="legend-loss"></div>
<div id="legend-events"></div>
<canvas id="chart-loss" width="1200" height="300"></canvas>
<h3>numerics telemetry (grad/param norms, update ratios — log scale)</h3>
<div id="legend-tel"></div>
<canvas id="chart-tel" width="1200" height="300"></canvas>
<script>
const COLORS = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#8c564b",
                "#e377c2", "#7f7f7f", "#bcbd22", "#17becf", "#ff7f0e"];
async function tick() {
  try {
    const r = await fetch("/data");
    const recs = await r.json();
    let evs = [];
    try { evs = await (await fetch("/events")).json(); } catch (e) {}
    draw(recs, evs);
  } catch (e) { /* server gone: stop quietly */ }
  setTimeout(tick, 2000);
}
function drawMarkers(canvasId, legendId, recs, evs) {
  // run-event markers (checkpoints / preemption / restarts / NaN
  // alarms) from the run's events.jsonl, as dashed vertical lines
  const c = document.getElementById(canvasId);
  const ctx = c.getContext("2d");
  const x0 = recs[0].step, x1 = recs[recs.length - 1].step || 1;
  const px = s => (s - x0) / Math.max(x1 - x0, 1) * (c.width - 40) + 30;
  let legend = "", seen = {};
  ctx.save();
  ctx.setLineDash([4, 4]);
  for (const ev of evs) {
    if (typeof ev.step !== "number") continue;
    ctx.strokeStyle = ev.color || "#999";
    ctx.globalAlpha = 0.6;
    ctx.beginPath();
    const x = px(ev.step);
    ctx.moveTo(x, 10); ctx.lineTo(x, c.height - 20);
    ctx.stroke();
    if (!seen[ev.label]) {
      seen[ev.label] = true;
      legend += `<span class="key"><span class="swatch" style=` +
        `"background:${ev.color || "#999"}"></span>${ev.label}</span>`;
    }
  }
  ctx.restore();
  document.getElementById(legendId).innerHTML = legend;
}
function drawSeries(canvasId, legendId, recs, keys, logScale) {
  const c = document.getElementById(canvasId);
  const ctx = c.getContext("2d");
  ctx.clearRect(0, 0, c.width, c.height);
  if (!keys.length) {  // e.g. a run without --telemetry
    document.getElementById(legendId).innerHTML =
      "<span class=\\"key\\" style=\\"color:#999\\">no such columns in " +
      "this run</span>";
    return;
  }
  const tx = logScale ? (v => v > 0 ? Math.log10(v) : NaN) : (v => v);
  let lo = Infinity, hi = -Infinity;
  for (const r of recs) for (const k of keys) {
    const v = tx(r[k]);
    if (typeof r[k] === "number" && isFinite(v)) {
      lo = Math.min(lo, v); hi = Math.max(hi, v);
    }
  }
  if (!(hi > lo)) { hi = lo + 1; }
  const last = recs[recs.length - 1];
  const x0 = recs[0].step, x1 = last.step || 1;
  const px = s => (s - x0) / Math.max(x1 - x0, 1) * (c.width - 40) + 30;
  const py = v => c.height - 20 -
                  (v - lo) / (hi - lo) * (c.height - 40);
  let legend = "";
  keys.forEach((k, i) => {
    ctx.strokeStyle = COLORS[i % COLORS.length];
    ctx.beginPath();
    let started = false;
    for (const r of recs) {
      const v = tx(r[k]);
      if (typeof r[k] !== "number" || !isFinite(v)) continue;
      const x = px(r.step), y = py(v);
      if (started) ctx.lineTo(x, y); else { ctx.moveTo(x, y); started = true; }
    }
    ctx.stroke();
    legend += `<span class="key"><span class="swatch" style=` +
      `"background:${COLORS[i % COLORS.length]}"></span>${k}</span>`;
  });
  document.getElementById(legendId).innerHTML = legend;
  ctx.fillStyle = "#333";
  const fmt = v => logScale ? "1e" + v.toFixed(1) : v.toFixed(3);
  ctx.fillText(fmt(hi), 2, 14);
  ctx.fillText(fmt(lo), 2, c.height - 8);
}
function draw(recs, evs) {
  if (!recs.length) return;
  const last = recs[recs.length - 1];
  document.getElementById("meta").textContent =
    `step ${last.step}` +
    (last.examples_per_sec ?
      ` — ${Math.round(last.examples_per_sec)} img/s` : "") +
    ` — ${recs.length} records`;
  // NaN panel: the telemetry counter, or a loss the server nulled
  // because it was non-finite, paints the banner with the first bad step
  let bad = null;
  for (const r of recs) {
    if ((typeof r.nonfinite === "number" && r.nonfinite > 0) ||
        ["d_loss", "g_loss", "classifier_loss"].some(
          k => k in r && r[k] === null)) { bad = r; break; }
  }
  const alarm = document.getElementById("alarm");
  if (bad) {
    alarm.style.display = "block";
    alarm.textContent = `NaN/Inf detected — first bad step ${bad.step}` +
      (typeof bad.nonfinite === "number" ?
        ` (${bad.nonfinite} non-finite values)` : "");
  } else { alarm.style.display = "none"; }
  const numKeys = Object.keys(last).filter(
    k => typeof last[k] === "number");
  drawSeries("chart-loss", "legend-loss", recs,
             numKeys.filter(k => k.endsWith("loss")), false);
  if (evs && evs.length) {
    drawMarkers("chart-loss", "legend-events", recs, evs);
  }
  drawSeries("chart-tel", "legend-tel", recs,
             numKeys.filter(k => k.endsWith("_norm") ||
                                 k.endsWith("_ratio")), true);
}
tick();
</script></body></html>
"""

MAX_POINTS = 2000  # downsample long runs so the payload stays small


class _TailCache:
    """Incremental JSONL tail: each poll parses only appended bytes (a
    long run's file would otherwise be re-parsed in full every 2s per
    open tab); a shrunken/replaced file resets the cache."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.partial = ""      # torn tail line carried to the next poll
        self.records: list = []

    def read(self) -> list:
        try:
            size = os.stat(self.path).st_size
        except OSError:
            return []
        if size < self.offset:  # truncated/replaced: start over
            self.offset, self.partial, self.records = 0, "", []
        if size > self.offset:
            with open(self.path) as f:
                f.seek(self.offset)
                chunk = self.partial + f.read()
                self.offset = f.tell()
            lines = chunk.split("\n")
            self.partial = lines.pop()  # "" on a clean newline boundary
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:  # gan4j-lint: disable=swallowed-exception — tailing a live file: a torn/malformed line is expected, not evidence
                    continue
                if "step" not in rec:
                    # step-less run-level records (the goodput summary)
                    # have no x coordinate on a step chart
                    continue
                # a diverged run writes NaN/Infinity, which json.dumps
                # would emit as INVALID JSON and permanently blank the
                # browser's fetch().json() — null them at parse time
                for k, val in rec.items():
                    if isinstance(val, float) and not math.isfinite(val):
                        rec[k] = None
                self.records.append(rec)
            if len(self.records) > 2 * MAX_POINTS:
                # bound the in-process cache too (the trainer hosts this
                # thread): halve by stride, keeping the exact last point
                self.records = (self.records[:-1][::2]
                                + self.records[-1:])
        records = self.records
        if len(records) > MAX_POINTS:
            stride = len(records) // MAX_POINTS + 1
            # keep the exact last point; avoid double-adding it when the
            # stride grid already lands on it
            records = records[:-1][::stride] + records[-1:]
        return records


def serve_metrics(jsonl_path: str, port: int = 8080,
                  host: str = "127.0.0.1") -> Callable[[], None]:
    """Start the dashboard server (daemon thread); returns a stop().

    When an ``events.jsonl`` (telemetry/events.py) sits next to the
    metrics file, ``/events`` serves its step-anchored marker events
    (checkpoints, preemption, restarts, NaN alarms) and the loss chart
    overlays them live."""
    from gan_deeplearning4j_tpu.telemetry.events import (
        EVENTS_NAME,
        marker_records,
    )

    cache = _TailCache(jsonl_path)
    events_cache = _TailCache(os.path.join(
        os.path.dirname(os.path.abspath(jsonl_path)), EVENTS_NAME))
    lock = threading.Lock()

    def marker_events() -> list:
        return marker_records(events_cache.read())

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path == "/data":
                with lock:  # ThreadingHTTPServer: one tail per poll
                    body = json.dumps(cache.read()).encode()
                ctype = "application/json"
            elif self.path == "/events":
                with lock:
                    body = json.dumps(marker_events()).encode()
                ctype = "application/json"
            else:
                body = _PAGE.encode()
                ctype = "text/html; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet: no stderr per request
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever,
                              name="gan4j-live-ui", daemon=True)
    thread.start()

    def stop() -> None:
        server.shutdown()
        server.server_close()

    stop.port = server.server_address[1]  # resolved port (0 = ephemeral)
    return stop


def serve_for_config(config, port: int) -> Callable[[], None]:
    """The mains' shared lifecycle: serve the trainer's metrics JSONL
    (gan_trainer.py's ``{dataset_name}_metrics.jsonl`` path) and announce
    the URL.  Returns stop() for the caller's finally block."""
    stop = serve_metrics(
        os.path.join(config.res_path,
                     f"{config.dataset_name}_metrics.jsonl"), port=port)
    print(f"[live-ui] http://127.0.0.1:{stop.port}/", flush=True)
    return stop
