"""Structured training metrics — the observability layer the reference lacks.

The reference's only signals are slf4j step logs ("Completed Batch {}",
dl4jGANComputerVision.java:477) and the periodic prediction CSVs the
notebook re-reads (SURVEY.md §5 "Metrics / logging").  Here every step can
record structured metrics (D-loss, G-loss, classifier loss, examples/sec —
the BASELINE.json north-star unit) to an in-memory ring + optional JSONL
file, without ever forcing a device sync: losses are stored as jax.Arrays
and only materialized when flushed.
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

# Path-backed loggers register here so ONE atexit hook can flush them:
# the async worker is a daemon thread, and without this the final batch
# of records handed to it could be dropped at interpreter exit.  A
# WeakSet so short-lived loggers (tests) don't accumulate forever.
_OPEN_LOGGERS: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False


def _flush_open_loggers() -> None:
    for logger in list(_OPEN_LOGGERS):
        try:
            logger.close()
        except Exception:  # gan4j-lint: disable=swallowed-exception — interpreter exit: never raise from the atexit hook
            pass


class MetricsLogger:
    """``async_io=True`` (default): flush hands the pending records to a
    background worker for materialization — the float() readback of a
    chunk's loss arrays BLOCKS until that chunk's dispatch has finished
    on device, so a synchronous flush after every chunk would serialize
    dispatch with compute (measured: the r3 "bookkeeping halves e2e"
    gap).  The worker eats the wait; the training thread keeps
    dispatching.  Readers (records/throughput) drain the worker first,
    so observable behavior — file content, record order — is unchanged
    (one FIFO worker)."""

    def __init__(self, path: Optional[str] = None, flush_every: int = 100,
                 ring_size: int = 10000, append: bool = False,
                 async_io: bool = True,
                 on_record: Optional[Callable[[Dict], None]] = None):
        self.path = path
        self.flush_every = flush_every
        self._pending: List[Dict] = []
        # bounded in-memory ring of materialized (host-float) records
        self._records: "deque" = deque(maxlen=ring_size)
        self._t0 = time.perf_counter()
        self._last_step_t = self._t0
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._worker_error: Optional[BaseException] = None
        self._failed: List[List[Dict]] = []
        self._closed = False
        # observer of every MATERIALIZED record, called on the worker
        # thread (async mode) so e.g. the NaN alarm costs the training
        # thread nothing (telemetry/ingraph.py NanAlarm.observe)
        self._on_record = on_record
        if async_io:
            self._q = queue.Queue()
            self._worker = threading.Thread(target=self._drain,
                                            name="gan4j-metrics-writer",
                                            daemon=True)
            self._worker.start()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            if not append:
                # truncate: one file per run (``append=True`` = a resumed
                # run continuing its own history)
                open(path, "w").close()
            global _ATEXIT_REGISTERED
            _OPEN_LOGGERS.add(self)
            if not _ATEXIT_REGISTERED:
                atexit.register(_flush_open_loggers)
                _ATEXIT_REGISTERED = True

    def _drain(self) -> None:
        q = self._q  # local ref: close() nulls the attribute while the
        while True:  # worker may still be draining the sentinel
            batch = q.get()
            if batch is None:  # close() sentinel
                q.task_done()
                return
            try:
                self._materialize(batch)
            except BaseException as e:
                # keep the FIRST error (raised at the next sync point) and
                # the un-materialized batch (recoverable via records()
                # retry once the fault — e.g. a full disk — clears); later
                # batches still attempt materialization
                if self._worker_error is None:
                    self._worker_error = e
                self._failed.append(batch)
            finally:
                q.task_done()

    def log_step(self, step: int, examples: int = 0, **metrics) -> None:
        """Record one step.  ``metrics`` values may be jax.Arrays — they are
        kept lazy until flush so logging never blocks the device."""
        now = time.perf_counter()
        rec = {
            "step": step,
            "wall_s": now - self._t0,
            "step_s": now - self._last_step_t,
        }
        if examples:
            rec["examples_per_sec"] = examples / max(rec["step_s"], 1e-9)
        rec.update(metrics)
        self._last_step_t = now
        self._pending.append(rec)
        # Flush on cadence even without a file: materializing releases the
        # pending records' live device buffers into the bounded ring.
        if len(self._pending) >= self.flush_every:
            self.flush()

    def log_chunk(self, start_step: int, n: int, examples: int,
                  metrics: Dict) -> None:
        """Record ``n`` consecutive steps from one multi-step dispatch.

        ``metrics`` values are length-``n`` jax.Arrays (one stacked array
        per metric for the WHOLE chunk).  Per-step ``log_step`` would cost
        3 sliced-scalar device dispatches per step plus 3 scalar readbacks
        per step at flush — host-side work that scales with steps and, on
        a tunneled PJRT link, dominates the run no matter how many steps
        one XLA dispatch advances.  A chunk record keeps ONE device array
        per metric; flush reads each back in one transfer and expands to
        per-step records (wall time attributed uniformly across the
        chunk's steps)."""
        now = time.perf_counter()
        self._pending.append({
            "_chunk": (start_step, n, examples,
                       self._last_step_t, now),
            **metrics,
        })
        self._last_step_t = now
        if sum(r["_chunk"][1] if "_chunk" in r else 1
               for r in self._pending) >= self.flush_every:
            self.flush()

    def _expand(self, rec: Dict) -> List[Dict]:
        """Materialized pending record -> per-step host records."""
        if "_chunk" not in rec:
            return [{k: (float(v) if hasattr(v, "dtype") else v)
                     for k, v in rec.items()}]
        start_step, n, examples, t0, t1 = rec["_chunk"]
        step_s = (t1 - t0) / n
        out = []
        for k in range(n):
            r = {"step": start_step + k,
                 "wall_s": (t0 - self._t0) + (k + 1) * step_s,
                 "step_s": step_s}
            if examples:
                r["examples_per_sec"] = examples / max(step_s, 1e-9)
            for key, v in rec.items():
                if key != "_chunk":
                    r[key] = float(v[k]) if hasattr(v, "dtype") else v
            out.append(r)
        return out

    def _materialize(self, pending: List[Dict]) -> None:
        # Overlapped readback: a naive float() per value is a full device
        # round trip each — on a tunneled PJRT link that is ~70ms * 3
        # losses * flush_every per flush, which would dominate a real run.
        from gan_deeplearning4j_tpu.utils.device import overlap_device_get

        pending = overlap_device_get(pending)
        materialized = []
        for rec in pending:
            materialized.extend(self._expand(rec))
        if self.path:
            with open(self.path, "a") as f:
                for rec in materialized:
                    f.write(json.dumps(rec) + "\n")
        self._records.extend(materialized)
        if self._on_record is not None:
            for rec in materialized:
                self._on_record(rec)

    def flush(self, wait: Optional[bool] = None) -> None:
        """Hand pending records off for materialization.  ``wait`` forces
        the synchronous semantics (drain the worker before returning);
        readers and end-of-run code use it, the hot loop does not."""
        if self._pending:
            batch, self._pending = self._pending, []
            if self._q is not None:
                self._q.put(batch)
            else:
                self._materialize(batch)
        if self._q is not None and wait:
            self._q.join()
        if self._failed and self._worker_error is None:
            # fault cleared: retry the preserved batches in order
            retry, self._failed = self._failed, []
            for batch in retry:
                self._materialize(batch)
        if self._worker_error is not None:
            e, self._worker_error = self._worker_error, None
            raise e

    def log_record(self, rec: Dict) -> None:
        """Append one raw, step-less record — run-level summaries like
        the goodput breakdown or the run-manifest pointer.  Values may be
        jax.Arrays (kept lazy until flush, like log_step's)."""
        self._pending.append(dict(rec))

    def close(self) -> None:
        """Flush every pending record and join the async worker.  The
        logger stays usable afterwards (flush falls back to synchronous
        materialization); idempotent, and registered with atexit for
        path-backed loggers so a daemon-thread worker can never drop the
        final batch at interpreter exit."""
        if self._closed:
            return
        try:
            self.flush(wait=True)
        finally:
            self._closed = True
            q, self._q = self._q, None
            if q is not None:
                q.put(None)  # sentinel: worker exits after draining
                self._worker.join(timeout=10.0)
            _OPEN_LOGGERS.discard(self)

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            # error path: still stop the worker, but don't let a flush
            # error (e.g. the readback of a poisoned loss) mask ``exc``
            try:
                self.close()
            except Exception:  # gan4j-lint: disable=swallowed-exception — a flush error (e.g. readback of a poisoned loss) must not mask exc
                pass

    def records(self) -> List[Dict]:
        self.flush(wait=True)
        return list(self._records)

    def throughput(self, last_n: int = 100) -> float:
        """Steady-state examples/sec over the last n recorded steps: the
        median, so the first step's XLA compile (orders of magnitude slower
        than a steady step) cannot drag the estimate down."""
        import statistics

        self.flush(wait=True)
        recs = list(self._records)[-last_n:]
        vals = [r["examples_per_sec"] for r in recs if "examples_per_sec" in r]
        return statistics.median(vals) if vals else 0.0
