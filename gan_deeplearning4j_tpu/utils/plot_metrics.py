"""Loss-curve rendering from metrics JSONL — the training-UI the stack implies.

The DL4J stack ships a training UI and the reference leans on the Spark UI
(SURVEY.md §5 metrics/observability row); this framework's structured
per-step JSONL (utils/metrics.py) is the data feed, and this module is the
viewer: one PNG of the loss curves per run, plus a CLI.

Run: ``python -m gan_deeplearning4j_tpu.utils.plot_metrics
outputs/computer_vision/mnist_metrics.jsonl``
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Sequence

# fixed categorical assignment (colorblind-validated order; identity never
# depends on position in the file)
_SERIES_COLORS = {
    "d_loss": "#2a78d6",
    "g_loss": "#eb6834",
    "classifier_loss": "#1baf7a",
}
_FALLBACK_COLORS = ["#eda100", "#e87ba4", "#008300", "#4a3aa7", "#e34948"]


def read_metrics(path: str) -> List[Dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def load_event_markers(metrics_jsonl: str) -> List[Dict]:
    """Step-anchored run events for overlay: when an ``events.jsonl``
    (telemetry/events.py) sits next to the metrics file, return its
    marker-vocabulary events (checkpoint saves, emergency saves,
    preemption, recovery restarts, NaN alarms) as
    ``[{"step", "name", "label", "color"}]``; [] when absent/empty."""
    from gan_deeplearning4j_tpu.telemetry.events import (
        EVENTS_NAME,
        marker_records,
        read_events,
    )

    path = os.path.join(os.path.dirname(os.path.abspath(metrics_jsonl)),
                        EVENTS_NAME)
    if not os.path.exists(path):
        return []
    return marker_records(read_events(path))


def _overlay_markers(axes, markers) -> None:
    """Vertical marker lines on every axis, one legend entry per marker
    KIND (a 100-checkpoint run must not produce 100 legend rows)."""
    seen = set()
    for m in markers:
        for i, ax in enumerate(axes):
            ax.axvline(m["step"], color=m["color"], alpha=0.55,
                       linewidth=1.0, linestyle="--",
                       label=(m["label"]
                              if i == 0 and m["label"] not in seen
                              else None))
        seen.add(m["label"])


def plot_losses(metrics_jsonl: str, out_png: Optional[str] = None,
                keys: Optional[Sequence[str]] = None,
                smooth: int = 1) -> str:
    """Render the loss curves of one run to ``out_png`` (default: next to
    the JSONL).  ``keys``: which scalar series to draw (default: every
    ``*_loss`` key present); ``smooth``: centered moving-average window in
    steps (1 = raw)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    # step records only: the feed also carries step-less run-level
    # records (the goodput breakdown, utils/metrics.py log_record)
    records = [r for r in read_metrics(metrics_jsonl) if "step" in r]
    if not records:
        raise ValueError(f"no step records in {metrics_jsonl}")
    if keys is None:
        keys = [k for k in records[0] if k.endswith("_loss")]
    steps = np.array([r["step"] for r in records])

    import itertools

    fig, ax = plt.subplots(figsize=(8, 4.5), dpi=120)
    fallback = itertools.cycle(_FALLBACK_COLORS)
    for key in keys:
        vals = np.array([r.get(key, np.nan) for r in records], dtype=float)
        w = max(1, min(smooth, len(vals)))
        if w > 1:
            # normalized windowed mean: edges average over the window
            # actually present instead of drooping toward zero padding
            kernel = np.ones(w)
            vals = (np.convolve(vals, kernel, mode="same")
                    / np.convolve(np.ones_like(vals), kernel, mode="same"))
        color = _SERIES_COLORS.get(key) or next(fallback)
        ax.plot(steps, vals, color=color, linewidth=1.6, label=key)
    # run-event markers (checkpoints, preemption, restarts, NaN alarms)
    # from the sibling events.jsonl, when one exists
    markers = load_event_markers(metrics_jsonl)
    _overlay_markers([ax], markers)
    ax.set_xlabel("step")
    ax.set_ylabel("loss")
    ax.set_title(os.path.basename(metrics_jsonl))
    # recessive grid, no top/right spines; legend identifies the series
    ax.grid(True, color="#dddddd", linewidth=0.6, alpha=0.6)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    if len(keys) > 1 or markers:
        ax.legend(frameon=False)
    fig.tight_layout()
    out_png = out_png or (os.path.splitext(metrics_jsonl)[0] + "_losses.png")
    fig.savefig(out_png)
    plt.close(fig)
    return out_png


def plot_telemetry(metrics_jsonl: str, out_png: Optional[str] = None,
                   smooth: int = 1) -> str:
    """Render the in-graph numerics telemetry of one run (grad/param
    norms on a log axis, update ratios below, NaN steps rubricated) to
    ``out_png`` (default: ``*_telemetry.png`` next to the JSONL).  The
    post-hoc view of the columns ``--telemetry`` adds to the metrics
    feed (telemetry/ingraph.py)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    records = [r for r in read_metrics(metrics_jsonl) if "step" in r]
    norm_keys = sorted({k for r in records for k in r
                        if k.endswith("_norm")})
    ratio_keys = sorted({k for r in records for k in r
                         if k.endswith("_ratio")})
    if not records or not (norm_keys or ratio_keys):
        raise ValueError(
            f"no telemetry columns in {metrics_jsonl} — was the run "
            "trained with --telemetry?")
    steps = np.array([r["step"] for r in records])

    import itertools

    fig, (ax_n, ax_r) = plt.subplots(
        2, 1, figsize=(8, 7), dpi=120, sharex=True)
    fallback = itertools.cycle(_FALLBACK_COLORS + list(
        _SERIES_COLORS.values()))

    def series(ax, keys, log):
        for key in keys:
            vals = np.array([r.get(key, np.nan) for r in records],
                            dtype=float)
            w = max(1, min(smooth, len(vals)))
            if w > 1:
                kernel = np.ones(w)
                vals = (np.convolve(vals, kernel, mode="same")
                        / np.convolve(np.ones_like(vals), kernel,
                                      mode="same"))
            ax.plot(steps, vals, color=next(fallback), linewidth=1.4,
                    label=key)
        if log:
            ax.set_yscale("log")
        ax.grid(True, color="#dddddd", linewidth=0.6, alpha=0.6)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
        ax.legend(frameon=False, fontsize=8)

    series(ax_n, norm_keys, log=True)
    ax_n.set_ylabel("global L2 norm")
    series(ax_r, ratio_keys, log=True)
    ax_r.set_ylabel("update ratio")
    ax_r.set_xlabel("step")
    # run-event markers on both panels (the checkpoint/restart/alarm
    # timeline a norms post-mortem wants to correlate against)
    markers = load_event_markers(metrics_jsonl)
    _overlay_markers([ax_n, ax_r], markers)
    if markers:
        ax_n.legend(frameon=False, fontsize=8)  # include marker labels
    # rubricate steps whose NaN/Inf counter fired (or whose norms went
    # non-finite) — the first-bad-step marker a post-mortem reads first
    bad = [r["step"] for r in records
           if r.get("nonfinite") or any(
               r.get(k) is not None and not np.isfinite(r.get(k, 0.0))
               for k in norm_keys if isinstance(r.get(k), float))]
    for ax in (ax_n, ax_r):
        for s in bad[:50]:  # cap: a fully-diverged run marks every step
            ax.axvline(s, color="#e34948", alpha=0.35, linewidth=0.8)
    ax_n.set_title(os.path.basename(metrics_jsonl)
                   + (f" — first NaN at step {bad[0]}" if bad else ""))
    fig.tight_layout()
    out_png = out_png or (
        os.path.splitext(metrics_jsonl)[0] + "_telemetry.png")
    fig.savefig(out_png)
    plt.close(fig)
    return out_png


def main(argv=None) -> str:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("metrics_jsonl")
    p.add_argument("--out", default=None, help="output PNG path")
    p.add_argument("--keys", nargs="*", default=None,
                   help="series to draw (default: every *_loss)")
    p.add_argument("--smooth", type=int, default=1,
                   help="moving-average window in steps")
    p.add_argument("--telemetry", action="store_true",
                   help="render the numerics-telemetry panel (grad/param "
                        "norms, update ratios, NaN markers) instead of "
                        "the loss curves")
    args = p.parse_args(argv)
    if args.telemetry:
        out = plot_telemetry(args.metrics_jsonl, args.out, args.smooth)
    else:
        out = plot_losses(args.metrics_jsonl, args.out, args.keys,
                          args.smooth)
    print(out)
    return out


if __name__ == "__main__":
    main()
