"""Bounded device-link probe, shared by every driver-facing entry.

The PJRT link to the chip is a shared tunnel that can wedge outright
(``jax.devices()`` then blocks indefinitely), so a process that must not
hang probes from a FRESH child interpreter under a timeout: the child
wedges and is killed, never the caller.  Used by the repo-root ``bench.py``
shim and ``benchmarks/acceptance.py`` — one implementation, no drift.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

# One small dispatch + readback; prints a single JSON line with the chosen
# platform and the measured round trip.  Honors an explicit JAX_PLATFORMS
# via the shared entry-point helper (the sitecustomize clobber makes the
# raw env var a no-op — runtime/backend.py NOTE).
_PROBE_CODE = """
import json, time
import numpy as np, jax, jax.numpy as jnp
from gan_deeplearning4j_tpu.runtime.backend import apply_env_platform
apply_env_platform()
f = jax.jit(lambda a: a @ a)
x = jnp.ones((64, 64)); np.asarray(f(x))
t0 = time.perf_counter()
for _ in range(5): np.asarray(f(x))
print(json.dumps({"platform": jax.default_backend(),
                  "rt_ms": (time.perf_counter() - t0) * 200}))
"""


def probe_with_retry(timeout_s: float, cwd: str | None = None,
                     attempts: int = 3, backoff_s: float = 45.0,
                     log=None):
    """``probe_device`` with bounded retry/backoff (a wedged tunnel often
    recovers within minutes).  Returns (platform, rt_ms) or raises
    RuntimeError carrying every attempt's reason — the ONE retry loop
    shared by every driver-facing entry."""
    reasons = []
    for attempt in range(1, attempts + 1):
        try:
            platform, rt_ms = probe_device(timeout_s, cwd=cwd)
            if log:
                log(f"probe ok (attempt {attempt}): platform={platform} "
                    f"round-trip {rt_ms:.1f}ms")
            return platform, rt_ms
        except RuntimeError as e:
            reasons.append(f"attempt {attempt}: {e}")
            if log:
                log(reasons[-1])
            if attempt < attempts:
                if log:
                    log(f"backing off {backoff_s:.0f}s before re-probe")
                time.sleep(backoff_s)
    raise RuntimeError("; ".join(reasons))


def probe_device(timeout_s: float, cwd: str | None = None):
    """(platform, round_trip_ms) via a bounded subprocess, or raise
    RuntimeError with a one-line reason.  ``cwd`` must make the package
    importable in the child (the repo root, or anywhere once installed)."""
    try:
        out = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                             cwd=cwd, capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        raise RuntimeError(
            f"device link unresponsive (>{timeout_s:.0f}s for a 64x64 "
            "dispatch+readback)") from None
    if out.returncode != 0:
        tail = " | ".join(out.stderr.strip().splitlines()[-2:])
        raise RuntimeError(f"device probe failed: {tail[-400:]}")
    try:
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        return rec["platform"], float(rec["rt_ms"])
    except (ValueError, KeyError, IndexError):
        raise RuntimeError(
            f"unparseable probe output: {out.stdout[-200:]!r}") from None
