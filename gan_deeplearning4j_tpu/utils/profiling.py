"""Tracing/profiling — the SURVEY.md §5 tracing row.

The reference's only profiling hooks are ``CudaEnvironment...setVerbose(true)``
(dl4jGANComputerVision.java:104) and the Spark UI that comes with the
SparkContext (:309).  The TPU-native equivalent is a first-class
jax.profiler integration: wrap any region in ``maybe_trace(dir)`` and a
TensorBoard-loadable trace (XLA op timeline, HBM usage, host/device
overlap) lands in ``dir``.  Every main and the benchmark expose it as a
``--profile DIR`` flag.

``summarize_trace(dir)`` extracts the top time sinks from the captured
``.trace.json.gz`` so a run can report where its step time goes without
leaving the terminal.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
from collections import defaultdict
from typing import List, Optional, Tuple


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]):
    """jax.profiler.trace(trace_dir) when a directory is given; no-op
    (zero overhead) otherwise — so the flag can always be plumbed."""
    if not trace_dir:
        yield
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield


def _trace_events(trace_dir: str) -> List[dict]:
    """Load all chrome-trace events jax.profiler wrote under trace_dir."""
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    events: List[dict] = []
    for path in sorted(glob.glob(pattern, recursive=True)):
        with gzip.open(path, "rt") as f:
            events.extend(json.load(f).get("traceEvents", []))
    return events


def summarize_trace(trace_dir: str, top: int = 10,
                    device_only: bool = True) -> List[Tuple[str, float]]:
    """Top-``top`` (event name, total milliseconds) sinks in a captured
    trace.  ``device_only`` keeps accelerator lanes when any exist (drops
    host python); a pure-host trace falls back to all lanes."""
    events = _trace_events(trace_dir)
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev.get("pid")] = ev.get("args", {}).get("name", "")

    def is_device(lane: str) -> bool:
        return any(k in lane.lower() for k in ("tpu", "/device", "gpu"))

    have_device = any(is_device(n) for n in pid_names.values())
    totals: "defaultdict[str, float]" = defaultdict(float)
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        lane = pid_names.get(ev.get("pid"), "")
        if device_only and have_device and not is_device(lane):
            continue
        totals[ev["name"]] += ev["dur"] / 1000.0  # us -> ms
    return sorted(totals.items(), key=lambda kv: -kv[1])[:top]
