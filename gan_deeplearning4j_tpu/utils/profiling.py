"""Tracing/profiling — the SURVEY.md §5 tracing row.

The reference's only profiling hooks are ``CudaEnvironment...setVerbose(true)``
(dl4jGANComputerVision.java:104) and the Spark UI that comes with the
SparkContext (:309).  The TPU-native equivalent is a first-class
jax.profiler integration: wrap any region in ``maybe_trace(dir)`` and a
TensorBoard-loadable trace (XLA op timeline, HBM usage, host/device
overlap) lands in ``dir``.  Every main and the benchmark expose it as a
``--profile DIR`` flag.

``summarize_trace(dir)`` extracts the top time sinks from the captured
``.trace.json.gz`` so a run can report where its step time goes without
leaving the terminal.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
from collections import defaultdict
from typing import List, Optional, Tuple


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]):
    """jax.profiler.trace(trace_dir) when a directory is given; no-op
    (zero overhead) otherwise — so the flag can always be plumbed.

    The traced region is also recorded as a ``profiler.trace`` event
    span (telemetry/events.py), and a ``host_anchor.json`` sidecar
    (wall-clock start of the capture) is dropped INTO ``trace_dir`` —
    the alignment anchor ``events.export_chrome_trace(...,
    jax_trace_dir=...)`` reads first.  The sidecar is authoritative
    because the run's file-backed event recorder is installed inside
    train(), i.e. after this wrapper opened; the span alone would land
    on whatever recorder was current here."""
    if not trace_dir:
        yield
        return
    import json
    import time

    import jax

    from gan_deeplearning4j_tpu.telemetry import events

    os.makedirs(trace_dir, exist_ok=True)
    try:
        with open(os.path.join(trace_dir, "host_anchor.json"), "w") as f:
            json.dump({"wall_start": time.time()}, f)
    except OSError:  # gan4j-lint: disable=swallowed-exception — alignment degrades to best-effort; the capture still runs
        pass
    with events.span("profiler.trace", trace_dir=trace_dir):
        with jax.profiler.trace(trace_dir):
            yield


def _trace_events(trace_dir: str) -> List[dict]:
    """Load all chrome-trace events jax.profiler wrote under trace_dir."""
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    events: List[dict] = []
    for path in sorted(glob.glob(pattern, recursive=True)):
        with gzip.open(path, "rt") as f:
            events.extend(json.load(f).get("traceEvents", []))
    return events


def summarize_trace(trace_dir: str, top: int = 10,
                    device_only: bool = True) -> List[Tuple[str, float]]:
    """Top-``top`` (event name, total milliseconds) sinks in a captured
    trace.  ``device_only`` keeps accelerator lanes when any exist (drops
    host python); a pure-host trace falls back to all lanes."""
    events = _trace_events(trace_dir)
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev.get("pid")] = ev.get("args", {}).get("name", "")

    def is_device(lane: str) -> bool:
        return any(k in lane.lower() for k in ("tpu", "/device", "gpu"))

    have_device = any(is_device(n) for n in pid_names.values())
    totals: "defaultdict[str, float]" = defaultdict(float)
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        lane = pid_names.get(ev.get("pid"), "")
        if device_only and have_device and not is_device(lane):
            continue
        totals[ev["name"]] += ev["dur"] / 1000.0  # us -> ms
    return sorted(totals.items(), key=lambda kv: -kv[1])[:top]


def print_trace_summary(trace_dir: str, top: int = 10,
                        log=print) -> List[Tuple[str, float]]:
    """The mains' shared ``--profile`` exit report: summarize the
    captured trace's top time sinks to ``log`` so a profiled run says
    where its step time went without leaving the terminal.  Returns the
    rows; never raises (a missing/empty capture must not fail the run
    that produced the real results)."""
    try:
        rows = summarize_trace(trace_dir, top=top)
    except Exception as e:
        log(f"[profile] could not summarize {trace_dir}: {e!r}")
        return []
    if not rows:
        log(f"[profile] no trace events captured under {trace_dir}")
        return rows
    log(f"[profile] top {len(rows)} time sinks ({trace_dir}):")
    for name, ms in rows:
        log(f"[profile]  {ms:12.3f} ms  {name}")
    return rows
