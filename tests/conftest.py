"""Test harness configuration.

The reference exercises its full distributed path on one machine via Spark
``local[4]`` (SURVEY.md §4.4).  The TPU-native equivalent: force the JAX host
platform with 8 virtual CPU devices so every pjit/shard_map collective path
runs clusterless.

Note: this environment's TPU plugin (axon) force-sets
``jax_platforms="axon,cpu"`` via ``jax.config.update`` at interpreter startup
(sitecustomize), which overrides the JAX_PLATFORMS env var — so we must
override it back through jax.config, after importing jax but before any
backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture
def recompile_sentinel():
    """Runtime recompile sanitizer (analysis/sanitizers.py) for perf-
    sensitive tests: warm your jitted function up, call
    ``sentinel.arm()``, run the steady phase — the fixture FAILS the
    test at teardown if any compile landed after arming.  (Tests that
    expect a recompile should assert on ``sentinel.recompiles``
    themselves and ``sentinel.recompiles.clear()`` before teardown.)"""
    from gan_deeplearning4j_tpu.analysis.sanitizers import RecompileSentinel

    with RecompileSentinel() as sentinel:
        yield sentinel
        sentinel.check()  # raises RecompileError -> the test fails


@pytest.fixture
def transfer_guard():
    """Transfer sanitizer: the whole test body runs under
    ``jax.transfer_guard("disallow")`` — any implicit host<->device
    transfer raises TransferGuardError at the offending op.  Stage
    inputs with an explicit ``jax.device_put`` (allowed) and keep
    readbacks out of the guarded assertions."""
    from gan_deeplearning4j_tpu.analysis.sanitizers import (
        no_implicit_transfers,
    )

    with no_implicit_transfers():
        yield


@pytest.fixture
def lockdep():
    """Runtime lock-order sanitizer (analysis/sanitizers.py): lock
    allocations inside the test become order-tracking proxies; the
    fixture FAILS the test at teardown on any observed lock-order
    inversion (both stacks in the error) or leaked non-daemon thread.
    Tests asserting ON an inversion should read ``dep.inversions`` and
    clear it before teardown."""
    from gan_deeplearning4j_tpu.analysis import sanitizers

    with sanitizers.lockdep(strict=False) as dep:
        yield dep
    dep.check()  # raises LockOrderError/ThreadLeakError -> test fails


@pytest.fixture(autouse=True)
def _lockdep_everywhere(request):
    """CI race lane (tier1.yml): with ``GAN4J_LOCKDEP=1`` every test in
    the selected suites runs under the lockdep sanitizer — the chaos
    and supervision e2e suites double as lock-order torture tests.
    Without the env var this fixture is a no-op, and a test that
    already requested the explicit ``lockdep`` fixture is left alone
    (no nested patching)."""
    if (os.environ.get("GAN4J_LOCKDEP") != "1"
            or "lockdep" in request.fixturenames):
        yield
        return
    from gan_deeplearning4j_tpu.analysis import sanitizers

    with sanitizers.lockdep(strict=False) as dep:
        yield
    dep.check()
