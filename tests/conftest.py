"""Test harness configuration.

The reference exercises its full distributed path on one machine via Spark
``local[4]`` (SURVEY.md §4.4).  The TPU-native equivalent: force the JAX host
platform with 8 virtual CPU devices so every pjit/shard_map collective path
runs clusterless.

Note: this environment's TPU plugin (axon) force-sets
``jax_platforms="axon,cpu"`` via ``jax.config.update`` at interpreter startup
(sitecustomize), which overrides the JAX_PLATFORMS env var — so we must
override it back through jax.config, after importing jax but before any
backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
