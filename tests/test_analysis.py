"""gan4j-lint: static rules, suppressions, baseline, CLI, and the
runtime trace sanitizers (analysis/ — PR 6).

Layout mirrors the contract in docs/STATIC_ANALYSIS.md:

* every rule has a firing fixture, a suppressed variant that does NOT
  fire, and a clean variant (the false-positive guard);
* the baseline round-trips (write -> reload -> all baselined) and is
  content-addressed (line shifts keep it, fixing the line drops it);
* the CLI honors the exit-code contract the CI lane keys on;
* the sanitizers catch an INJECTED recompile / implicit transfer and
  stay silent on a cached, device-resident loop;
* the repo itself lints clean with an empty baseline — the
  zero-findings gate, asserted here AND in bench --dryrun.
"""

import json
import textwrap

import numpy as np
import pytest

from gan_deeplearning4j_tpu.analysis import (
    RecompileError,
    RecompileSentinel,
    TransferGuardError,
    all_rules,
    lint_package,
    lint_paths,
    no_implicit_transfers,
)
from gan_deeplearning4j_tpu.analysis import baseline as baseline_mod
from gan_deeplearning4j_tpu.analysis import cli


def lint_src(tmp_path, src, rules=None, name="snippet.py", **kw):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return lint_paths([str(p)], rules=rules, **kw)


def rule_names(result):
    return [f.rule for f in result.findings]


# -- prng-key-reuse -----------------------------------------------------------


def test_key_reuse_fires(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def f(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))
            return a + b
    """, rules=["prng-key-reuse"])
    assert rule_names(res) == ["prng-key-reuse"]
    assert res.findings[0].line == 6
    assert "already consumed" in res.findings[0].message


def test_key_reuse_in_loop_fires(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def f(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.uniform(key, (3,)))
            return out
    """, rules=["prng-key-reuse"])
    assert rule_names(res) == ["prng-key-reuse"]
    assert "loop" in res.findings[0].message


def test_key_reuse_match_cases_not_sequential(tmp_path):
    # match/case arms are mutually exclusive — one consumption per
    # case is NOT a reuse (same merge discipline as if/else)
    res = lint_src(tmp_path, """
        import jax

        def f(key, v):
            match v:
                case 1:
                    return jax.random.uniform(key, (2,))
                case 2:
                    return jax.random.normal(key, (2,))
                case _:
                    return None
    """, rules=["prng-key-reuse"])
    assert res.findings == []
    # ...but a consumption AFTER a match that consumed in every case
    # is a reuse (the key is spent whichever arm ran)
    res = lint_src(tmp_path, """
        import jax

        def g(key, v):
            match v:
                case 1:
                    a = jax.random.uniform(key, (2,))
                case _:
                    a = jax.random.normal(key, (2,))
            return a + jax.random.uniform(key, (2,))
    """, rules=["prng-key-reuse"])
    assert rule_names(res) == ["prng-key-reuse"]


def test_key_reuse_clean_variants(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def split_fix(key):
            k1, k2 = jax.random.split(key)
            return jax.random.uniform(k1, (3,)) + jax.random.normal(k2, (3,))

        def loop_fix(key, n):
            out = []
            for i in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.uniform(sub, (3,)))
            return out

        def fold_fix(key, n):
            return [jax.random.uniform(jax.random.fold_in(key, i), (3,))
                    for i in range(n)]

        def presplit_loop(key, n):
            out = []
            for k in jax.random.split(key, n):
                out.append(jax.random.uniform(k, (3,)))
            return out

        def branches(key, flag):
            # runtime takes ONE branch: not a reuse
            if flag:
                return jax.random.uniform(key, (3,))
            else:
                return jax.random.normal(key, (3,))

        def not_random(s):
            return s.split(",") + s.split(";")  # str.split is not a key op
    """, rules=["prng-key-reuse"])
    assert res.findings == []


def test_key_reuse_suppressed(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def f(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))  # gan4j-lint: disable=prng-key-reuse — deliberate correlated draw
            return a + b
    """, rules=["prng-key-reuse"])
    assert res.findings == [] and len(res.suppressed) == 1


# -- tracer-side-effect -------------------------------------------------------


def test_tracer_side_effect_fires(tmp_path):
    res = lint_src(tmp_path, """
        import jax
        from functools import partial

        acc = []

        @jax.jit
        def decorated(x):
            acc.append(x)
            return x * 2

        @partial(jax.jit, donate_argnums=0)
        def via_partial(x):
            global hits
            hits = 1
            return x

        def by_name(x, table):
            def body(c, x):
                table[0] = c
                return c + x, c
            return jax.lax.scan(body, x, None, length=3)
    """, rules=["tracer-side-effect"])
    assert rule_names(res) == ["tracer-side-effect"] * 3


def test_tracer_side_effect_clean(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        @jax.jit
        def local_list_ok(x):
            parts = []
            parts.append(x)       # local: trace-time is the only time
            return sum(parts)

        def untraced(x):
            acc.append(x)         # not traced: plain Python, fine here
            return x

        def tree_map_ok(tree):
            # jax.tree.map is NOT a tracing entry point
            return jax.tree.map(lambda a: a * 2, tree)
    """, rules=["tracer-side-effect"])
    assert res.findings == []


# -- host-sync-in-hot-path ----------------------------------------------------


def test_host_sync_fires(tmp_path):
    res = lint_src(tmp_path, """
        import jax
        import numpy as np

        def block(x):
            return jax.block_until_ready(x)

        def hot(step, xs):
            tot = 0.0
            for x in xs:
                y = step(x)
                tot += float(y)
            return tot

        def jit_bound(f, xs):
            g = jax.jit(f)
            out = []
            for x in xs:
                out.append(np.asarray(g(x)))
            return out

        def marked(fn, xs):  # gan4j-lint: hot-path
            vals = []
            for x in xs:
                vals.append(x.item())
            return vals
    """, rules=["host-sync-in-hot-path"])
    kinds = sorted(f.message.split()[0] for f in res.findings)
    assert len(res.findings) == 4
    assert any("block_until_ready" in f.message for f in res.findings)
    assert any("float()" in f.message for f in res.findings)
    assert any("np.asarray" in f.message for f in res.findings)
    assert any(".item()" in f.message for f in res.findings), kinds


def test_host_sync_clean(tmp_path):
    res = lint_src(tmp_path, """
        import numpy as np

        def cold_loop(xs):
            # no step dispatch in the loop: materialization is fine
            return [float(x) for x in xs] + [np.asarray(xs)]

        def hot_but_clean(step, xs, fence):
            losses = None
            for x in xs:
                losses = step(x)
            fence(losses)             # fence AFTER the loop
            return float(losses[0])   # readback after the loop
    """, rules=["host-sync-in-hot-path"])
    assert res.findings == []


def test_host_sync_suppressed(tmp_path):
    res = lint_src(tmp_path, """
        def hot(step, xs):
            tot = 0.0
            for x in xs:
                y = step(x)
                # gan4j-lint: disable=host-sync-in-hot-path — convergence gate needs the scalar
                tot += float(y)
            return tot
    """, rules=["host-sync-in-hot-path"])
    assert res.findings == [] and len(res.suppressed) == 1


# -- recompile-hazard ---------------------------------------------------------


def test_recompile_hazard_fires(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def wrap_in_loop(fs, x):
            for f in fs:
                g = jax.jit(f)        # fresh callable per iteration
                x = g(x)
            return x

        def lambda_per_call(xs):
            f = jax.jit(lambda a, h: h(a))
            out = []
            for x in xs:
                out.append(f(x, lambda a: a * 2))
            return out

        def bad_static():
            f = jax.jit(lambda a, b: a, static_argnums=1)
            return f(1.0, [1, 2])

        def bad_static_name():
            f = jax.jit(lambda a, cfg=None: a, static_argnames="cfg")
            return f(1.0, cfg={"k": 1})
    """, rules=["recompile-hazard"])
    assert rule_names(res) == ["recompile-hazard"] * 4


def test_recompile_hazard_clean(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def hoisted(f, xs):
            g = jax.jit(f)            # wrapped ONCE
            return [g(x) for x in xs]

        def hashable_static():
            f = jax.jit(lambda a, b: a, static_argnums=1)
            return f(1.0, (1, 2))     # tuple: hashable

        def tree_map_in_loop(trees):
            # jax.tree.map is not a trace entry — a lambda here is fine
            return [jax.tree.map(lambda a: a * 2, t) for t in trees]
    """, rules=["recompile-hazard"])
    assert res.findings == []


# -- unlocked-shared-write ----------------------------------------------------


def test_unlocked_write_fires(tmp_path):
    res = lint_src(tmp_path, """
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self.table = {}

            def bump(self):
                self.count += 1

            def put(self, k, v):
                self.table[k] = v
    """, rules=["unlocked-shared-write"])
    assert rule_names(res) == ["unlocked-shared-write"] * 2


def test_unlocked_write_clean(tmp_path):
    res = lint_src(tmp_path, """
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0   # __init__ happens-before publication

            def bump(self):
                with self._lock:
                    self.count += 1

            def _bump_locked(self):
                self.count += 1  # documented: caller holds the lock

            def explicit(self):
                self._lock.acquire()
                self.count += 1
                self._lock.release()

        class NoLock:
            def __init__(self):
                self.count = 0

            def bump(self):
                self.count += 1  # no lock owned: not this rule's claim
    """, rules=["unlocked-shared-write"])
    assert res.findings == []


def test_unlocked_write_suppressed(tmp_path):
    res = lint_src(tmp_path, """
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                self.count += 1  # gan4j-lint: disable=unlocked-shared-write — single-threaded init phase
    """, rules=["unlocked-shared-write"])
    assert res.findings == [] and len(res.suppressed) == 1


# -- swallowed-exception ------------------------------------------------------


def test_swallowed_exception_fires(tmp_path):
    res = lint_src(tmp_path, """
        def silent():
            try:
                return 1
            except Exception:
                pass

        def bare():
            try:
                return 1
            except:
                return None
    """, rules=["swallowed-exception"])
    assert rule_names(res) == ["swallowed-exception"] * 2
    assert "bare except" in res.findings[1].message


def test_swallowed_exception_clean(tmp_path):
    res = lint_src(tmp_path, """
        import logging
        import queue

        def logged(q):
            try:
                return q.get_nowait()
            except Exception as e:
                logging.warning("drain failed: %r", e)
                return None

        def control_flow(q):
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass  # draining a queue: Empty IS the loop exit

        def reraising():
            try:
                return 1
            except:
                raise
    """, rules=["swallowed-exception"])
    assert res.findings == []


def test_swallowed_exception_suppressed(tmp_path):
    res = lint_src(tmp_path, """
        def best_effort(path):
            try:
                import os
                os.unlink(path)
            except OSError:  # gan4j-lint: disable=swallowed-exception — cleanup of a maybe-absent temp file
                pass
    """, rules=["swallowed-exception"])
    assert res.findings == [] and len(res.suppressed) == 1


# -- engine mechanics ---------------------------------------------------------


def test_disable_all_suppression(tmp_path):
    res = lint_src(tmp_path, """
        import jax

        def f(key):
            a = jax.random.uniform(key, (3,))
            # gan4j-lint: disable=all — fixture
            b = jax.random.normal(key, (3,))
            return a + b
    """)
    assert res.findings == [] and len(res.suppressed) == 1


def test_unknown_rule_raises(tmp_path):
    with pytest.raises(ValueError, match="unknown rule"):
        lint_src(tmp_path, "x = 1\n", rules=["no-such-rule"])


def test_parse_error_reported(tmp_path):
    res = lint_src(tmp_path, "def broken(:\n")
    assert res.findings == [] and len(res.errors) == 1
    assert res.errors[0].rule == "parse-error"
    assert not res.ok  # unparseable code must fail the gate


def test_rule_catalogue_complete():
    assert set(all_rules()) == {
        "prng-key-reuse", "tracer-side-effect", "host-sync-in-hot-path",
        "recompile-hazard", "unlocked-shared-write",
        "swallowed-exception",
        # the gan4j-race set (PR 9; tests/test_race.py is their spec)
        "lock-order-cycle", "lock-held-blocking-call", "thread-hygiene"}


# -- baseline -----------------------------------------------------------------


BASELINE_SRC = """
    def one():
        try:
            return 1
        except Exception:
            pass

    def two():
        try:
            return 2
        except Exception:
            pass
"""


def test_baseline_round_trip(tmp_path):
    res = lint_src(tmp_path, BASELINE_SRC)
    assert len(res.findings) == 2
    bl = tmp_path / "baseline.json"
    n = baseline_mod.write(str(bl), res.findings)
    assert n == 2
    res2 = lint_src(tmp_path,
                    BASELINE_SRC,
                    baseline_fingerprints=baseline_mod.load(str(bl)))
    assert res2.findings == [] and len(res2.baselined) == 2
    assert res2.ok


def test_baseline_survives_line_shift_catches_new(tmp_path):
    res = lint_src(tmp_path, BASELINE_SRC)
    bl = tmp_path / "baseline.json"
    baseline_mod.write(str(bl), res.findings)
    # shift everything down (a comment block above) and ADD a new
    # violation: the old two stay baselined, the new one is active
    shifted = "# pushed\n# down\n# by comments\n" + textwrap.dedent(
        BASELINE_SRC) + textwrap.dedent("""
        def three():
            try:
                return 3
            except ValueError:
                pass
    """)
    (tmp_path / "snippet.py").write_text(shifted)
    res2 = lint_paths([str(tmp_path / "snippet.py")],
                      baseline_fingerprints=baseline_mod.load(str(bl)))
    assert len(res2.baselined) == 2
    assert len(res2.findings) == 1
    assert "ValueError" in res2.findings[0].snippet


def test_baseline_version_mismatch(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"version": 99, "fingerprints": {}}))
    with pytest.raises(ValueError, match="version"):
        baseline_mod.load(str(bl))


# -- CLI contract -------------------------------------------------------------


CLEAN_SRC = "def fine():\n    return 1\n"
DIRTY_SRC = "def bad():\n    try:\n        return 1\n" \
            "    except Exception:\n        pass\n"


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text(CLEAN_SRC)
    dirty = tmp_path / "dirty.py"
    dirty.write_text(DIRTY_SRC)
    assert cli.main([str(clean)]) == 0
    assert cli.main([str(dirty)]) == 1
    assert cli.main([str(dirty), "--rules", "bogus"]) == 2


def test_cli_json_report(tmp_path, capsys):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(DIRTY_SRC)
    out_file = tmp_path / "report.json"
    assert cli.main([str(dirty), "--format", "json",
                     "--output", str(out_file)]) == 1
    doc = json.loads(out_file.read_text())
    assert doc["summary"]["findings"] == 1 and not doc["summary"]["ok"]
    assert doc["findings"][0]["rule"] == "swallowed-exception"


def test_cli_write_baseline_then_gate(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(DIRTY_SRC)
    bl = tmp_path / "bl.json"
    assert cli.main([str(dirty), "--baseline", str(bl),
                     "--write-baseline"]) == 0
    assert cli.main([str(dirty), "--baseline", str(bl)]) == 0
    # a NEW violation is still a gate failure
    dirty.write_text(DIRTY_SRC + "\n\ndef worse():\n    try:\n"
                     "        return 2\n    except:\n        pass\n")
    assert cli.main([str(dirty), "--baseline", str(bl)]) == 1


def test_cli_list_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    registry = all_rules()
    for rule, cls in registry.items():
        if cls.scope == "file":
            assert rule in out
    # the package-scope concurrency rules are gan4j-race's catalogue
    # (race_cli), not gan4j-lint's
    assert "lock-order-cycle" not in out


def test_cli_refuses_vacuous_pass(tmp_path, capsys):
    """A gate that lints nothing must not answer green: nonexistent
    paths and .py-free directories are usage errors (exit 2), not
    passes."""
    assert cli.main([str(tmp_path / "no_such_dir")]) == 2
    assert "no such path" in capsys.readouterr().err
    empty = tmp_path / "empty"
    empty.mkdir()
    (empty / "notes.txt").write_text("not python")
    assert cli.main([str(empty)]) == 2
    assert "no .py files" in capsys.readouterr().err


# -- --changed mode (PR 7 satellite: the pre-commit fast path) ---------------


def _git_repo(tmp_path):
    import subprocess

    def git(*cmd):
        subprocess.run(["git", "-C", str(tmp_path), *cmd], check=True,
                       capture_output=True,
                       env={"PATH": "/usr/bin:/bin:/usr/local/bin",
                            "GIT_AUTHOR_NAME": "t",
                            "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t",
                            "HOME": str(tmp_path)})

    git("init", "-q")
    (tmp_path / "tracked.py").write_text(CLEAN_SRC)
    git("add", "tracked.py")
    git("commit", "-qm", "seed")
    return git


def test_changed_empty_diff_is_clean_pass(tmp_path, capsys):
    _git_repo(tmp_path)
    assert cli.main([str(tmp_path), "--changed", "HEAD"]) == 0
    assert "no changed .py files" in capsys.readouterr().out


def test_changed_lints_tracked_modification(tmp_path):
    _git_repo(tmp_path)
    (tmp_path / "tracked.py").write_text(DIRTY_SRC)
    assert cli.main([str(tmp_path), "--changed", "HEAD"]) == 1


def test_changed_includes_untracked(tmp_path):
    _git_repo(tmp_path)
    (tmp_path / "fresh.py").write_text(DIRTY_SRC)
    assert cli.main([str(tmp_path), "--changed", "HEAD"]) == 1


def test_changed_skips_unchanged_dirty_file(tmp_path):
    """A violation already committed at the ref is OUT of scope — the
    mode gates the diff, not the tree."""
    git = _git_repo(tmp_path)
    (tmp_path / "old_dirt.py").write_text(DIRTY_SRC)
    git("add", "old_dirt.py")
    git("commit", "-qm", "dirt")
    (tmp_path / "clean_new.py").write_text(CLEAN_SRC)
    assert cli.main([str(tmp_path), "--changed", "HEAD"]) == 0


def test_changed_finds_untracked_under_subdir_anchor(tmp_path):
    """The default invocation anchors at a SUBDIRECTORY of the repo
    (the installed package dir): untracked files must still be found —
    ls-files prints cwd-relative paths, which must be joined from the
    repo root like the diff's."""
    _git_repo(tmp_path)
    sub = tmp_path / "pkg"
    sub.mkdir()
    (sub / "fresh.py").write_text(DIRTY_SRC)
    assert cli.main([str(sub), "--changed", "HEAD"]) == 1


def test_changed_usage_errors(tmp_path, capsys):
    # outside a git repo -> usage error, not a pass
    (tmp_path / "a.py").write_text(CLEAN_SRC)
    assert cli.main([str(tmp_path), "--changed", "HEAD"]) == 2
    assert "not inside a git" in capsys.readouterr().err
    # unknown ref -> usage error
    _git_repo(tmp_path)
    assert cli.main([str(tmp_path), "--changed", "no-such-ref"]) == 2
    # --write-baseline over a partial subset is refused
    with pytest.raises(SystemExit):
        cli.main([str(tmp_path), "--changed", "HEAD",
                  "--baseline", str(tmp_path / "bl.json"),
                  "--write-baseline"])


# -- --warn-unused-suppressions (the stale-suppression audit) ----------------


def test_stale_suppression_flagged(tmp_path):
    res = lint_src(tmp_path, """
        def fine():
            # gan4j-lint: disable=swallowed-exception — long gone
            return 1
    """, audit_suppressions=True)
    assert rule_names(res) == ["unused-suppression"]
    assert "never fired" in res.findings[0].message


def test_used_suppression_not_flagged(tmp_path):
    res = lint_src(tmp_path, """
        def risky():
            try:
                return 1
            except Exception:  # gan4j-lint: disable=swallowed-exception — fixture
                pass
    """, audit_suppressions=True)
    assert res.findings == [] and len(res.suppressed) == 1


def test_stale_disable_all_and_unknown_rule_flagged(tmp_path):
    res = lint_src(tmp_path, """
        x = 1  # gan4j-lint: disable=all — nothing here
        y = 2  # gan4j-lint: disable=not-a-rule — renamed away
    """, audit_suppressions=True)
    msgs = sorted(f.message for f in res.findings)
    assert len(msgs) == 2
    assert "'disable=all' silenced nothing" in msgs[0]
    assert "unknown rule" in msgs[1]


def test_explicit_escape_hatch_silences_audit(tmp_path):
    """Only a justified disable=unused-suppression silences an audit
    finding — the audited directive's own 'all' must NOT (a stale
    disable=all hiding its own staleness is the rot itself)."""
    res = lint_src(tmp_path, """
        # gan4j-lint: disable=unused-suppression — kept for doc parity
        x = 1  # gan4j-lint: disable=swallowed-exception — long gone
    """, audit_suppressions=True)
    assert res.findings == [] and len(res.suppressed) == 1


def test_unselected_rule_suppression_not_audited(tmp_path):
    """Only a run that actually executed the rule can call its
    suppression stale."""
    res = lint_src(tmp_path, """
        def fine():
            # gan4j-lint: disable=swallowed-exception — unknowable here
            return 1
    """, rules=["prng-key-reuse"], audit_suppressions=True)
    assert res.findings == []
    # disable=all is equally unknowable under a partial rule set: the
    # finding it silences may belong to a rule that did not run
    res = lint_src(tmp_path, """
        def risky():
            try:
                return 1
            except Exception:  # gan4j-lint: disable=all — fixture
                pass
    """, rules=["prng-key-reuse"], audit_suppressions=True)
    assert res.findings == []


def test_docstring_directive_neither_suppresses_nor_audits(tmp_path):
    """A docstring documenting the syntax is not a directive: it must
    not silence the finding below it, and the audit must not call it
    stale."""
    res = lint_src(tmp_path, '''
        def documented():
            """Use # gan4j-lint: disable=swallowed-exception — why."""
            try:
                return 1
            except Exception:
                pass
    ''', audit_suppressions=True)
    assert rule_names(res) == ["swallowed-exception"]


def test_audit_rides_the_cli_flag(tmp_path, capsys):
    stale = tmp_path / "stale.py"
    stale.write_text("# gan4j-lint: disable=swallowed-exception — x\n"
                     "y = 1\n")
    assert cli.main([str(stale)]) == 0  # off by default
    assert cli.main([str(stale), "--warn-unused-suppressions"]) == 1
    assert "unused-suppression" in capsys.readouterr().out


# -- every rule trips the CLI gate (the injected-violation proof) ------------


INJECTED = {
    "prng-key-reuse": """
        import jax

        def f(key):
            a = jax.random.uniform(key, (2,))
            return a + jax.random.normal(key, (2,))
    """,
    "tracer-side-effect": """
        import jax

        hits = []

        @jax.jit
        def f(x):
            hits.append(x)
            return x
    """,
    "host-sync-in-hot-path": """
        def f(step, xs):
            t = 0.0
            for x in xs:
                t += float(step(x))
            return t
    """,
    "recompile-hazard": """
        import jax

        def f(fs, x):
            for g in fs:
                x = jax.jit(g)(x)
            return x
    """,
    "unlocked-shared-write": """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                self.n += 1
    """,
    "swallowed-exception": """
        def f():
            try:
                return 1
            except Exception:
                pass
    """,
}


@pytest.mark.parametrize("rule", sorted(INJECTED))
def test_injected_violation_fails_gate(tmp_path, rule):
    lint_rules = sorted(r for r, cls in all_rules().items()
                        if cls.scope == "file")
    p = tmp_path / "scratch.py"
    p.write_text(textwrap.dedent(INJECTED[rule]))
    assert cli.main([str(p), "--rules", rule]) == 1
    assert cli.main([str(p), "--disable", rule,
                     "--rules", ",".join(lint_rules)]) in (0, 1)


# -- the zero-findings gate on THIS repo --------------------------------------


def test_repo_lints_clean():
    """The acceptance criterion: gan4j-lint over the whole installed
    package, default rules, EMPTY baseline — zero findings.  Every
    suppression in the tree carries a justification (reviewed at
    dogfooding time; see docs/STATIC_ANALYSIS.md)."""
    res = lint_package()
    assert res.ok, "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}"
        for f in res.findings + res.errors)
    assert res.files_checked > 100  # the walk actually saw the package


# -- runtime sanitizers -------------------------------------------------------


def make_jitted():
    import jax

    return jax.jit(lambda a: a * 2.0 + 1.0)


def test_recompile_sentinel_silent_on_cached_loop():
    import jax

    f = make_jitted()
    x = jax.device_put(np.ones((4,), np.float32))
    with RecompileSentinel() as s:
        f(x)                      # warmup compile
        s.arm()
        for _ in range(3):
            f(x)                  # cache hits: silence
        assert s.compiles and not s.recompiles
        s.check()                 # must not raise
        assert s.ok


def test_recompile_sentinel_catches_injected_recompile():
    import jax

    f = make_jitted()
    with RecompileSentinel() as s:
        f(jax.device_put(np.ones((4,), np.float32)))
        s.arm()
        f(jax.device_put(np.ones((5,), np.float32)))  # new shape!
        assert len(s.recompiles) == 1
        with pytest.raises(RecompileError, match="post-warmup"):
            s.check()


def test_recompile_sentinel_watch_scoping():
    """Once watch regions are in use, post-arm compiles only count on
    a thread inside one — a first-time compile of an auxiliary program
    (the trainer's eval-cadence inference) is benign, a recompile
    inside the watched hot dispatch is a violation."""
    import jax

    f = make_jitted()
    aux = jax.jit(lambda a: a - 1.0)
    with RecompileSentinel() as s:
        with s.watch():
            f(jax.device_put(np.ones((4,), np.float32)))
        s.arm()
        aux(jax.device_put(np.ones((4,), np.float32)))  # outside watch
        assert s.recompiles == [] and len(s.benign_compiles) == 1
        s.check()  # benign compiles are not violations
        with s.watch():
            f(jax.device_put(np.ones((9,), np.float32)))  # new shape!
        assert len(s.recompiles) == 1
        with pytest.raises(RecompileError):
            s.check()


def test_recompile_sentinel_metric_and_event():
    import jax

    from gan_deeplearning4j_tpu.telemetry import MetricsRegistry, events

    reg = MetricsRegistry()
    recorder = events.EventRecorder()   # ring-only
    prev = events.install(recorder)
    try:
        steps = iter([7])
        with RecompileSentinel(registry=reg,
                               step_fn=lambda: next(steps)) as s:
            f = make_jitted()
            f(jax.device_put(np.ones((4,), np.float32)))
            s.arm()
            f(jax.device_put(np.ones((6,), np.float32)))
    finally:
        events.install(prev)
    assert "gan4j_recompiles_total 1" in reg.render()
    hits = [e for e in recorder.recent()
            if e["name"] == "compile.recompile"]
    assert hits and hits[0]["step"] == 7


def test_recompile_metric_precreated_at_zero():
    from gan_deeplearning4j_tpu.telemetry import MetricsRegistry

    assert "gan4j_recompiles_total 0" in MetricsRegistry().render()


def test_transfer_guard_catches_implicit_transfer():
    import jax

    f = make_jitted()
    f(np.ones((4,), np.float32))        # compile OUTSIDE the guard
    with pytest.raises(TransferGuardError, match="implicit transfer"):
        with no_implicit_transfers():
            f(np.ones((4,), np.float32))  # implicit host->device


def test_transfer_guard_allows_device_resident_loop():
    import jax

    f = make_jitted()
    x = jax.device_put(np.ones((4,), np.float32))
    y = f(x)                            # compile outside
    with no_implicit_transfers():
        for _ in range(3):
            y = f(y)                    # pure device work
        x2 = jax.device_put(np.ones((4,), np.float32))  # explicit: ok
        y = f(x2)
    assert np.isfinite(np.asarray(y)).all()  # readback AFTER the guard


def test_transfer_guard_emits_violation_event():
    import jax

    from gan_deeplearning4j_tpu.telemetry import events

    recorder = events.EventRecorder()
    prev = events.install(recorder)
    try:
        f = make_jitted()
        f(np.ones((3,), np.float32))
        with pytest.raises(TransferGuardError):
            with no_implicit_transfers():
                f(np.ones((3,), np.float32))
    finally:
        events.install(prev)
    assert any(e["name"] == "transfer.violation"
               for e in recorder.recent())


# -- the pytest fixtures (conftest.py) ---------------------------------------


def test_recompile_sentinel_fixture(recompile_sentinel):
    import jax

    f = make_jitted()
    x = jax.device_put(np.ones((4,), np.float32))
    f(x)
    recompile_sentinel.arm()
    f(x)  # cached: the fixture's teardown check passes


def test_transfer_guard_fixture(transfer_guard):
    # NB even a Python scalar constant (x * 2.0) would be an implicit
    # host->device transfer under the guard — operands must already
    # live on device (exactly the discipline the hot loop needs)
    import jax
    import jax.numpy as jnp

    x = jax.device_put(np.ones((4,), np.float32))
    y = jnp.sum(x + x)
    assert y.shape == ()


# -- trainer + bench integration ---------------------------------------------


def test_trainer_sanitize_run(tmp_path):
    """A real (tiny, insurance) fused training run with
    config.sanitize=True: completes, keeps gan4j_recompiles_total at 0
    (zero post-warmup recompiles through compile, steady steps and
    teardown) and the transfer guard never fires on the resident hot
    loop."""
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer
    from gan_deeplearning4j_tpu.train.insurance_main import (
        InsuranceWorkload,
        default_config,
    )

    trainer = GANTrainer(InsuranceWorkload(), default_config(
        num_iterations=4, res_path=str(tmp_path), metrics=False,
        print_every=10 ** 9, save_every=10 ** 9, sanitize=True))
    result = trainer.train(log=lambda s: None)
    assert result["steps"] == 4
    assert "gan4j_recompiles_total 0" in trainer.registry.render()
    # the sentinel was torn down with the run
    assert trainer._sanitizer is None


def test_trainer_sanitize_with_eval_cadence(tmp_path):
    """The eval-cadence artifact dumps compile their own (auxiliary)
    inference programs AFTER the sentinel arms — those land outside
    the watched hot dispatches and must stay benign: a sanitized run
    with real print/save cadences still reports zero recompiles."""
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer
    from gan_deeplearning4j_tpu.train.insurance_main import (
        InsuranceWorkload,
        default_config,
    )

    trainer = GANTrainer(InsuranceWorkload(), default_config(
        num_iterations=4, res_path=str(tmp_path), metrics=False,
        print_every=2, save_every=2, sanitize=True))
    result = trainer.train(log=lambda s: None)
    assert result["steps"] == 4
    assert "gan4j_recompiles_total 0" in trainer.registry.render()


def test_bench_sanitizer_dryrun():
    from gan_deeplearning4j_tpu import bench

    prev = bench.BATCH
    bench.BATCH = 8
    try:
        out = bench.sanitizer_dryrun()
    finally:
        bench.BATCH = prev
    assert out["ok"]
    assert out["warmup_compiles"] >= 1
    assert out["post_warmup_recompiles"] == 0
    assert out["transfer_ok"]


def test_bench_lint_dryrun():
    from gan_deeplearning4j_tpu import bench

    out = bench.lint_dryrun()
    assert out["ok"] and out["findings"] == 0
    assert out["files_checked"] > 100
