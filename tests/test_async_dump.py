"""AsyncArtifactWriter: ordering, flush, error surfacing, sync fallback."""

import threading
import time

import pytest

from gan_deeplearning4j_tpu.utils.async_dump import AsyncArtifactWriter


def test_jobs_run_in_submit_order_and_flush_waits():
    done = []
    w = AsyncArtifactWriter(max_pending=2)
    for i in range(8):
        w.submit(lambda i=i: (time.sleep(0.01), done.append(i)))
    w.flush()
    assert done == list(range(8))
    w.close()


def test_worker_error_surfaces_on_main_thread():
    w = AsyncArtifactWriter()

    def boom():
        raise RuntimeError("disk full")

    w.submit(boom)
    with pytest.raises(RuntimeError, match="disk full"):
        w.flush()
    # after surfacing, the writer is usable again
    ran = []
    w.submit(lambda: ran.append(1))
    w.close()
    assert ran == [1]


def test_jobs_after_error_are_skipped_until_reraise():
    w = AsyncArtifactWriter()
    ran = []
    w.submit(lambda: (_ for _ in ()).throw(ValueError("first")))
    w.submit(lambda: ran.append("skipped"))
    with pytest.raises(ValueError, match="first"):
        w.flush()
    assert ran == []  # the job submitted after the failure did not run
    w.close()


def test_synchronous_mode_runs_inline():
    w = AsyncArtifactWriter(synchronous=True)
    tid = []
    w.submit(lambda: tid.append(threading.get_ident()))
    assert tid == [threading.get_ident()]
    w.flush()
    w.close()


def test_backpressure_bounds_pending_jobs():
    gate = threading.Event()
    w = AsyncArtifactWriter(max_pending=1)
    w.submit(gate.wait)          # occupies the worker
    w.submit(lambda: None)       # fills the queue slot
    t0 = time.perf_counter()
    blocked = threading.Thread(target=lambda: w.submit(lambda: None))
    blocked.start()
    blocked.join(timeout=0.05)
    assert blocked.is_alive()    # third submit is blocked on the full queue
    gate.set()
    blocked.join(timeout=5)
    assert not blocked.is_alive()
    w.close()
    assert time.perf_counter() - t0 < 5


def test_submit_after_close_runs_inline():
    w = AsyncArtifactWriter()
    w.close()
    ran = []
    w.submit(lambda: ran.append(1))
    assert ran == [1]
    w.close()


def test_flush_timeout_raises_on_stalled_worker():
    gate = threading.Event()
    w = AsyncArtifactWriter()
    w.submit(gate.wait)  # a hung write job
    with pytest.raises(RuntimeError, match="stalled"):
        w.flush(timeout=0.2)
    gate.set()
    w.close()


def test_device_helpers_roundtrip():
    """overlap_device_get / start_host_copy / device_fence: materialize
    arbitrary pytrees with non-array leaves passing through."""
    import jax.numpy as jnp
    import numpy as np

    from gan_deeplearning4j_tpu.utils import (
        device_fence,
        overlap_device_get,
        start_host_copy,
    )

    tree = {"a": jnp.arange(4.0), "b": [jnp.ones((2, 2)), "label"],
            "c": (3, None)}
    assert start_host_copy(tree) is tree  # passthrough, non-blocking
    out = overlap_device_get(tree)
    np.testing.assert_array_equal(out["a"], np.arange(4.0))
    assert isinstance(out["a"], np.ndarray)
    np.testing.assert_array_equal(out["b"][0], np.ones((2, 2)))
    assert out["b"][1] == "label" and out["c"] == (3, None)
    device_fence(tree)  # completes without error
