"""The driver bench contract (VERDICT r2 next-step #1): ``python bench.py``
prints ONE final JSON line and exits 0 regardless of device-link state.

Two rounds of driver captures failed with raw tracebacks (BENCH_r01: stale
step signature; BENCH_r02: wedged tunnel crashing ``jax.devices()``), so
this module pins the hardened entry's behavior with:

  * a guaranteed-dead backend (``JAX_PLATFORMS=tpu`` with no libtpu, plus
    a bogus plugin dir) -> structured skip line, rc 0, cached last-good
    payload attached;
  * a healthy CPU backend -> the real benchmark JSON (cached-baseline
    path, ``--skip-e2e`` keeps it fast).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env, args=(), timeout=600):
    env = {k: v for k, v in os.environ.items()}
    # strip the suite's virtual-device flag: the child must see a normal
    # host; also drop any inherited platform pin before applying the
    # test's own
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)


def _last_json(out):
    lines = out.stdout.strip().splitlines()
    assert lines, f"no stdout; stderr tail: {out.stderr[-500:]}"
    return json.loads(lines[-1])


def test_dead_backend_emits_structured_skip():
    """A backend that cannot initialize must yield rc 0 + a parseable
    skip line carrying the cached last-good number — never a traceback."""
    out = _run_bench({
        # 'tpu' with no libtpu and a bogus plugin dir fails initialization
        # quickly and deterministically on this CPU host
        "JAX_PLATFORMS": "tpu",
        "PJRT_DEVICE": "TPU",
        "TPU_LIBRARY_PATH": "/nonexistent/libtpu.so",
        "BENCH_PROBE_ATTEMPTS": "2",
        "BENCH_PROBE_BACKOFF": "1",
        # the dead-TPU init HANGS (it does not fail fast), so every
        # attempt burns the FULL probe timeout before the kill: this
        # knob is pure wall-clock, 2x60s of it at the old value
        "BENCH_PROBE_TIMEOUT": "15",
    })
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-500:])
    rec = _last_json(out)
    assert rec["skipped"] is True
    assert rec["value"] is None
    assert "reason" in rec and rec["reason"]
    assert "attempt 2" in rec["reason"]  # the retry loop actually ran
    # the committed last-good payload rides along, clearly labeled
    assert rec["cached"]["metric"] == "dcgan_mnist_img_per_sec"
    assert "NOT measured this round" in rec["cached_note"]


def test_healthy_cpu_backend_emits_benchmark_json():
    """With a live (CPU) backend the entry passes through the inner
    benchmark's JSON: the cached batch-200 CPU baseline, no skip."""
    out = _run_bench({"JAX_PLATFORMS": "cpu"}, args=("--skip-e2e",))
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-800:])
    rec = _last_json(out)
    assert rec.get("skipped") is not True
    assert rec["metric"] == "dcgan_mnist_img_per_sec"
    assert rec["value"] > 0
    assert rec["unit"] == "img/sec/chip"
    assert "vs_baseline" in rec
