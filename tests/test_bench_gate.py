"""The variance-aware bench regression gate (bench_gate.py): tolerance
math, lower-is-better direction, spread-vs-legacy fallbacks, and the
missing-series / missing-lastgood semantics the CI lane leans on."""

import json

from gan_deeplearning4j_tpu import bench_gate


def _capture(med, iqr, **extra_series):
    cap = {"multistep_step_ms": med,
           "spread": {"median_ms": med, "iqr_ms": iqr}}
    for name, (m, q) in extra_series.items():
        cap[name] = {"multistep_step_ms": m,
                     "spread": {"median_ms": m, "iqr_ms": q}}
    return cap


def test_self_comparison_passes():
    cap = _capture(10.0, 0.1, fast_mode=(12.0, 0.2))
    verdict = bench_gate.check_capture(cap, cap)
    assert verdict["ok"] and verdict["compared"] == 2
    assert all(not c["regressed"] for c in verdict["checks"])


def test_regression_beyond_floor_and_iqr_fails():
    old = _capture(10.0, 0.1)
    new = _capture(20.0, 0.1)  # 2x slower: way past 5% floor and 3*IQR
    verdict = bench_gate.check_capture(new, old)
    assert not verdict["ok"]
    row = verdict["checks"][0]
    assert row["regressed"] and row["slower_by_ms"] == 10.0


def test_speedup_never_regresses():
    old = _capture(10.0, 0.1)
    new = _capture(2.0, 0.1)
    assert bench_gate.check_capture(new, old)["ok"]


def test_noisy_captures_widen_the_gate():
    # 8% slower would trip the 5% floor, but both captures carry ~0.5ms
    # IQR: allowed = max(0.5, 3*(0.5+0.5)) = 3.0ms, so 0.8ms passes
    old = _capture(10.0, 0.5)
    new = _capture(10.8, 0.5)
    verdict = bench_gate.check_capture(new, old)
    assert verdict["ok"]
    assert verdict["checks"][0]["allowed_slowdown_ms"] == 3.0
    # same medians with tight IQRs: now 0.8ms IS a regression
    tight_old, tight_new = _capture(10.0, 0.0), _capture(10.8, 0.0)
    assert not bench_gate.check_capture(tight_new, tight_old)["ok"]


def test_legacy_capture_without_spread_uses_flat_step_ms():
    old = {"multistep_step_ms": 10.0}  # pre-v7 lastgood
    new = _capture(10.2, 0.0)
    verdict = bench_gate.check_capture(new, old)
    assert verdict["ok"]  # 2% < the 5% floor; IQR fallback is 0
    row = verdict["checks"][0]
    assert row["old_iqr_ms"] == 0.0 and row["old_median_ms"] == 10.0


def test_series_missing_on_either_side_is_skipped_not_failed():
    old = _capture(10.0, 0.1)
    new = _capture(10.0, 0.1, celeba=(3.0, 0.05))  # new block, no old
    verdict = bench_gate.check_capture(new, old)
    assert verdict["ok"] and "celeba" in verdict["skipped"]
    # and nothing comparable at all -> not ok (a vacuous green is a lie)
    assert not bench_gate.check_capture({}, old)["ok"]


def test_missing_lastgood_file_is_a_vacuous_pass(tmp_path):
    cap = _capture(10.0, 0.1)
    verdict = bench_gate.check_against_lastgood(
        cap, str(tmp_path / "nope.json"))
    assert verdict["ok"] and verdict["compared"] == 0
    assert "no usable lastgood" in verdict["reason"]


def test_lastgood_roundtrip_through_file(tmp_path):
    old = _capture(10.0, 0.1)
    path = tmp_path / "BENCH_LASTGOOD.json"
    path.write_text(json.dumps(old))
    assert bench_gate.check_against_lastgood(
        _capture(10.1, 0.1), str(path))["ok"]
    assert not bench_gate.check_against_lastgood(
        _capture(25.0, 0.1), str(path))["ok"]


def test_fleet_series_is_gated():
    # the fleet bench's capture block ("fleet" in SERIES): a 10x
    # regression on the fleet dispatch must go red like any other series
    old = _capture(10.0, 0.1, fleet=(4.0, 0.05))
    new = _capture(10.0, 0.1, fleet=(40.0, 0.05))
    verdict = bench_gate.check_capture(new, old)
    assert not verdict["ok"]
    assert [c["series"] for c in verdict["checks"]
            if c["regressed"]] == ["fleet"]
    assert ("fleet", 4.0, 0.05) in bench_gate.series_stats(old)


def test_per_series_lastgood_record_wins_over_legacy_shape(tmp_path):
    # the per-series-keyed record form: a fleet capture gates against
    # ITS series even though no whole-capture lastgood ever carried one
    path = tmp_path / "BENCH_LASTGOOD.json"
    path.write_text(json.dumps(
        {"series": {"fleet": {"median_ms": 4.0, "iqr_ms": 0.05}}}))
    fleet_cap = {"fleet": {"multistep_step_ms": 4.1,
                           "spread": {"median_ms": 4.1, "iqr_ms": 0.05}}}
    verdict = bench_gate.check_against_lastgood(fleet_cap, str(path))
    assert verdict["ok"] and verdict["compared"] == 1
    slow = {"fleet": {"multistep_step_ms": 40.0,
                      "spread": {"median_ms": 40.0, "iqr_ms": 0.05}}}
    assert not bench_gate.check_against_lastgood(slow, str(path))["ok"]


def test_update_lastgood_merges_per_series(tmp_path):
    path = tmp_path / "BENCH_LASTGOOD.json"
    # a legacy whole-capture record converts on first merge...
    path.write_text(json.dumps(_capture(10.0, 0.1)))
    rec = bench_gate.update_lastgood(
        str(path), {"fleet": {"multistep_step_ms": 4.0,
                              "spread": {"median_ms": 4.0,
                                         "iqr_ms": 0.05}}})
    # ...and the fleet merge did NOT clobber the multistep baseline
    assert rec["series"]["multistep"] == {"median_ms": 10.0,
                                          "iqr_ms": 0.1}
    assert rec["series"]["fleet"] == {"median_ms": 4.0, "iqr_ms": 0.05}
    # both invocations now gate individually against the one file
    assert bench_gate.check_against_lastgood(
        _capture(10.1, 0.1), str(path))["ok"]
    assert not bench_gate.check_against_lastgood(
        {"fleet": {"multistep_step_ms": 40.0,
                   "spread": {"median_ms": 40.0, "iqr_ms": 0.0}}},
        str(path))["ok"]


def test_no_overlap_is_vacuous_pass_but_empty_capture_is_not():
    # a fleet-only capture against a legacy main-only lastgood shares
    # zero series: the documented "new series must not fail
    # retroactively" case — vacuous pass with a reason, promotable via
    # update_lastgood.  A capture with no series at all stays not-ok.
    main_only = _capture(10.0, 0.1)
    fleet_only = {"fleet": {"multistep_step_ms": 4.0,
                            "spread": {"median_ms": 4.0,
                                       "iqr_ms": 0.05}}}
    verdict = bench_gate.check_capture(fleet_only, main_only)
    assert verdict["ok"] and verdict["compared"] == 0
    assert "vacuous" in verdict["reason"]
    assert not bench_gate.check_capture({}, main_only)["ok"]
