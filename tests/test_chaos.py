"""Fault-injection (chaos) suite — the fault-tolerance contract under
actual faults, not docstrings (testing/chaos.py; docs/FAULT_TOLERANCE.md).

Fast tier (runs in the CI chaos lane AND tier-1):
  * every enumerated kill point during ``save()`` leaves a restorable
    directory (first-save and re-save/swap cases, plus real SIGKILL of a
    subprocess mid-save);
  * corruption (byte flip / truncation / missing file) is caught by the
    manifest and restore falls back to the previous verified checkpoint;
  * the async checkpointer writes byte-identical artifacts and surfaces
    worker faults;
  * ``PrefetchIterator.close`` during an active/stalled worker neither
    deadlocks nor drops a worker exception;
  * recovery classification: fatal vs retryable vs preemption, plus the
    progress-aware restart budget.

Slow tier (full suite): end-to-end restart-equals-never-failed with a
crash injected MID-CHECKPOINT-WRITE, and SIGTERM-driven emergency
checkpoint + resume to the bit-identical end state.

Every test is seeded; a watchdog fixture bounds each test so an injected
deadlock fails instead of hanging the runner (CHAOS_TEST_TIMEOUT, s).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from gan_deeplearning4j_tpu.checkpoint import (
    AsyncCheckpointer,
    CheckpointCorruptError,
    NoVerifiedCheckpointError,
    TrainCheckpointer,
)
from gan_deeplearning4j_tpu.checkpoint.checkpointer import MANIFEST_NAME
from gan_deeplearning4j_tpu.testing import (
    ChaosInjector,
    InjectedCrash,
    StallingSource,
)

SEED = 666


@pytest.fixture(autouse=True)
def _watchdog():
    """Per-test deadline: an injected deadlock must FAIL the test, not
    hang the runner (the CI chaos lane sets CHAOS_TEST_TIMEOUT)."""
    limit = int(os.environ.get("CHAOS_TEST_TIMEOUT", "300"))
    if not hasattr(signal, "SIGALRM"):  # non-POSIX: rely on lane timeout
        yield
        return

    def on_alarm(signum, frame):
        raise TimeoutError(f"chaos test exceeded {limit}s watchdog")

    prev = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(limit)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, prev)


def _graph():
    from gan_deeplearning4j_tpu.models import mlpgan_insurance as M

    return M.build_discriminator()


def _extra():
    return {"note": "x", "arr": np.arange(8, dtype=np.float32)}


def _assert_restorable(directory, expect_steps):
    """A fresh checkpointer over ``directory`` (init reclaims debris)
    must restore SOME verified checkpoint, at one of ``expect_steps``."""
    ck = TrainCheckpointer(directory)
    g = _graph()
    step, extra = ck.restore({"dis": g})
    assert step in expect_steps
    assert extra["note"] == "x"
    np.testing.assert_array_equal(extra["arr"],
                                  np.arange(8, dtype=np.float32))
    # no debris left behind either way
    assert not [n for n in os.listdir(directory)
                if n.startswith((".ckpt_tmp_", ".ckpt_del_"))]
    return step


# -- kill-during-save: every enumerated point -------------------------------


def test_every_first_save_kill_point_restorable(tmp_path):
    """Checkpoint at step 2 committed, then a kill at EVERY enumerated
    write/rename point of the step-4 save: restore must always succeed
    (step 4 when the kill hit after the bytes were complete — the
    adopted-orphan path — else step 2)."""
    inj = ChaosInjector(SEED)
    base = tmp_path / "base"
    ck0 = TrainCheckpointer(str(base), keep=10)
    g = _graph()
    ck0.save(2, {"dis": g}, extra=_extra())
    events = inj.count_save_events(
        lambda: ck0.save(4, {"dis": g}, extra=_extra()))
    shutil.rmtree(str(base / "ckpt_4"))  # keep only the step-2 state
    assert len(events) >= 5  # per-file writes, manifest, swap points

    for k in range(len(events)):
        d = str(tmp_path / f"kill_{k}")
        shutil.copytree(str(base), d)
        ck = TrainCheckpointer(d, keep=10)
        with inj.kill_at_save_event(k) as kp:
            with pytest.raises(InjectedCrash):
                ck.save(4, {"dis": g}, extra=_extra())
        assert kp.fired
        step = _assert_restorable(d, {2, 4})
        if events[k] in ("post_swap",):
            assert step == 4  # the rename committed before the kill


def test_every_resave_kill_point_restorable(tmp_path):
    """Re-saving an EXISTING step exercises the rename/rename/rmtree
    swap (the availability window the old rmtree-then-rename code had):
    a kill at any point must leave step 2 restorable — from the old
    copy, the new copy, or an adopted orphan of either."""
    inj = ChaosInjector(SEED + 1)
    base = tmp_path / "base"
    ck0 = TrainCheckpointer(str(base), keep=10)
    g = _graph()
    ck0.save(2, {"dis": g}, extra=_extra())
    events = inj.count_save_events(
        lambda: ck0.save(2, {"dis": g}, extra=_extra()))
    assert "mid_swap" in events  # the swap path really ran

    for k in range(len(events)):
        d = str(tmp_path / f"kill_{k}")
        shutil.copytree(str(base), d)
        with inj.kill_at_save_event(k):
            with pytest.raises(InjectedCrash):
                TrainCheckpointer(d, keep=10).save(
                    2, {"dis": g}, extra=_extra())
        _assert_restorable(d, {2})


def test_sigkill_subprocess_mid_save_restorable(tmp_path):
    """The real thing: SIGKILL (no python frames unwound, no cleanup) at
    a seeded moment while a subprocess loops checkpoint saves.  After at
    least one committed save, the directory must always restore."""
    script = textwrap.dedent("""
        import sys

        import jax

        jax.config.update("jax_platforms", "cpu")  # as tests/conftest.py

        import numpy as np

        from gan_deeplearning4j_tpu.checkpoint import TrainCheckpointer
        from gan_deeplearning4j_tpu.models import mlpgan_insurance as M

        ck = TrainCheckpointer(sys.argv[1], keep=3)
        g = M.build_discriminator()
        extra = {"note": "x", "arr": np.arange(8, dtype=np.float32)}
        ck.save(1, {"dis": g}, extra=extra)
        print("READY", flush=True)
        step = 2
        while True:
            ck.save(step, {"dis": g}, extra=extra)
            step += 1
    """)
    inj = ChaosInjector(SEED + 2)
    for trial in range(2):
        d = str(tmp_path / f"trial_{trial}")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", script, d], stdout=subprocess.PIPE,
            text=True, env=env)
        try:
            line = proc.stdout.readline()
            assert line.strip() == "READY"
            time.sleep(inj.rng.uniform(0.0, 0.25))  # land mid-save
            proc.kill()
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        ck = TrainCheckpointer(d)
        g = _graph()
        step, extra = ck.restore({"dis": g})
        assert step >= 1
        assert extra["note"] == "x"


# -- corruption: manifest-verified fallback ---------------------------------


def test_corrupt_one_file_falls_back(tmp_path):
    """One flipped byte in any file of the newest checkpoint (manifest
    intact — only hashing can catch it): restore falls back to the
    previous verified step; an EXPLICIT request for the corrupt step
    raises instead of silently substituting."""
    for seed in range(4):  # seeded choice covers different victim files
        d = str(tmp_path / f"s{seed}")
        ck = TrainCheckpointer(d, keep=10)
        g = _graph()
        ck.save(2, {"dis": g}, extra=_extra())
        ck.save(4, {"dis": g}, extra=_extra())
        ChaosInjector(seed).corrupt_one_file(
            os.path.join(d, "ckpt_4"), exclude_manifest=True)
        assert not ck.verify(4)
        assert ck.verify(2)
        assert ck.latest_verified_step() == 2
        g2 = _graph()
        step, _ = ck.restore({"dis": g2})
        assert step == 2
        with pytest.raises(CheckpointCorruptError):
            ck.restore({"dis": _graph()}, step=4)


def test_truncated_and_missing_state_npz_fall_back(tmp_path):
    """state.npz TRUNCATED (torn write) vs MISSING (lost file): both are
    detected by verification and restore falls back — the resume edge
    cases the manifest exists for."""
    inj = ChaosInjector(SEED + 3)
    for fault in ("truncate", "missing"):
        d = str(tmp_path / fault)
        ck = TrainCheckpointer(d, keep=10)
        g = _graph()
        ck.save(2, {"dis": g}, extra=_extra())
        ck.save(4, {"dis": g}, extra=_extra())
        if fault == "truncate":
            path, _ = inj.truncate_file(os.path.join(d, "ckpt_4"))
        else:
            inj.delete_file(os.path.join(d, "ckpt_4"), "state.npz")
        assert not ck.verify(4)
        g2 = _graph()
        step, extra = ck.restore({"dis": g2})
        assert step == 2
        np.testing.assert_array_equal(extra["arr"],
                                      np.arange(8, dtype=np.float32))


def test_resave_swap_kill_adopts_the_newer_copy(tmp_path):
    """Kill between the two swap renames of a re-save: BOTH copies of
    the step survive as orphans; init must adopt the NEWER (.ckpt_tmp_)
    bytes, not the superseded .ckpt_del_ copy — if the re-save changed
    content, resuming from the stale copy would silently undo it."""
    inj = ChaosInjector(SEED + 6)
    d = str(tmp_path)
    ck = TrainCheckpointer(d, keep=10)
    g = _graph()
    ck.save(2, {"dis": g}, extra={"note": "old", "arr": np.zeros(2)})
    events = inj.count_save_events(
        lambda: ck.save(2, {"dis": g},
                        extra={"note": "old", "arr": np.zeros(2)}))
    k = events.index("mid_swap")
    with inj.kill_at_save_event(k):
        with pytest.raises(InjectedCrash):
            ck.save(2, {"dis": g}, extra={"note": "new",
                                          "arr": np.ones(2)})
    assert not os.path.exists(os.path.join(d, "ckpt_2"))  # both orphaned
    step, extra = TrainCheckpointer(d).restore({"dis": _graph()})
    assert step == 2
    assert extra["note"] == "new"  # the fully-fsynced replacement won


def test_restore_missing_explicit_step_is_not_found_not_corrupt(tmp_path):
    ck = TrainCheckpointer(str(tmp_path), keep=10)
    ck.save(2, {"dis": _graph()}, extra=_extra())
    with pytest.raises(FileNotFoundError):
        ck.restore({"dis": _graph()}, step=5)  # absent, NOT "corrupt"


def test_legacy_pre_manifest_checkpoint_still_restores(tmp_path):
    """A checkpoint written BEFORE the manifest format (no MANIFEST.json
    but a committed state.json) is unverifiable, not corrupt: restore
    accepts it — an upgrade must not silently discard a long run — while
    a verified checkpoint, when present, still wins."""
    d = str(tmp_path)
    ck = TrainCheckpointer(d, keep=10)
    g = _graph()
    ck.save(4, {"dis": g}, extra=_extra())
    os.remove(os.path.join(d, "ckpt_4", MANIFEST_NAME))  # legacy layout
    assert not ck.verify(4)
    step, extra = ck.restore({"dis": _graph()})  # fallback tier
    assert step == 4 and extra["note"] == "x"
    step, _ = ck.restore({"dis": _graph()}, step=4)  # explicit request
    assert step == 4
    # a verified checkpoint outranks a NEWER legacy one
    ck.save(2, {"dis": g}, extra=_extra())
    step, _ = ck.restore({"dis": _graph()})
    assert step == 2


def test_all_checkpoints_corrupt_raises_no_verified(tmp_path):
    d = str(tmp_path)
    ck = TrainCheckpointer(d, keep=10)
    g = _graph()
    ck.save(2, {"dis": g}, extra=_extra())
    ChaosInjector(SEED).corrupt_one_file(os.path.join(d, "ckpt_2"),
                                         exclude_manifest=True)
    with pytest.raises(NoVerifiedCheckpointError):
        ck.restore({"dis": _graph()})


def test_resume_falls_back_to_step_zero_on_torn_only_checkpoint(tmp_path):
    """Trainer-level: --resume with the ONLY checkpoint torn must start
    from step 0 (deterministic replay), not crash the restart."""
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer
    from gan_deeplearning4j_tpu.train.insurance_main import (
        InsuranceWorkload,
        default_config,
    )

    d = str(tmp_path)
    t = GANTrainer(InsuranceWorkload(), default_config(
        num_iterations=2, res_path=d, checkpoint_every=2, metrics=False))
    ckdir = os.path.join(d, "checkpoints")
    t.checkpointer.save(2, t._graphs(), extra=t._checkpoint_extra())
    ChaosInjector(SEED).corrupt_one_file(
        os.path.join(ckdir, "ckpt_2"), exclude_manifest=True)
    t2 = GANTrainer(InsuranceWorkload(), default_config(
        num_iterations=2, res_path=d, checkpoint_every=2, resume=True,
        metrics=False))
    t2._maybe_resume(iter_train=None)  # must not touch the iterator
    assert t2.batch_counter == 0


# -- async checkpointer ------------------------------------------------------


def test_async_sync_saves_byte_identical(tmp_path):
    """The async path commits EXACTLY the bytes the sync path commits —
    same manifest (sizes + SHA-256) for the same state."""
    g = _graph()
    sync = TrainCheckpointer(str(tmp_path / "sync"), keep=5)
    sync.save(3, {"dis": g}, extra=_extra())
    with AsyncCheckpointer(
            TrainCheckpointer(str(tmp_path / "async"), keep=5)) as ack:
        ack.save(3, {"dis": g}, extra=_extra())
        ack.wait()

    def manifest(root):
        with open(os.path.join(str(tmp_path), root, "ckpt_3",
                               MANIFEST_NAME)) as f:
            return json.load(f)["files"]

    assert manifest("sync") == manifest("async")


def test_async_checkpointer_surfaces_worker_fault(tmp_path):
    """A fault during background serialization re-raises on the training
    thread at the next barrier — never a silent gap in the history."""
    inj = ChaosInjector(SEED + 4)
    g = _graph()
    ack = AsyncCheckpointer(TrainCheckpointer(str(tmp_path), keep=5))
    with inj.kill_at_save_event(1):
        ack.save(2, {"dis": g}, extra=_extra())
        with pytest.raises(InjectedCrash):
            ack.wait()
    # the wrapper stays usable; the NEXT save commits normally
    ack.save(4, {"dis": g}, extra=_extra())
    ack.close()
    assert TrainCheckpointer(str(tmp_path)).latest_verified_step() == 4


def test_async_restore_sees_queued_save(tmp_path):
    """Reads barrier on the writer: latest_step()/restore() immediately
    after save() observe the queued checkpoint, not a torn directory."""
    g = _graph()
    with AsyncCheckpointer(TrainCheckpointer(str(tmp_path))) as ack:
        ack.save(7, {"dis": g}, extra=_extra())
        assert ack.latest_step() == 7
        assert ack.verify(7)
        step, _ = ack.restore({"dis": _graph()})
        assert step == 7


# -- prefetch close vs active worker (satellite: data/prefetch.py) ----------


class _ListSource:
    """Minimal has_next/next/reset DataSet iterator over arrays."""

    def __init__(self, n=8, rows=4, fail_at=None):
        self.n = n
        self.rows = rows
        self.fail_at = fail_at
        self.i = 0

    def has_next(self):
        return self.i < self.n

    def reset(self):
        self.i = 0

    def next(self):
        from gan_deeplearning4j_tpu.data.csv import DataSet

        if self.fail_at is not None and self.i == self.fail_at:
            raise RuntimeError("injected decode failure")
        self.i += 1
        return DataSet(np.full((self.rows, 3), self.i, np.float32),
                       np.zeros((self.rows, 1), np.float32))


def test_prefetch_close_during_stalled_worker_no_deadlock(tmp_path):
    """close() while the worker is wedged INSIDE source.next() (hung
    storage) must return promptly — the join gives up, the daemon worker
    dies with the process."""
    from gan_deeplearning4j_tpu.data.prefetch import PrefetchIterator

    # stall at the SECOND next() call: the first batch fills the depth-1
    # queue, so the worker is inside source.next() when we close
    src = StallingSource(_ListSource(n=8), stall_at=1)
    it = PrefetchIterator(src, prefetch_depth=1)
    assert src.stalled.wait(timeout=10)  # worker is stuck in next()
    t0 = time.perf_counter()
    it.close(timeout=0.5)
    assert time.perf_counter() - t0 < 5.0  # no deadlock, bounded
    src.release()  # let the daemon thread exit cleanly


def test_prefetch_close_while_worker_putting_no_deadlock(tmp_path):
    """close() racing a worker blocked in put() on a FULL queue (the
    consumer never read): the stop flag breaks the worker's put loop and
    close returns; repeated for many seeds to shake the race."""
    from gan_deeplearning4j_tpu.data.prefetch import PrefetchIterator

    for trial in range(20):
        it = PrefetchIterator(_ListSource(n=64), prefetch_depth=1)
        time.sleep(0.001 * (trial % 3))  # vary the interleaving
        t0 = time.perf_counter()
        it.close(timeout=2.0)
        assert time.perf_counter() - t0 < 5.0
        assert not it._thread.is_alive()


def test_prefetch_close_never_drops_worker_exception(tmp_path):
    """A decode error raised by the worker survives close()'s queue
    drain: preserved on ``.error`` (and raised by a late __next__), even
    when the consumer never read a single item."""
    from gan_deeplearning4j_tpu.data.prefetch import PrefetchIterator

    src = _ListSource(n=8, fail_at=1)
    it = PrefetchIterator(src, prefetch_depth=1)
    it._thread.join(timeout=10)  # worker died on the injected failure
    it.close()
    assert isinstance(it.error, RuntimeError)

    # and the consumer-facing path still raises it after close
    src = _ListSource(n=8, fail_at=0)
    it = PrefetchIterator(src, prefetch_depth=1)
    it._thread.join(timeout=10)
    it.close()
    with pytest.raises(RuntimeError, match="injected decode failure"):
        while True:
            next(it)


# -- recovery classification + budget ---------------------------------------


class _FakeTrainer:
    def __init__(self, exc, step):
        self._exc = exc
        self.batch_counter = step

    def train(self, log=print):
        if self._exc is None:
            return {"steps": self.batch_counter}
        raise self._exc


def test_recovery_fatal_errors_not_retried():
    from gan_deeplearning4j_tpu.telemetry import NanAlarmError
    from gan_deeplearning4j_tpu.train.gan_trainer import train_with_recovery
    from gan_deeplearning4j_tpu.train.preemption import PreemptionError

    for exc in (ValueError("structure mismatch"),
                TypeError("bad config"),
                NanAlarmError("nan at step 3"),
                CheckpointCorruptError("ckpt_4 torn"),
                PreemptionError("preempted", step=2)):
        calls = []

        def make(resume, exc=exc):
            calls.append(resume)
            return _FakeTrainer(exc, 0)

        with pytest.raises(type(exc)):
            train_with_recovery(make, max_restarts=5,
                                log=lambda s: None, backoff_base_s=0)
        assert calls == [False]  # ONE attempt: no restart burned


def test_recovery_progress_aware_budget():
    """Failures at ADVANCING steps reset the budget (flaky-host tax per
    incident); failures at the SAME step exhaust it (crash loop)."""
    from gan_deeplearning4j_tpu.train.gan_trainer import train_with_recovery

    # 4 advancing failures with max_restarts=1: budget keeps resetting
    seq = [(RuntimeError("f"), 2), (RuntimeError("f"), 4),
           (RuntimeError("f"), 6), (RuntimeError("f"), 8), (None, 10)]
    it = iter(seq)

    def make(resume):
        exc, step = next(it)
        return _FakeTrainer(exc, step)

    res = train_with_recovery(make, max_restarts=1, log=lambda s: None,
                              backoff_base_s=0)
    assert res == {"steps": 10}

    # crash loop at the SAME step: budget exhausts at max_restarts
    attempts = []

    def make_loop(resume):
        attempts.append(resume)
        return _FakeTrainer(RuntimeError("loop"), 5)

    with pytest.raises(RuntimeError, match="loop"):
        train_with_recovery(make_loop, max_restarts=2,
                            log=lambda s: None, backoff_base_s=0)
    assert len(attempts) == 3  # initial + 2 restarts


def test_injected_crash_is_retryable(tmp_path):
    """The chaos InjectedCrash (a RuntimeError) goes through the
    RETRYABLE path — kill-during-save then restart is the exact scenario
    the recovery wrapper exists for."""
    from gan_deeplearning4j_tpu.train.gan_trainer import train_with_recovery

    seq = [(InjectedCrash("kill"), 3), (None, 8)]
    it = iter(seq)
    res = train_with_recovery(lambda resume: _FakeTrainer(*next(it)),
                              max_restarts=1, log=lambda s: None,
                              backoff_base_s=0)
    assert res == {"steps": 8}


# -- preemption guard (fast, signal plumbing only) --------------------------


def test_preemption_guard_latches_and_restores_handler():
    from gan_deeplearning4j_tpu.train.preemption import (
        PreemptionGuard,
        parse_signals,
    )

    assert parse_signals("SIGUSR1,term") == (signal.SIGUSR1,
                                             signal.SIGTERM)
    with pytest.raises(ValueError, match="unknown signal"):
        parse_signals("SIGBOGUS")
    with pytest.raises(ValueError, match="uncatchable"):
        parse_signals("SIGTERM,SIGKILL")  # rejected at config time

    prev = signal.getsignal(signal.SIGUSR1)
    with PreemptionGuard("SIGUSR1") as guard:
        assert not guard.triggered
        os.kill(os.getpid(), signal.SIGUSR1)
        for _ in range(100):  # delivery is between bytecodes
            if guard.triggered:
                break
            time.sleep(0.01)
        assert guard.triggered
        assert guard.signal_name() == "SIGUSR1"
    assert signal.getsignal(signal.SIGUSR1) is prev


def test_trainer_rejects_unknown_preempt_signal(tmp_path):
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer
    from gan_deeplearning4j_tpu.train.insurance_main import (
        InsuranceWorkload,
        default_config,
    )

    res = str(tmp_path / "never")
    with pytest.raises(ValueError, match="unknown signal"):
        GANTrainer(InsuranceWorkload(), default_config(
            num_iterations=2, res_path=res, preempt_signals="SIGBOGUS"))
    assert not os.path.exists(res)  # fail-fast: before any side effect


# -- NaN alarm -> emergency checkpoint handoff ------------------------------


def test_nan_snapshot_goes_through_emergency_path(tmp_path):
    """The snapshot action exits through the ONE emergency-checkpoint
    mechanism: the forensic dump is a full manifest-verified checkpoint
    (extra state included), not a second ad-hoc save format."""
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer
    from gan_deeplearning4j_tpu.train.insurance_main import (
        InsuranceWorkload,
        default_config,
    )

    d = str(tmp_path)
    t = GANTrainer(InsuranceWorkload(), default_config(
        res_path=d, n_devices=1, telemetry=True, nan_alarm="snapshot"))
    t.metrics.log_step(11, d_loss=float("nan"), nonfinite=1.0)
    t.metrics.flush(wait=True)
    t._poll_nan_alarm()  # trips -> snapshot, keeps training semantics
    snap = TrainCheckpointer(os.path.join(d, "nan_snapshot"))
    assert snap.latest_verified_step() is not None
    # full checkpoint semantics: restores into a fresh 4-graph set WITH
    # the run-state extras the old ad-hoc snapshot path dropped
    step, extra = snap.restore(InsuranceWorkload().build_graphs())
    assert step == t.batch_counter
    assert "soften_real" in extra


def test_nan_abort_is_fatal_in_recovery(tmp_path):
    """nan_alarm='abort' + recovery: NO restart is burned replaying into
    the same NaN (the satellite's classification requirement)."""
    from gan_deeplearning4j_tpu.telemetry import NanAlarmError
    from gan_deeplearning4j_tpu.train.gan_trainer import (
        GANTrainer,
        train_with_recovery,
    )
    from gan_deeplearning4j_tpu.train.insurance_main import (
        InsuranceWorkload,
        default_config,
    )

    calls = []

    def make(resume):
        calls.append(resume)
        t = GANTrainer(InsuranceWorkload(), default_config(
            res_path=str(tmp_path), n_devices=1, telemetry=True,
            nan_alarm="abort"))
        t.metrics.log_step(3, d_loss=float("nan"), nonfinite=1.0)
        t.metrics.flush(wait=True)
        orig = t.train
        t.train = lambda log=print: t._poll_nan_alarm() or orig(log=log)
        return t

    with pytest.raises(NanAlarmError):
        train_with_recovery(make, max_restarts=5, log=lambda s: None,
                            backoff_base_s=0)
    assert calls == [False]


# -- slow end-to-end chaos ---------------------------------------------------


def _insurance_cfg(res, **kw):
    from gan_deeplearning4j_tpu.train.insurance_main import default_config

    base = dict(num_iterations=8, batch_size=20, res_path=res,
                print_every=10 ** 9, save_every=8, metrics=False,
                n_devices=1, checkpoint_every=2)
    base.update(kw)
    return default_config(**base)


@pytest.mark.slow
def test_mid_checkpoint_write_crash_resume_bit_identical(tmp_path):
    """The tentpole end-to-end proof: a kill injected IN THE MIDDLE of
    writing the step-4 checkpoint (after step 2's committed), recovery
    restarts from a verified checkpoint, and the final params are
    BIT-IDENTICAL to a never-failed run."""
    from gan_deeplearning4j_tpu.train import insurance_main
    from gan_deeplearning4j_tpu.train.gan_trainer import (
        GANTrainer,
        train_with_recovery,
    )

    ref_dir = str(tmp_path / "ref")
    ref = GANTrainer(insurance_main.InsuranceWorkload(),
                     _insurance_cfg(ref_dir))
    ref.train(log=lambda s: None)

    inj = ChaosInjector(SEED + 5)
    chaos_dir = str(tmp_path / "chaos")

    def make_trainer(resume):
        cfg = _insurance_cfg(chaos_dir, resume=resume)
        return GANTrainer(insurance_main.InsuranceWorkload(), cfg)

    # crash inside the SECOND save (step 4), at a mid-write event
    with inj.kill_at_save_event(index=2, after_times=1) as kp:
        res = train_with_recovery(make_trainer, max_restarts=1,
                                  log=lambda s: None, backoff_base_s=0)
    assert kp.fired  # the kill actually happened
    assert res["steps"] == 8
    # compare via the artifacts both runs dumped at step 8 (exact bytes
    # of the predictions = bit-identical classifier params + state)
    from gan_deeplearning4j_tpu.data import read_csv_matrix

    a = read_csv_matrix(os.path.join(
        ref_dir, "insurance_test_predictions_8.csv"))
    b = read_csv_matrix(os.path.join(
        chaos_dir, "insurance_test_predictions_8.csv"))
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_sigterm_emergency_checkpoint_resumes_to_same_state(tmp_path):
    """SIGTERM mid-run: the in-flight step finishes, an emergency
    checkpoint lands BETWEEN checkpoint_every boundaries, PREEMPTED.json
    is written, and a --resume run finishes with the same final state as
    an uninterrupted run (same prediction artifact, same params)."""
    from gan_deeplearning4j_tpu.train import insurance_main
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer
    from gan_deeplearning4j_tpu.train.preemption import (
        MARKER_NAME,
        PreemptionError,
    )

    ref_dir = str(tmp_path / "ref")
    ref = GANTrainer(insurance_main.InsuranceWorkload(),
                     _insurance_cfg(ref_dir, checkpoint_every=4,
                                    steps_per_call=1))
    ref.train(log=lambda s: None)

    pre_dir = str(tmp_path / "pre")
    t = GANTrainer(insurance_main.InsuranceWorkload(),
                   _insurance_cfg(pre_dir, checkpoint_every=4,
                                  steps_per_call=1,
                                  preempt_signals="SIGTERM"))
    orig = t._step_bookkeeping

    def kick_then_book(*a, **kw):
        if t.batch_counter == 2:  # signal lands mid-step-3
            os.kill(os.getpid(), signal.SIGTERM)
        return orig(*a, **kw)

    t._step_bookkeeping = kick_then_book
    with pytest.raises(PreemptionError) as ei:
        t.train(log=lambda s: None)
    # emergency checkpoint at step 3: BETWEEN the every-4 boundaries
    assert ei.value.step == 3
    assert os.path.exists(os.path.join(pre_dir, MARKER_NAME))
    ck = TrainCheckpointer(os.path.join(pre_dir, "checkpoints"))
    assert ck.latest_verified_step() == 3

    t2 = GANTrainer(insurance_main.InsuranceWorkload(),
                    _insurance_cfg(pre_dir, checkpoint_every=4,
                                   steps_per_call=1, resume=True))
    res = t2.train(log=lambda s: None)
    assert res["steps"] == 8
    assert not os.path.exists(os.path.join(pre_dir, MARKER_NAME))
    for layer, lp in ref.dis.params.items():
        for name, v in lp.items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(t2.dis.params[layer][name]),
                err_msg=f"dis/{layer}/{name}")


@pytest.mark.slow
def test_async_checkpoint_run_resumes_identically(tmp_path):
    """--async-checkpoint end to end: a run checkpointing asynchronously
    resumes (after an injected crash) to the same final state as a
    synchronous-checkpoint never-failed run — same artifacts."""
    from gan_deeplearning4j_tpu.train import insurance_main
    from gan_deeplearning4j_tpu.train.gan_trainer import (
        GANTrainer,
        train_with_recovery,
    )

    ref_dir = str(tmp_path / "ref")
    GANTrainer(insurance_main.InsuranceWorkload(),
               _insurance_cfg(ref_dir)).train(log=lambda s: None)

    async_dir = str(tmp_path / "async")
    state = {"fails_left": 1}

    def make_trainer(resume):
        t = GANTrainer(
            insurance_main.InsuranceWorkload(),
            _insurance_cfg(async_dir, resume=resume,
                           async_checkpoint=True))
        orig_step = t._step_bookkeeping
        orig_chunk = t._chunk_bookkeeping

        def fail_if_due():
            if t.batch_counter == 4 and state["fails_left"] > 0:
                state["fails_left"] -= 1
                raise RuntimeError("injected crash after step-4 save")

        def step(*a, **kw):
            fail_if_due()
            return orig_step(*a, **kw)

        def chunk(*a, **kw):
            fail_if_due()
            return orig_chunk(*a, **kw)

        t._step_bookkeeping = step
        t._chunk_bookkeeping = chunk
        return t

    res = train_with_recovery(make_trainer, max_restarts=1,
                              log=lambda s: None, backoff_base_s=0)
    assert res["steps"] == 8
    assert state["fails_left"] == 0
    from gan_deeplearning4j_tpu.data import read_csv_matrix

    a = read_csv_matrix(os.path.join(
        ref_dir, "insurance_test_predictions_8.csv"))
    b = read_csv_matrix(os.path.join(
        async_dir, "insurance_test_predictions_8.csv"))
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_maybe_resume_fast_forward_partial_tail_epoch_boundary(tmp_path):
    """_maybe_resume fast-forward ACROSS an epoch boundary with a
    partial tail (40 rows, batch 16 -> [16, 16, skip-8]): the iterator
    position after resume equals the position the uninterrupted
    consumption pattern reaches — including from an emergency-checkpoint
    step that no cadence boundary produced."""
    from gan_deeplearning4j_tpu.data import RecordReaderDataSetIterator
    from gan_deeplearning4j_tpu.train import cv_main
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer

    d = str(tmp_path)
    kw = dict(batch_size=16, print_every=100, save_every=100,
              metrics=False, checkpoint_every=2)
    wl = cv_main.CVWorkload(n_train=40, n_test=16)
    t = GANTrainer(wl, cv_main.default_config(
        num_iterations=3, res_path=d, **kw))
    train_csv, _ = wl.ensure_data(d)
    c = t.c
    # emergency-style checkpoint at step 3 (odd: between every-2 marks)
    t.batch_counter = 3
    t._emergency_checkpoint()

    t2 = GANTrainer(cv_main.CVWorkload(n_train=40, n_test=16),
                    cv_main.default_config(num_iterations=6, res_path=d,
                                           resume=True, **kw))
    it2 = RecordReaderDataSetIterator(
        train_csv, c.batch_size, c.label_index, c.num_classes)
    t2._maybe_resume(it2)
    assert t2.batch_counter == 3

    # manual replay of the training loop's consumption for 3 steps
    ref_it = RecordReaderDataSetIterator(
        train_csv, c.batch_size, c.label_index, c.num_classes)
    steps_done = 0
    while steps_done < 3:
        if not ref_it.has_next():
            ref_it.reset()
        ds = ref_it.next()
        if ds.num_examples() < c.batch_size:
            ref_it.reset()
            continue
        steps_done += 1
        if not ref_it.has_next():
            ref_it.reset()
    # the NEXT batch both iterators yield must be identical
    np.testing.assert_array_equal(it2.next().features,
                                  ref_it.next().features)
